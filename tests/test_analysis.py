"""Static-analyzer test tier: each checker must fire on a seeded fixture
violation (with the right file:line) and stay silent on the real repo.

Fixtures are tiny source trees written to tmp_path and analyzed through
the same ``load_package``/``run_checks`` pipeline the CLI uses, so the
tests exercise path scoping and baseline handling too — not just the AST
visitors. The final tier-1 gate shells out to ``python -m
kube_throttler_tpu.analysis`` exactly the way ``make lint`` does.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import pytest

from kube_throttler_tpu.analysis import run_checks, run_repo
from kube_throttler_tpu.analysis.__main__ import main as analysis_main
from kube_throttler_tpu.analysis.core import (
    apply_baseline,
    load_baseline,
    load_package,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_tree(root, files):
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return str(root)


def findings_for(root, checks, allowlist_path=None):
    return run_checks(load_package(str(root)), checks, allowlist_path=allowlist_path)


# ------------------------------------------------------------------ guarded


class TestGuardedBy:
    def test_unguarded_write_fires_with_line(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Box:
                    GUARDED_BY = {"_items": "self._lock"}

                    def __init__(self):
                        self._lock = threading.Lock()
                        self._items = []

                    def ok(self):
                        with self._lock:
                            self._items.append(1)

                    def bad(self):
                        self._items.append(2)
                '''
            },
        )
        found = findings_for(root, ("guarded",))
        assert len(found) == 1
        f = found[0]
        assert f.checker == "guarded"
        assert f.relpath == "mod.py"
        assert f.line == 16  # the self._items read in bad()
        assert "_items" in f.message and "Box.bad" in f.message

    def test_inline_annotation_and_locked_suffix(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._n = 0  #: guarded-by: self._lock

                    def _bump_locked(self):
                        self._n += 1  # caller-holds-lock contract: no finding

                    def bad(self):
                        return self._n
                '''
            },
        )
        found = findings_for(root, ("guarded",))
        assert [f.line for f in found] == [13]
        assert "Box.bad" in found[0].message

    def test_condition_alias_satisfies_lock_guard(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Q:
                    GUARDED_BY = {"_q": "self._lock"}

                    def __init__(self):
                        self._lock = threading.Lock()
                        self._cv = threading.Condition(self._lock)
                        self._q = []

                    def put(self, x):
                        with self._cv:  # holding the condition IS holding the lock
                            self._q.append(x)
                '''
            },
        )
        assert findings_for(root, ("guarded",)) == []


# ---------------------------------------------------------------- lockorder


_CYCLE_SRC = {
    "mod.py": '''\
    import threading


    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def ab(self):
            with self._a:
                with self._b:
                    pass

        def ba(self):
            with self._b:
                with self._a:
                    pass
    '''
}


class TestLockOrder:
    def test_cycle_fires(self, tmp_path):
        found = findings_for(write_tree(tmp_path, _CYCLE_SRC), ("lockorder",))
        cycles = [f for f in found if "cycle" in f.message]
        assert len(cycles) == 1
        assert "mod.AB._a" in cycles[0].message and "mod.AB._b" in cycles[0].message
        assert cycles[0].relpath == "mod.py"

    def test_allowlist_silences_vetted_edge(self, tmp_path):
        root = write_tree(tmp_path, _CYCLE_SRC)
        allow = tmp_path / "allow.txt"
        # removing either direction breaks the 2-cycle
        allow.write_text("mod.AB._b -> mod.AB._a  # vetted: ba() only runs in tests\n")
        found = findings_for(root, ("lockorder",), allowlist_path=str(allow))
        assert [f for f in found if "cycle" in f.message] == []

    def test_nonreentrant_self_reacquire_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class S:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                '''
            },
        )
        found = findings_for(root, ("lockorder",))
        assert any("re-acquired while held" in f.message for f in found)

    def test_rlock_self_nesting_is_fine(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class R:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                '''
            },
        )
        assert findings_for(root, ("lockorder",)) == []


# ------------------------------------------------------------------- purity


class TestPurity:
    def test_host_call_in_jitted_fn(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                import time

                import jax


                @jax.jit
                def tick(x):
                    t = time.monotonic()
                    return x + t
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert found[0].line == 8
        assert "time.monotonic()" in found[0].message

    def test_host_call_reachable_through_helper(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                import random

                import jax


                def helper(x):
                    return x * random.random()


                @jax.jit
                def entry(x):
                    return helper(x)
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert "random.random()" in found[0].message
        assert found[0].line == 7

    def test_branch_on_traced_param(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                import jax


                @jax.jit
                def f(x, n):
                    if n > 3:
                        return x
                    return -x
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert found[0].line == 6
        assert "Python if on traced parameter(s) n" in found[0].message

    def test_static_argnames_and_structure_checks_exempt(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/k.py": '''\
                from functools import partial

                import jax


                @partial(jax.jit, static_argnames=("n",))
                def f(x, n, y=None):
                    if n > 3:            # static arg: fine
                        return x
                    if y is None:        # structure check: fine
                        return x
                    if x.shape[0] > 2:   # trace-time shape: fine
                        return x
                    return -x
                ''',
            },
        )
        assert findings_for(root, ("purity",)) == []

    def test_shard_map_body_checked(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "parallel/s.py": '''\
                import threading

                from somewhere import shard_map


                def build(mesh):
                    def _body(a):
                        threading.Lock()
                        return a

                    return shard_map(_body, mesh=mesh, in_specs=(), out_specs=())
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert "threading.Lock()" in found[0].message


# ----------------------------------------------------------------- registry


_REGISTRY_BASE = {
    "faults/plan.py": '''\
    KNOWN_SITES = frozenset({"transport.request", "journal.append"})
    ''',
    "metrics.py": '''\
    METRIC_NAMES = frozenset({"kube_throttler_good_total"})
    ''',
}


class TestRegistry:
    def test_unregistered_fault_site(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                **_REGISTRY_BASE,
                "mod.py": '''\
                def f(self):
                    self.faults.check("transport.request")
                    self.faults.check("transport.typo")
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert found[0].line == 3
        assert "transport.typo" in found[0].message

    def test_faultrule_pattern_must_match_some_site(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                **_REGISTRY_BASE,
                "mod.py": '''\
                from faults import FaultRule

                ok = FaultRule(site="transport.*")
                bad = FaultRule(site="watch.*")
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert found[0].line == 4
        assert "watch.*" in found[0].message

    def test_undeclared_metric_name(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                **_REGISTRY_BASE,
                "mod.py": '''\
                def setup(registry):
                    registry.gauge_vec("kube_throttler_good_total", "h", ["a"])
                    registry.counter_vec("kube_throttler_drifted_total", "h", ["a"])
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert found[0].line == 3
        assert "kube_throttler_drifted_total" in found[0].message

    def test_missing_registry_declarations_flagged(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "faults/plan.py": "SITES = None\n",
                "metrics.py": "x = 1\n",
            },
        )
        messages = "\n".join(f.message for f in findings_for(root, ("registry",)))
        assert "KNOWN_SITES" in messages
        assert "METRIC_NAMES" in messages


# ------------------------------------------------------- baseline mechanics


class TestBaseline:
    def test_waived_findings_do_not_fail(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Box:
                    GUARDED_BY = {"_items": "self._lock"}

                    def __init__(self):
                        self._lock = threading.Lock()
                        self._items = []

                    def bad(self):
                        return self._items
                '''
            },
        )
        found = findings_for(root, ("guarded",))
        assert len(found) == 1
        baseline_path = tmp_path / "baseline.txt"
        baseline_path.write_text(f"{found[0].key()}  # vetted lock-free read\n")
        baseline = load_baseline(str(baseline_path))
        new, waived, stale = apply_baseline(found, baseline)
        assert new == [] and len(waived) == 1 and stale == []

    def test_stale_waivers_reported(self, tmp_path):
        baseline = {"guarded|gone.py|read of '_x' outside its lock in G.f": "old"}
        new, waived, stale = apply_baseline([], baseline)
        assert new == [] and waived == [] and len(stale) == 1

    def test_key_is_line_stable(self, tmp_path):
        """Shifting a violation by a line must not change its baseline key."""

        body = textwrap.dedent(
            '''\
            import threading


            class Box:
                GUARDED_BY = {"_items": "self._lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def bad(self):
                    return self._items
            '''
        )

        def tree(prefix_lines):
            return {"mod.py": "# pad\n" * prefix_lines + body}

        a = findings_for(write_tree(tmp_path / "a", tree(0)), ("guarded",))
        b = findings_for(write_tree(tmp_path / "b", tree(3)), ("guarded",))
        assert a[0].line != b[0].line
        assert a[0].key() == b[0].key()


# ----------------------------------------------------------- CLI / repo gate


class TestCli:
    def test_cli_nonzero_on_seeded_violation(self, tmp_path):
        root = write_tree(tmp_path, _CYCLE_SRC)
        empty_baseline = tmp_path / "baseline.txt"
        empty_baseline.write_text("")
        rc = analysis_main(
            ["--root", root, "--baseline", str(empty_baseline), "-q"]
        )
        assert rc == 1

    def test_cli_zero_on_clean_tree(self, tmp_path):
        root = write_tree(tmp_path, {"mod.py": "x = 1\n"})
        rc = analysis_main(["--root", root, "--no-baseline", "-q"])
        assert rc == 0

    def test_repo_is_clean_inprocess(self):
        """The real package must analyze clean against the checked-in
        baseline, and every baseline waiver must still be live."""
        new, waived, stale = run_repo()
        assert new == [], "\n".join(f.render() for f in new)
        assert stale == [], f"stale baseline waivers: {stale}"

    def test_repo_gate_subprocess(self):
        """Tier-1 regression gate: exactly what `make lint` runs."""
        proc = subprocess.run(
            [sys.executable, "-m", "kube_throttler_tpu.analysis"],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------------------------------- blocking


class TestBlocking:
    def test_direct_blocking_under_lock_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading
                import time


                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def bad(self):
                        with self._lock:
                            time.sleep(0.5)
                ''',
            },
        )
        found = findings_for(root, ("blocking",))
        assert len(found) == 1
        assert found[0].line == 11
        assert "sleep()" in found[0].message
        assert "mod.Box._lock" in found[0].message

    def test_interprocedural_fsync_under_lock(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import os
                import threading


                class J:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._file = None

                    def _sync(self):
                        os.fsync(self._file.fileno())

                    def append(self, line):
                        with self._lock:
                            self._sync()
                ''',
            },
        )
        found = findings_for(root, ("blocking",))
        assert len(found) == 1
        assert "os.fsync()" in found[0].message and "append -> _sync" in found[0].message

    def test_dispatch_bridge_catches_blocking_io_under_store_lock(self, tmp_path):
        """The historical PR 8 class: a store's handler fan-out runs under
        the store lock, and a registered journal handler does file I/O —
        the blocking reaches the store lock through the observer seam."""
        root = write_tree(
            tmp_path,
            {
                "store.py": '''\
                import threading


                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._handlers = []

                    def add_event_handler(self, kind, fn):
                        self._handlers.append(fn)

                    def _dispatch_locked(self, event):
                        for h in self._handlers:
                            h(event)

                    def update_status(self, event):
                        with self._lock:
                            self._dispatch_locked(event)
                ''',
                "journal.py": '''\
                import os


                class Journal:
                    def __init__(self, store):
                        self._file = None
                        store.add_event_handler("Throttle", self._on_event)

                    def _on_event(self, event):
                        os.fsync(self._file.fileno())
                ''',
            },
        )
        found = findings_for(root, ("blocking",))
        assert any(
            "os.fsync()" in f.message and "store.Store._lock" in f.message
            for f in found
        ), [f.render() for f in found]

    def test_allowlist_and_stale_detection(self, tmp_path):
        from kube_throttler_tpu.analysis import blocking
        from kube_throttler_tpu.analysis.core import load_package

        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading
                import time


                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def bad(self):
                        with self._lock:
                            time.sleep(0.5)
                ''',
            },
        )
        allow = tmp_path / "allow.txt"
        allow.write_text(
            "mod.Box._lock -> sleep()  # vetted\n"
            "mod.Box._lock -> os.fsync()  # DEAD waiver\n"
        )
        stale = []
        found = blocking.check(
            load_package(str(root)), allowlist_path=str(allow), stale_out=stale
        )
        assert found == []
        assert stale == [("mod.Box._lock", "os.fsync()")]


# ------------------------------------------------------------------ threads


_SILENT_THREAD_SRC = {
    "mod.py": '''\
    import threading


    class Pump:
        def __init__(self):
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                self.step()

        def step(self):
            pass
    '''
}


class TestThreads:
    def test_silent_death_fires_at_target(self, tmp_path):
        found = findings_for(write_tree(tmp_path, _SILENT_THREAD_SRC), ("threads",))
        assert len(found) == 1
        f = found[0]
        assert f.relpath == "mod.py"
        assert f.line == 9  # the _loop def
        assert "no top-level exception routing" in f.message

    def test_broad_handler_passes(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Pump:
                    def __init__(self):
                        threading.Thread(target=self._loop, daemon=True).start()

                    def _loop(self):
                        while True:
                            try:
                                self.step()
                            except Exception:
                                self.note_death()

                    def step(self):
                        pass

                    def note_death(self):
                        pass
                ''',
            },
        )
        assert findings_for(root, ("threads",)) == []

    def test_waiver_comment_silences(self, tmp_path):
        src = dict(_SILENT_THREAD_SRC)
        src["mod.py"] = src["mod.py"].replace(
            "self._t = threading.Thread(target=self._loop, daemon=True)",
            "#: thread: fire-and-forget\n"
            "        self._t = threading.Thread(target=self._loop, daemon=True)",
        )
        assert findings_for(write_tree(tmp_path, src), ("threads",)) == []

    def test_spawn_under_lock_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import threading


                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def go(self):
                        with self._lock:
                            t = threading.Thread(target=run, daemon=True)
                            t.start()


                def run():
                    try:
                        pass
                    finally:
                        pass
                ''',
            },
        )
        found = findings_for(root, ("threads",))
        assert len(found) == 1
        assert "spawned while holding mod.Box._lock" in found[0].message

    def test_unbounded_shutdown_join_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                class Box:
                    def stop(self):
                        self._t.join()

                    def other(self):
                        self._t.join()  # not a shutdown path: not flagged

                    def fmt(self, xs):
                        return ",".join(xs)  # str.join: not flagged
                ''',
            },
        )
        found = findings_for(root, ("threads",))
        assert len(found) == 1
        assert found[0].line == 3
        assert "without timeout in shutdown path Box.stop" in found[0].message


# ---------------------------------------------------------------- excsafety


class TestExcSafety:
    def test_fd_leak_on_exception_path_fires(self, tmp_path):
        """The historical FileLeaseElector class: os.open, then a fallible
        call, then ownership transfer — the fd leaks if the call raises."""
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import fcntl
                import os


                class Elector:
                    def try_take(self):
                        fd = os.open("/tmp/x", os.O_RDWR)
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        self._fd = fd
                        return True
                ''',
            },
        )
        found = findings_for(root, ("excsafety",))
        assert len(found) == 1
        assert found[0].line == 7
        assert "os.open()" in found[0].message and "fcntl.flock" in found[0].message

    def test_except_path_close_passes(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import fcntl
                import os


                class Elector:
                    def try_take(self):
                        fd = os.open("/tmp/x", os.O_RDWR)
                        try:
                            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        except BaseException:
                            os.close(fd)
                            raise
                        self._fd = fd
                        return True
                ''',
            },
        )
        assert findings_for(root, ("excsafety",)) == []

    def test_with_form_and_never_closed(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                def good(path):
                    with open(path) as f:
                        return f.read()


                def bad(path):
                    f = open(path)
                    return None
                ''',
            },
        )
        found = findings_for(root, ("excsafety",))
        assert len(found) == 1
        assert found[0].line == 7
        assert "never closed" in found[0].message

    def test_acquire_without_finally_release(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                class Box:
                    def bad(self):
                        self._lock.acquire()
                        self.work()
                        self._lock.release()

                    def good(self):
                        self._lock.acquire()
                        try:
                            self.work()
                        finally:
                            self._lock.release()
                ''',
            },
        )
        found = findings_for(root, ("excsafety",))
        assert len(found) == 1
        assert found[0].line == 3
        assert "no finally-release" in found[0].message

    def test_prepare_loop_without_compensator(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                class Gang:
                    def gang_prepare_bad(self, pods):
                        for pod in pods:
                            self.plugin.reserve(pod)

                    def gang_prepare_good(self, pods):
                        done = []
                        try:
                            for pod in pods:
                                self.plugin.reserve(pod)
                                done.append(pod)
                        except Exception:
                            for pod in done:
                                self.plugin.unreserve(pod)
                            raise
                ''',
            },
        )
        found = findings_for(root, ("excsafety",))
        assert len(found) == 1
        assert found[0].line == 4
        assert "no compensating unreserve/rollback" in found[0].message


# ----------------------------------------------------------------- protocol


class TestProtocol:
    def test_unhandled_control_type_fires_per_venue(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "engine/journal.py": '''\
                import json


                class StoreJournal:
                    def _apply(self, event):
                        etype = event["type"]
                        if etype == "EPOCH":
                            return

                    def _compact_locked(self):
                        self._file.write(json.dumps({"type": "EPOCH", "epoch": 1}))

                    def emit(self):
                        self._file.write(json.dumps({"type": "GANG", "op": "begin"}))
                ''',
                "engine/replication.py": '''\
                class StandbyReplicator:
                    def _apply_lines(self, data):
                        for event in data:
                            if event.get("type") == "EPOCH":
                                continue
                ''',
            },
        )
        found = findings_for(root, ("protocol",))
        msgs = [f.message for f in found]
        assert any("'GANG'" in m and "_apply" in m for m in msgs)
        assert any("'GANG'" in m and "_apply_lines" in m for m in msgs)
        assert any("'GANG'" in m and "_compact_locked" in m for m in msgs)
        # EPOCH is dispatched everywhere: no finding for it
        assert not any("'EPOCH'" in m for m in msgs)

    def test_ipc_mtype_without_worker_handler_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/front.py": '''\
                from .ipc import send_frame


                class Front:
                    def send(self, sock, lock):
                        send_frame(sock, lock, "evt", 0, [])
                        send_frame(sock, lock, "zap", 0, [])
                ''',
                "sharding/worker.py": '''\
                def serve(rfile, sock, lock):
                    while True:
                        mtype, rid, body = read_frame(rfile)
                        if mtype == "evt":
                            pass
                ''',
                "sharding/ipc.py": '''\
                def send_frame(sock, lock, mtype, rid, body):
                    pass


                def read_frame(rfile):
                    return None
                ''',
            },
        )
        found = findings_for(root, ("protocol",))
        assert any(
            "'zap'" in f.message and "no worker-side dispatch arm" in f.message
            for f in found
        ), [f.render() for f in found]

    def test_unfenced_durable_write_fires_and_domination_passes(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "engine/journal.py": '''\
                class StoreJournal:
                    def __init__(self):
                        self.fencing = None
                        self._file = None

                    def bad_append(self, line):
                        self._file.write(line)

                    def good_append(self, line):
                        if self.fencing is not None and self.fencing.is_stale():
                            return
                        self._writer()

                    def _writer(self):
                        self._file.write("x")
                ''',
            },
        )
        found = findings_for(root, ("protocol",))
        assert len(found) == 1
        assert found[0].line == 7
        assert "bad_append" in found[0].message
        assert "not dominated by a fencing-epoch check" in found[0].message

    def test_format_registry_coverage_and_stale_rows(self, tmp_path):
        """Planted rolling-upgrade bugs: a sent frame type, an emitted
        journal control type, and a supported snapshot version each
        missing their FORMAT_REGISTRY row, plus one stale row and one
        unknown-domain row — all five must fire."""
        root = write_tree(
            tmp_path,
            {
                "version.py": '''\
                FORMAT_REGISTRY = {
                    "frame:evt": 1,
                    "journal:EPOCH": 1,
                    "snapshot:1": 1,
                    "frame:ghost": 1,
                    "weird:row": 1,
                }
                ''',
                "sharding/ipc.py": '''\
                def send_frame(sock, lock, mtype, rid, body):
                    pass
                ''',
                "sharding/front.py": '''\
                from .ipc import send_frame


                class Front:
                    def send(self, sock, lock):
                        send_frame(sock, lock, "evt", 0, [])
                        send_frame(sock, lock, "zap", 0, [])
                ''',
                "sharding/worker.py": '''\
                def serve(rfile):
                    while True:
                        mtype = read(rfile)
                        if mtype == "evt":
                            pass
                        elif mtype == "zap":
                            pass
                ''',
                "engine/journal.py": '''\
                import json


                class StoreJournal:
                    def _apply(self, event):
                        if event["type"] == "EPOCH":
                            return
                        if event["type"] == "GANG":
                            return

                    def _compact_locked(self):
                        self._file.write(json.dumps({"type": "EPOCH", "epoch": 1}))
                        self._file.write(json.dumps({"type": "GANG", "op": "x"}))
                ''',
                "engine/snapshot.py": '''\
                SUPPORTED_SNAPSHOT_VERSIONS = (1, 2)
                ''',
            },
        )
        found = findings_for(root, ("protocol",))
        msgs = [f.message for f in found]
        assert any("no 'frame:zap' row" in m for m in msgs), msgs
        assert any("no 'journal:GANG' row" in m for m in msgs), msgs
        assert any("no 'snapshot:2' row" in m for m in msgs), msgs
        assert any("'frame:ghost'" in m and "stale" in m for m in msgs), msgs
        assert any("'weird:row'" in m and "unknown domain" in m for m in msgs), msgs
        # declared rows referenced by the code are NOT findings
        assert not any("'frame:evt'" in m for m in msgs), msgs
        assert not any("'journal:EPOCH'" in m for m in msgs), msgs
        assert not any("'snapshot:1'" in m for m in msgs), msgs

    def test_computed_format_registry_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "version.py": '''\
                _ROWS = [("frame:evt", 1)]
                FORMAT_REGISTRY = dict(_ROWS)
                ''',
            },
        )
        found = findings_for(root, ("protocol",))
        assert any(
            "pure dict literal" in f.message and f.line == 2 for f in found
        ), [f.render() for f in found]


# ------------------------------------------------------------- stale waivers


class TestStaleWaivers:
    def test_dead_baseline_waiver_fails_and_prunes(self, tmp_path):
        root = write_tree(tmp_path, {"mod.py": "x = 1\n"})
        baseline = tmp_path / "baseline.txt"
        baseline.write_text(
            "# comment survives\n"
            "guarded|gone.py|read of '_x' outside its lock in G.f  # dead\n"
        )
        rc = analysis_main(
            ["--root", str(root), "--baseline", str(baseline), "-q"]
        )
        assert rc == 1  # stale waiver is an ERROR, not a warning
        rc = analysis_main(
            ["--root", str(root), "--baseline", str(baseline), "--prune-stale", "-q"]
        )
        assert rc == 0
        text = baseline.read_text()
        assert "gone.py" not in text and "# comment survives" in text
        # pruned file is clean on the next run
        assert analysis_main(
            ["--root", str(root), "--baseline", str(baseline), "-q"]
        ) == 0

    def test_dead_blocking_allow_entry_fails_and_prunes(self, tmp_path):
        root = write_tree(tmp_path, {"mod.py": "x = 1\n"})
        baseline = tmp_path / "baseline.txt"
        baseline.write_text("")
        allow = tmp_path / "blocking_allow.txt"
        allow.write_text("mod.Box._lock -> os.fsync()  # dead waiver\n")
        args = [
            "--root", str(root), "--baseline", str(baseline),
            "--blocking-allowlist", str(allow), "-q",
        ]
        assert analysis_main(args) == 1
        assert analysis_main(args + ["--prune-stale"]) == 0
        assert "os.fsync" not in allow.read_text()
        assert analysis_main(args) == 0


# ------------------------------------------------------ purity scope (PR 10)


class TestPurityScope:
    def test_sharding_jit_entry_is_scanned(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/kernels.py": '''\
                import time

                import jax


                @jax.jit
                def shard_tick(x):
                    t = time.monotonic()
                    return x + t
                ''',
            },
        )
        found = findings_for(root, ("purity",))
        assert len(found) == 1
        assert "time.monotonic()" in found[0].message

    def test_real_repo_gang_check_entries_reachable(self):
        from kube_throttler_tpu.analysis import PACKAGE_ROOT, purity
        from kube_throttler_tpu.analysis.core import load_package

        modules = [
            m
            for m in load_package(PACKAGE_ROOT)
            if m.relpath.replace("\\\\", "/").startswith(
                ("ops/", "parallel/", "sharding/")
            )
        ]
        entries = purity._entry_points(modules)
        entry_files = {m.relpath.replace("\\\\", "/") for m, _, _, _ in entries}
        assert "ops/gang_check.py" in entry_files, sorted(entry_files)


# ------------------------------------------- registry coverage (PR 8/9 families)


class TestRegistryCoverage:
    def test_real_known_sites_cover_new_families(self):
        from kube_throttler_tpu.analysis import PACKAGE_ROOT
        from kube_throttler_tpu.analysis.core import load_package
        from kube_throttler_tpu.analysis.registry import _find_module, _literal_str_set

        modules = load_package(PACKAGE_ROOT)
        sites = _literal_str_set(_find_module(modules, "faults/plan.py"), "KNOWN_SITES")
        for expected in (
            "scenario.leader.kill", "shard.ipc.send", "shard.worker.kill",
            "ha.journal.batch", "gang.reserve.partial", "mock.lease",
        ):
            assert expected in sites
        names = _literal_str_set(_find_module(modules, "metrics.py"), "METRIC_NAMES")
        for expected in (
            "kube_throttler_shard_scatter_duration_seconds",
            "kube_throttler_scenario_slo_gate",
        ):
            assert expected in names

    @pytest.mark.parametrize(
        "bad_site",
        [
            "scenario.leader.typo",
            "shard.ipc.typo",
            "ha.journal.typo",
            "gang.reserve.typo",
            "mock.lease2",
        ],
    )
    def test_one_miss_per_family_fires(self, tmp_path, bad_site):
        root = write_tree(
            tmp_path,
            {
                "faults/plan.py": '''\
                KNOWN_SITES = frozenset({
                    "scenario.leader.kill", "shard.ipc.send", "ha.journal.batch",
                    "gang.reserve.partial", "mock.lease",
                })
                ''',
                "metrics.py": "METRIC_NAMES = frozenset({'kube_throttler_shard_up'})\n",
                "mod.py": f'''\
                def f(self):
                    self.faults.check("{bad_site}")
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert bad_site in found[0].message

    def test_shard_metric_family_miss_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "faults/plan.py": "KNOWN_SITES = frozenset({'mock.lease'})\n",
                "metrics.py": (
                    "METRIC_NAMES = frozenset({"
                    "'kube_throttler_shard_up', 'kube_throttler_scenario_slo_gate'})\n"
                ),
                "mod.py": '''\
                def setup(registry):
                    registry.gauge_vec("kube_throttler_shard_up", "h", ["a"])
                    registry.gauge_vec("kube_throttler_shard_upp", "h", ["a"])
                ''',
            },
        )
        found = findings_for(root, ("registry",))
        assert len(found) == 1
        assert "kube_throttler_shard_upp" in found[0].message


# --------------------------------------------------- gen-3: dtype (device.py)


class TestDtype:
    def _tree(self, body):
        return {
            "ops/schema.py": '''\
            INT64_MILLI_PLANES = frozenset({"thr_req", "used_req", "req", "pod_req"})
            ''',
            "ops/mod.py": body,
        }

    def test_narrowing_astype_fires_with_line(self, tmp_path):
        root = write_tree(
            tmp_path,
            self._tree(
                '''\
                import jax.numpy as jnp


                def f(state):
                    ok = state.thr_req + 1
                    return state.used_req.astype(jnp.int32)
                '''
            ),
        )
        found = findings_for(root, ("dtype",))
        assert len(found) == 1
        f = found[0]
        assert f.relpath == "ops/mod.py" and f.line == 6
        assert "used_req" in f.message and "int32" in f.message

    def test_comparison_mask_cast_is_legal(self, tmp_path):
        # (req != 0).astype(int32) is a bool mask — the Compare subtree
        # must not taint the cast (the pallas limb-split idiom)
        root = write_tree(
            tmp_path,
            self._tree(
                '''\
                import jax.numpy as jnp


                def f(pods):
                    return (pods.req != 0).astype(jnp.int32)
                '''
            ),
        )
        assert findings_for(root, ("dtype",)) == []

    def test_narrow_reduction_accumulator_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            self._tree(
                '''\
                import jax.numpy as jnp


                def f(state, m):
                    good = jnp.sum(m == 1, axis=1, dtype=jnp.int32)
                    return jnp.sum(state.thr_req, axis=1, dtype=jnp.int32)
                '''
            ),
        )
        found = findings_for(root, ("dtype",))
        assert [f.line for f in found] == [6]
        assert "thr_req" in found[0].message and "accumulator" in found[0].message

    def test_default_dtype_allocation_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            self._tree(
                '''\
                import numpy as np


                class KS:
                    def grow(self, t, r):
                        self.pod_req = np.zeros((t, r))
                        self.pod_present = np.zeros((t, r))
                        self.other = np.zeros((t, r))
                '''
            ),
        )
        found = findings_for(root, ("dtype",))
        assert [f.line for f in found] == [6]
        assert "pod_req" in found[0].message

    def test_out_of_scope_modules_ignored(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/schema.py": 'INT64_MILLI_PLANES = frozenset({"req"})\n',
                "client/mod.py": '''\
                import jax.numpy as jnp


                def f(x):
                    return x.req.astype(jnp.int32)
                ''',
            },
        )
        assert findings_for(root, ("dtype",)) == []


# ------------------------------------------------ gen-3: donation (donation.py)


_DONATED_ENTRY = '''\
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def update_planes(st, delta):
    return st + delta
'''


class TestDonation:
    def test_read_after_donate_fires_with_line(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": _DONATED_ENTRY,
                "engine/mod.py": '''\
                from ..ops.kern import update_planes


                def tick(st, delta):
                    out = update_planes(st, delta)
                    stale = st.sum()
                    return out, stale
                ''',
            },
        )
        found = findings_for(root, ("donation",))
        assert len(found) == 1
        f = found[0]
        assert f.relpath == "engine/mod.py" and f.line == 6
        assert "'st'" in f.message and "donated" in f.message

    def test_rebind_clears_the_obligation(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": _DONATED_ENTRY,
                "engine/mod.py": '''\
                from ..ops.kern import update_planes


                def tick(st, delta):
                    st = update_planes(st, delta)
                    return st.sum()
                ''',
            },
        )
        assert findings_for(root, ("donation",)) == []

    def test_self_attr_read_after_donate_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": _DONATED_ENTRY,
                "engine/mod.py": '''\
                from ..ops.kern import update_planes


                class Mgr:
                    def tick(self, delta):
                        out = update_planes(self.st, delta)
                        return self.st.sum(), out

                    def tick_ok(self, delta):
                        self.st = update_planes(self.st, delta)
                        return self.st.sum()
                ''',
            },
        )
        found = findings_for(root, ("donation",))
        assert [f.line for f in found] == [7]
        assert "self.st" in found[0].message

    def test_donate_argnames_and_wrapper_assignment(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": '''\
                import jax


                def _raw(st, delta):
                    return st + delta


                update_planes = jax.jit(_raw, donate_argnums=(0,))
                ''',
                "engine/mod.py": '''\
                from ..ops.kern import update_planes


                def tick(st, delta):
                    out = update_planes(st, delta)
                    return st.shape, out
                ''',
            },
        )
        found = findings_for(root, ("donation",))
        assert len(found) == 1 and found[0].line == 6


# -------------------------------------------------- gen-3: retrace (retrace.py)


_JIT_ENTRY = '''\
from functools import partial

import jax


@partial(jax.jit, static_argnames=("num_groups",))
def kernel(x, num_groups):
    return x.sum() + num_groups
'''


class TestRetrace:
    def test_unpadded_dynamic_shape_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": _JIT_ENTRY,
                "engine/mod.py": '''\
                import numpy as np

                from ..ops.kern import kernel


                def tick(pods):
                    x = np.zeros((len(pods), 4), dtype=np.int64)
                    return kernel(x, num_groups=4)
                ''',
            },
        )
        found = findings_for(root, ("retrace",))
        assert len(found) == 1
        f = found[0]
        assert f.relpath == "engine/mod.py" and f.line == 8
        assert "'x'" in f.message and "data-dependent" in f.message

    def test_pow2_padding_is_sanctioned(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": _JIT_ENTRY,
                "engine/mod.py": '''\
                import numpy as np

                from ..ops.kern import kernel


                def _next_pow2(n):
                    return 1 << (n - 1).bit_length()


                def tick(pods):
                    bp = _next_pow2(len(pods))
                    x = np.zeros((bp, 4), dtype=np.int64)
                    return kernel(x, num_groups=4)
                ''',
            },
        )
        assert findings_for(root, ("retrace",)) == []

    def test_data_dependent_static_arg_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": _JIT_ENTRY,
                "engine/mod.py": '''\
                import numpy as np

                from ..ops.kern import kernel


                def tick(groups, x):
                    return kernel(x, num_groups=len(groups))
                ''',
            },
        )
        found = findings_for(root, ("retrace",))
        assert len(found) == 1
        assert "static arg 'num_groups'" in found[0].message
        assert found[0].line == 7

    def test_capacity_named_shape_is_sanctioned(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "ops/kern.py": _JIT_ENTRY,
                "engine/mod.py": '''\
                import numpy as np

                from ..ops.kern import kernel


                def tick(self, pods):
                    x = np.zeros((self.pcap, x_dim), dtype=np.int64)
                    return kernel(x, num_groups=4)
                ''',
            },
        )
        assert findings_for(root, ("retrace",)) == []


# ------------------------------------------------ gen-3: envguard (envguard.py)


class TestEnvGuard:
    def test_unguarded_parse_fires_with_line(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import os

                CHUNK = int(os.environ.get("KT_CHUNK", "64"))
                ''',
            },
        )
        found = findings_for(root, ("envguard",))
        assert len(found) == 1
        f = found[0]
        assert f.line == 3 and "KT_CHUNK" in f.message

    def test_guarded_parse_is_clean(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import os

                try:
                    CHUNK = int(os.environ.get("KT_CHUNK", "64"))
                except ValueError:
                    CHUNK = 64
                ''',
            },
        )
        assert findings_for(root, ("envguard",)) == []

    def test_non_kt_knobs_ignored(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import os

                PORT = int(os.environ.get("HTTP_PORT", "80"))
                ''',
            },
        )
        assert findings_for(root, ("envguard",)) == []

    def test_subscript_and_getenv_forms(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "mod.py": '''\
                import os

                A = float(os.getenv("KT_A", "1.5"))
                B = int(os.environ["KT_B"])
                ''',
            },
        )
        found = findings_for(root, ("envguard",))
        assert [f.line for f in found] == [3, 4]

    def test_real_bug_class_is_guarded_in_tree(self):
        # the ADVICE r5 _GATHER_CHUNK_ELEMS class: the repo-wide gate
        # (0 envguard findings) plus these two spot checks on the knobs
        # the class was named after
        import kube_throttler_tpu.ops.check as check

        assert check._GATHER_CHUNK_ELEMS == 64 * 1024 * 1024
        new, _, _ = run_repo(checks=("envguard",))
        assert new == []


# ------------------------------------------------------- epochs (PR 18)


class TestEpochs:
    def _run(self, root, allow=None, stale_out=None):
        return run_checks(
            load_package(str(root)),
            ("epochs",),
            epoch_allowlist_path=allow,
            stale_allow_out=stale_out,
        )

    def test_undominated_write_fires_with_line(self, tmp_path):
        """The registry is read from the fixture's own schema.py AST
        (the frozenset(...) wrapper unwraps) — ``custom_plane`` is not in
        the checker's fallback set, so a finding naming it proves the
        declared registry (not the fallback) is enforced."""
        root = write_tree(
            tmp_path,
            {
                "ops/schema.py": '''\
                VERDICT_EPOCH_PLANES = frozenset(
                    {
                        "thr_cnt",
                        "custom_plane",
                    }
                )
                ''',
                "engine/state.py": '''\
                class Arena:
                    def __init__(self):
                        self.thr_cnt = {}
                        self.custom_plane = {}
                        self.col_epoch = {}

                    def bumped(self, col):
                        self.thr_cnt[col] = 1
                        self.col_epoch[col] = 1

                    def missing(self, col):
                        self.thr_cnt[col] = 2
                        self.custom_plane[col] = 3
                ''',
            },
        )
        found = self._run(root)
        assert [(f.relpath, f.line) for f in found] == [
            ("engine/state.py", 12),
            ("engine/state.py", 13),
        ]
        assert "'thr_cnt'" in found[0].message
        assert "Arena.missing" in found[0].message
        assert "'custom_plane'" in found[1].message

    def test_interprocedural_domination_to_fixpoint(self, tmp_path):
        """A bump in EVERY caller dominates the writing helper; one
        rogue caller breaks the proof and the finding lands on the
        write site."""
        root = write_tree(
            tmp_path / "clean",
            {
                "engine/state.py": '''\
                class Arena:
                    def __init__(self):
                        self.thr_cnt = {}
                        self.col_epoch = {}

                    def _store(self, col):
                        self.thr_cnt[col] = 1

                    def commit(self, col):
                        self._store(col)
                        self.col_epoch[col] += 1
                ''',
            },
        )
        assert self._run(root) == []

        root = write_tree(
            tmp_path / "rogue",
            {
                "engine/state.py": '''\
                class Arena:
                    def __init__(self):
                        self.thr_cnt = {}
                        self.col_epoch = {}

                    def _store(self, col):
                        self.thr_cnt[col] = 1

                    def commit(self, col):
                        self._store(col)
                        self.col_epoch[col] += 1

                    def rogue(self, col):
                        self._store(col)
                ''',
            },
        )
        found = self._run(root)
        assert [(f.relpath, f.line) for f in found] == [("engine/state.py", 7)]
        assert "Arena._store" in found[0].message

    def test_inline_annotation_dominates(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "engine/state.py": '''\
                class Arena:
                    def __init__(self):
                        self.thr_cnt = {}

                    def flip(self, col):  #: epoch-bumps: batch commit bumps after the sweep
                        self.thr_cnt[col] = 1
                ''',
            },
        )
        assert self._run(root) == []

    def test_string_literal_plane_and_mutating_call(self, tmp_path):
        """The getattr-named row-encode shape: a covered plane name as a
        string literal at a call site IS the write; so is a mutating
        container call on the plane attribute."""
        root = write_tree(
            tmp_path,
            {
                "engine/state.py": '''\
                class Arena:
                    def __init__(self):
                        self.thr_cnt = {}

                    def route(self, col):
                        self._amount_into_row("thr_cnt", col)

                    def wipe(self):
                        self.thr_cnt.clear()
                ''',
            },
        )
        found = self._run(root)
        assert [(f.line, "Arena.route" in f.message) for f in found[:1]] == [(6, True)]
        assert [(f.line, "Arena.wipe" in f.message) for f in found[1:]] == [(9, True)]

    def test_local_rebind_is_not_a_plane_write(self, tmp_path):
        """A bare ``thr_cnt = {}`` binds a local (the snapshot-export
        shape); only subscript stores through the name count."""
        root = write_tree(
            tmp_path,
            {
                "engine/state.py": '''\
                class Arena:
                    def export(self):
                        thr_cnt = {}
                        thr_cnt[0] = 1
                        return thr_cnt
                ''',
            },
        )
        found = self._run(root)
        assert [f.line for f in found] == [4]  # the subscript store only

    def test_out_of_scope_ignored(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "scenarios/state.py": '''\
                class Arena:
                    def missing(self, col):
                        self.thr_cnt[col] = 2
                ''',
            },
        )
        assert self._run(root) == []

    def test_allow_roundtrip_and_stale_report(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "engine/state.py": '''\
                class Arena:
                    def missing(self, col):
                        self.thr_cnt[col] = 2
                ''',
            },
        )
        allow = tmp_path / "epoch_allow.txt"
        allow.write_text(
            "engine.state.Arena.missing -> thr_cnt  # growth zero-fill only\n"
            "engine.state.Gone.f -> thr_cnt  # dead entry\n"
        )
        stale_out = {}
        assert self._run(root, allow=str(allow), stale_out=stale_out) == []
        assert stale_out["epochs"] == [("engine.state.Gone.f", "thr_cnt")]

    def test_cli_stale_epoch_waiver_fails_and_prunes(self, tmp_path):
        root = write_tree(tmp_path, {"mod.py": "x = 1\n"})
        baseline = tmp_path / "baseline.txt"
        baseline.write_text("")
        allow = tmp_path / "epoch_allow.txt"
        allow.write_text(
            "# vetted epoch-bump exceptions\n"
            "engine.gone.Arena.f -> thr_cnt  # dead waiver\n"
        )
        args = [
            "--root", str(root), "--baseline", str(baseline),
            "--epoch-allowlist", str(allow), "-q",
        ]
        assert analysis_main(args) == 1
        assert analysis_main(args + ["--prune-stale"]) == 0
        text = allow.read_text()
        assert "Arena.f" not in text and "# vetted" in text
        assert analysis_main(args) == 0

    def test_repo_registry_and_domination_proof(self):
        """The real registry parses out of ops/schema.py (no silent
        fallback) and every covered write in the tree is dominated —
        zero findings with a zero-entry allow file is the PR's
        machine-checked coherence proof for the verdict cache."""
        from kube_throttler_tpu.analysis import PACKAGE_ROOT
        from kube_throttler_tpu.analysis.epochs import (
            _FALLBACK_PLANES,
            load_planes,
        )
        from kube_throttler_tpu.ops import schema

        planes = load_planes(load_package(PACKAGE_ROOT))
        assert planes == set(schema.VERDICT_EPOCH_PLANES)
        assert planes > set(_FALLBACK_PLANES)  # registry, not fallback
        stale_out = {}
        new, _, _ = run_repo(checks=("epochs",), stale_allow_out=stale_out)
        assert new == [], "\n".join(f.render() for f in new)
        assert stale_out["epochs"] == []


# ---------------------------------------------------- deadlines (PR 18)


class TestDeadlines:
    def _run(self, root, allow=None, stale_out=None):
        return run_checks(
            load_package(str(root)),
            ("deadlines",),
            deadline_allowlist_path=allow,
            stale_allow_out=stale_out,
        )

    def test_deadline_less_ops_fire_with_lines(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/net.py": '''\
                import socket


                class Client:
                    def dial(self, host):
                        return socket.create_connection((host, 9))

                    def pump(self, sock):
                        return sock.recv(4096)

                    def rpc(self, fut):
                        return fut.result()

                    def halt(self, thr):
                        thr.join()

                    def wait_up(self, ev):
                        ev.wait()
                ''',
            },
        )
        found = self._run(root)
        got = [(f.line, f.message.split(" on the")[0]) for f in found]
        assert got == [
            (6, "deadline-less create_connection()"),
            (9, "deadline-less .recv()"),
            (12, "deadline-less .result()"),
            (15, "deadline-less .join()"),
            (18, "deadline-less .wait()"),
        ]
        assert all("Client." in f.message for f in found)

    def test_bounded_ops_are_clean(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/net.py": '''\
                import socket


                class Client:
                    def dial(self, host):
                        return socket.create_connection((host, 9), timeout=3.0)

                    def pump(self, sock):
                        sock.settimeout(2.0)
                        return sock.recv(4096)

                    def rpc(self, fut):
                        return fut.result(timeout=1.0)

                    def halt(self, thr):
                        thr.join(2.0)

                    def wait_up(self, ev):
                        return ev.wait(0.5)

                    def render(self, xs):
                        return ",".join(xs)
                ''',
            },
        )
        assert self._run(root) == []

    def test_explicit_timeout_none_still_fires(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/net.py": '''\
                class Client:
                    def rpc(self, fut):
                        return fut.result(timeout=None)

                    def wait_up(self, ev):
                        ev.wait(None)
                ''',
            },
        )
        assert [f.line for f in self._run(root)] == [3, 6]

    def test_reachability_pulls_in_out_of_scope_helper(self, tmp_path):
        """An unbounded recv in a helper OUTSIDE the transport scope is
        flagged when a transport function reaches it — and silent when
        nothing in scope calls it."""
        helper = {
            "util/io.py": '''\
            def drain(sock):
                return sock.recv(1)
            ''',
        }
        root = write_tree(tmp_path / "unreached", dict(helper))
        assert self._run(root) == []

        reached = dict(helper)
        reached["sharding/net.py"] = '''\
        from util.io import drain


        def pump(sock):
            return drain(sock)
        '''
        root = write_tree(tmp_path / "reached", reached)
        found = self._run(root)
        assert [(f.relpath, f.line) for f in found] == [("util/io.py", 2)]
        assert "io.drain" in found[0].message

    def test_allow_roundtrip_and_stale_report(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/net.py": '''\
                class Client:
                    def rpc(self, fut):
                        return fut.result()
                ''',
            },
        )
        allow = tmp_path / "deadline_allow.txt"
        allow.write_text(
            "sharding.net.Client.rpc -> .result()  # bounded by the task deadline\n"
            "sharding.net.Gone.f -> .wait()  # dead entry\n"
        )
        stale_out = {}
        assert self._run(root, allow=str(allow), stale_out=stale_out) == []
        assert stale_out["deadlines"] == [("sharding.net.Gone.f", ".wait()")]

    def test_cli_stale_deadline_waiver_fails_and_prunes(self, tmp_path):
        root = write_tree(tmp_path, {"mod.py": "x = 1\n"})
        baseline = tmp_path / "baseline.txt"
        baseline.write_text("")
        allow = tmp_path / "deadline_allow.txt"
        allow.write_text("sharding.gone.C.f -> .recv()  # dead waiver\n")
        args = [
            "--root", str(root), "--baseline", str(baseline),
            "--deadline-allowlist", str(allow), "-q",
        ]
        assert analysis_main(args) == 1
        assert analysis_main(args + ["--prune-stale"]) == 0
        assert ".recv()" not in allow.read_text()
        assert analysis_main(args) == 0

    def test_repo_transport_is_deadline_disciplined(self):
        """Every blocking op reachable from the PR 16/17 transport
        surface carries a bound; the one vetted exception
        (AdmissionFront._scatter's .result(), bounded by the per-op RPC
        deadline inside the task) is allow-filed and still live."""
        stale_out = {}
        new, _, _ = run_repo(checks=("deadlines",), stale_allow_out=stale_out)
        assert new == [], "\n".join(f.render() for f in new)
        assert stale_out["deadlines"] == []
        from kube_throttler_tpu.analysis import DEFAULT_DEADLINE_ALLOWLIST
        from kube_throttler_tpu.analysis.core import load_pair_allowlist

        allow = load_pair_allowlist(DEFAULT_DEADLINE_ALLOWLIST)
        assert ("sharding.front.AdmissionFront._scatter", ".result()") in allow


# -------------------------------------------------------- taint (PR 18)


class TestTaint:
    def test_unauthenticated_pickle_of_recv_bytes(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/wire.py": '''\
                import pickle


                def ingest(sock):
                    data = sock.recv(65536)
                    return pickle.loads(data)
                ''',
            },
        )
        found = findings_for(root, ("taint",))
        assert [(f.relpath, f.line) for f in found] == [("sharding/wire.py", 6)]
        assert found[0].message == (
            "unauthenticated pickle.loads of network bytes "
            "(no hmac.compare_digest gate in ingest)"
        )

    def test_compare_digest_gate_satisfies(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/wire.py": '''\
                import hmac
                import pickle


                def read_frame(rfile):
                    payload = rfile.read(100)
                    tag = rfile.read(32)
                    if not hmac.compare_digest(tag, b"x" * 32):
                        raise ValueError("bad tag")
                    return pickle.loads(payload)
                ''',
            },
        )
        assert findings_for(root, ("taint",)) == []

    def test_ungated_pickle_is_a_bypass_even_untainted(self, tmp_path):
        """pickle.loads of bytes the checker can't trace to the network
        is still a new ingestion point inside the transport scope."""
        root = write_tree(
            tmp_path,
            {
                "sharding/wire.py": '''\
                import pickle


                def restore(blob):
                    return pickle.loads(blob)
                ''',
            },
        )
        found = findings_for(root, ("taint",))
        assert [f.line for f in found] == [5]
        assert "bypasses the authenticated framing layer" in found[0].message
        assert "(in restore)" in found[0].message

    def test_json_flagged_only_when_tainted(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "sharding/wire.py": '''\
                import json


                def parse(sock):
                    raw = sock.recv(4096)
                    return json.loads(raw)


                def config(text):
                    return json.loads(text)
                ''',
            },
        )
        found = findings_for(root, ("taint",))
        assert [f.line for f in found] == [6]
        assert "unauthenticated json.loads" in found[0].message

    def test_taint_flows_through_params_and_tuples(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "engine/replication.py": '''\
                import pickle


                class Applier:
                    def handle(self, rfile):
                        head, body = rfile.read(4), rfile.read(10)
                        return pickle.loads(body)
                ''',
            },
        )
        found = findings_for(root, ("taint",))
        assert [(f.relpath, f.line) for f in found] == [("engine/replication.py", 7)]
        assert "gate in Applier.handle" in found[0].message

    def test_out_of_scope_ignored(self, tmp_path):
        root = write_tree(
            tmp_path,
            {
                "plugin/wire.py": '''\
                import pickle


                def ingest(sock):
                    return pickle.loads(sock.recv(65536))
                ''',
            },
        )
        assert findings_for(root, ("taint",)) == []

    def test_repo_boundary_holds(self):
        """read_frame stays the only ingestion point: the repo's taint
        run is clean modulo the one baseline-waived local-bytes pickle
        (the reshard import path), which must still be live."""
        new, waived, _ = run_repo(checks=("taint",))
        assert new == [], "\n".join(f.render() for f in new)
        assert any(f.checker == "taint" for f in waived)
