"""Hypothesis property: the batched victim-selection kernel
(ops/victim_select.py) is equivalent to the SEQUENTIAL host oracle
(policy/victims.py ``sequential_victim_select``) on the verdict, the
selected victim SET, and the remaining-deficit vector — over generated
contribution matrices, deficit vectors, and victim caps, plus the
padding form the device wrapper dispatches (ladder-padded rows/dims must
be inert).

Guarded by importorskip like tests/test_gang_property.py; the seeded
deterministic twin (tests/test_policy.py TestKernelOracleSeeded) keeps
the equivalence tested on environments without hypothesis.
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from kube_throttler_tpu.ops.victim_select import victim_select
from kube_throttler_tpu.policy.victims import sequential_victim_select

amounts = st.sampled_from([0, 0, 1, 2, 5, 100, 333, 1000])
deficits = st.sampled_from([0, 1, 4, 250, 900, 2000])


@st.composite
def problems(draw):
    n = draw(st.integers(min_value=1, max_value=32))
    m = draw(st.integers(min_value=1, max_value=6))
    contrib = np.array(
        [[draw(amounts) for _ in range(m)] for _ in range(n)], dtype=np.int64
    )
    deficit = np.array([draw(deficits) for _ in range(m)], dtype=np.int64)
    cap = draw(st.sampled_from([0, 0, 1, 2, n]))
    return contrib, deficit, cap


@settings(max_examples=60, deadline=None)
@given(problems())
def test_kernel_equals_sequential_oracle(problem):
    contrib, deficit, cap = problem
    ok_s, sel_s, rem_s = sequential_victim_select(deficit, contrib, max_victims=cap)
    sel_k, ok_k, rem_k = victim_select(contrib, deficit, max_victims=cap)
    assert bool(np.asarray(ok_k)) == ok_s
    assert list(np.nonzero(np.asarray(sel_k))[0]) == sel_s
    assert np.asarray(rem_k).tolist() == rem_s.tolist()


@settings(max_examples=25, deadline=None)
@given(problems(), st.integers(min_value=0, max_value=8))
def test_padding_is_inert(problem, pad):
    """Zero-padded candidate rows and zero-deficit dims — the wrapper's
    ladder form — never change the verdict or the selected set."""
    contrib, deficit, cap = problem
    n, m = contrib.shape
    contrib_p = np.zeros((n + pad, m + pad), dtype=np.int64)
    contrib_p[:n, :m] = contrib
    deficit_p = np.zeros(m + pad, dtype=np.int64)
    deficit_p[:m] = deficit
    sel_a, ok_a, _ = victim_select(contrib, deficit, max_victims=cap)
    sel_b, ok_b, _ = victim_select(contrib_p, deficit_p, max_victims=cap)
    assert bool(np.asarray(ok_a)) == bool(np.asarray(ok_b))
    assert list(np.nonzero(np.asarray(sel_a))[0]) == list(
        np.nonzero(np.asarray(sel_b))[0]
    )
