"""Wire-protocol transport: reflectors (list+watch+resume+410),
remote status writer (optimistic concurrency), kubeconfig parsing, and the
end-to-end remote-mode daemon against the in-process mock apiserver
(reference integration tier: plugin.go:71-130 + test/integration/, but
deterministic — no kind cluster)."""

from __future__ import annotations

import time
from dataclasses import replace

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.client.mockserver import MockApiServer
from kube_throttler_tpu.client.transport import (
    ApiClient,
    GoneError,
    Reflector,
    RemoteSession,
    RemoteStatusWriter,
    RemoteVersions,
    RestConfig,
    parse_kubeconfig,
)
from kube_throttler_tpu.engine.store import ConflictError, Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args


def _throttle(name, labels, **threshold):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(**threshold),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(LabelSelector(match_labels=labels)),
                )
            ),
        ),
    )


def _bound(pod):
    bound = replace(pod, spec=replace(pod.spec, node_name="node-1"))
    bound.status.phase = "Running"
    return bound


@pytest.fixture()
def apiserver():
    server = MockApiServer(bookmark_interval=0.05)
    server.store.create_namespace(Namespace("default"))
    server.start()
    yield server
    server.stop()


def _wait(predicate, timeout=10.0, every=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(every)
    return predicate()


class TestKubeconfig:
    def test_parse(self, tmp_path):
        path = tmp_path / "kubeconfig"
        path.write_text(
            """
apiVersion: v1
kind: Config
current-context: target
clusters:
- name: c1
  cluster:
    server: http://127.0.0.1:8443
- name: c2
  cluster:
    server: https://other:6443
    insecure-skip-tls-verify: true
contexts:
- name: other
  context: {cluster: c2, user: u2}
- name: target
  context: {cluster: c1, user: u1}
users:
- name: u1
  user: {token: sekrit}
- name: u2
  user: {}
"""
        )
        cfg = parse_kubeconfig(str(path))
        assert cfg.server == "http://127.0.0.1:8443"
        assert cfg.token == "sekrit"
        assert cfg.verify_tls

    def test_parse_client_certs_inline_and_file(self, tmp_path):
        """client-certificate/key as file paths AND as inline *-data base64
        (the shape kubeadm/minikube kubeconfigs actually use)."""
        import base64

        cert_file = tmp_path / "crt.pem"
        cert_file.write_text("CERT")
        path = tmp_path / "kubeconfig"
        path.write_text(
            f"""
clusters:
- name: c1
  cluster:
    server: https://h:6443
    certificate-authority-data: {base64.b64encode(b"CADATA").decode()}
contexts:
- name: ctx
  context: {{cluster: c1, user: u1}}
current-context: ctx
users:
- name: u1
  user:
    client-certificate: {cert_file}
    client-key-data: {base64.b64encode(b"KEYDATA").decode()}
"""
        )
        cfg = parse_kubeconfig(str(path))
        assert cfg.cert_file == str(cert_file)
        assert open(cfg.key_file, "rb").read() == b"KEYDATA"
        assert open(cfg.ca_file, "rb").read() == b"CADATA"

    def test_parse_first_context_when_current_missing(self, tmp_path):
        path = tmp_path / "kubeconfig"
        path.write_text(
            """
clusters:
- name: c1
  cluster: {server: "http://h:1"}
contexts:
- name: only
  context: {cluster: c1}
"""
        )
        assert parse_kubeconfig(str(path)).server == "http://h:1"

    def test_in_cluster_config_from_sa_mount(self, tmp_path, monkeypatch):
        from kube_throttler_tpu.client.transport import in_cluster_config

        (tmp_path / "token").write_text("sa-token\n")
        (tmp_path / "ca.crt").write_text("CERT")
        monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
        monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "6443")
        cfg = in_cluster_config(sa_dir=str(tmp_path))
        assert cfg.server == "https://10.0.0.1:6443"
        assert cfg.token_file == str(tmp_path / "token")
        assert cfg.verify_tls

    def test_in_cluster_config_requires_env_and_token(self, tmp_path, monkeypatch):
        from kube_throttler_tpu.client.transport import in_cluster_config

        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        with pytest.raises(ValueError, match="KUBERNETES_SERVICE_HOST"):
            in_cluster_config(sa_dir=str(tmp_path))
        monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
        with pytest.raises(ValueError, match="token missing"):
            in_cluster_config(sa_dir=str(tmp_path))

    def test_token_file_rotation_picked_up(self, apiserver, tmp_path):
        apiserver.token = "tok-2"
        token_path = tmp_path / "token"
        token_path.write_text("tok-1\n")
        client = ApiClient(
            RestConfig(server=apiserver.url, token_file=str(token_path))
        )
        with pytest.raises(Exception):  # 401 with the stale token
            client.list("Pod")
        import os as _os

        token_path.write_text("tok-2\n")
        # force a new mtime even on coarse-granularity filesystems
        st = _os.stat(token_path)
        _os.utime(token_path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        items, _ = client.list("Pod")  # rotated token honored mid-process
        assert items == []


class TestListWatch:
    def test_list_returns_items_and_rv(self, apiserver):
        apiserver.store.create_throttle(_throttle("t1", {"a": "b"}, pod=5))
        client = ApiClient(RestConfig(server=apiserver.url))
        items, rv = client.list("Throttle")
        assert len(items) == 1
        assert items[0]["metadata"]["name"] == "t1"
        assert int(rv) >= int(items[0]["metadata"]["resourceVersion"])

    def test_reflector_syncs_and_follows(self, apiserver):
        local = Store()
        client = ApiClient(RestConfig(server=apiserver.url))
        refl = Reflector(client, "Throttle", local)
        refl.start()
        try:
            assert refl.wait_for_sync(5)
            apiserver.store.create_throttle(_throttle("t1", {"a": "b"}, pod=5))
            assert _wait(lambda: len(local.list_throttles()) == 1)
            # modification flows
            t1 = apiserver.store.get_throttle("default", "t1")
            apiserver.store.update_throttle(
                replace(t1, spec=replace(t1.spec, threshold=ResourceAmount.of(pod=7)))
            )
            assert _wait(
                lambda: local.list_throttles()
                and local.list_throttles()[0].spec.threshold.resource_counts == 7
            )
            # deletion flows
            apiserver.store.delete_throttle("default", "t1")
            assert _wait(lambda: len(local.list_throttles()) == 0)
        finally:
            refl.stop()

    def test_reflector_survives_stream_close_via_rv_resume(self, apiserver):
        local = Store()
        client = ApiClient(RestConfig(server=apiserver.url))
        refl = Reflector(client, "Pod", local)
        refl.start()
        try:
            assert refl.wait_for_sync(5)
            apiserver.store.create_pod(_bound(make_pod("p1")))
            assert _wait(lambda: len(local.list_pods()) == 1)
            # bounce every watch stream: server restart on the same store is
            # not possible (port changes), so force-close by shutting down
            # connections — the reflector re-watches from last_rv
            before_rv = refl.last_resource_version
            apiserver.store.create_pod(_bound(make_pod("p2")))
            assert _wait(lambda: len(local.list_pods()) == 2)
            assert int(refl.last_resource_version) > int(before_rv)
        finally:
            refl.stop()

    def test_watch_410_after_log_compaction(self):
        server = MockApiServer(log_size=4, bookmark_interval=0.05)
        server.start()
        try:
            for i in range(10):  # overflow the 4-entry log
                server.store.create_namespace(Namespace(f"ns-{i}"))
            client = ApiClient(RestConfig(server=server.url))
            with pytest.raises(GoneError):
                for _ in client.watch("Namespace", "1"):
                    pass
        finally:
            server.stop()

    def test_reflector_metrics_exported(self, apiserver):
        from kube_throttler_tpu.metrics import Registry

        registry = Registry()
        local = Store()
        session = RemoteSession(
            RestConfig(server=apiserver.url), local, metrics_registry=registry
        )
        session.start(sync_timeout=10)
        try:
            apiserver.store.create_pod(_bound(make_pod("p1")))
            assert _wait(lambda: len(local.list_pods()) == 1)
            expo = registry.exposition()
            assert 'kube_throttler_reflector_lists_total{kind="Pod"}' in expo
            assert 'kube_throttler_reflector_events_total{kind="Pod"}' in expo
        finally:
            session.stop()

    def test_reflector_recovers_from_410_by_relisting(self):
        server = MockApiServer(log_size=4, bookmark_interval=0.05)
        server.start()
        local = Store()
        client = ApiClient(RestConfig(server=server.url))
        refl = Reflector(client, "Namespace", local)
        try:
            refl.start()
            assert refl.wait_for_sync(5)
            # compact far past the reflector's resume point while it holds
            # an open stream; events still arrive live, but ALSO drive the
            # rv-too-old path by bouncing: stop and restart with a stale rv
            for i in range(10):
                server.store.create_namespace(Namespace(f"ns-{i}"))
            assert _wait(lambda: len(local.list_namespaces()) == 10)
            refl.stop()
            refl2 = Reflector(client, "Namespace", local)
            refl2.last_resource_version = "1"  # stale → watch 410s → relist
            refl2.start()
            server.store.create_namespace(Namespace("late"))
            assert _wait(lambda: local.get_namespace("late") is not None)
            refl2.stop()
        finally:
            refl.stop()
            server.stop()

    def test_bearer_token_enforced(self, apiserver):
        apiserver.token = "sekrit"
        client_bad = ApiClient(RestConfig(server=apiserver.url))
        with pytest.raises(Exception):
            client_bad.list("Pod")
        client_ok = ApiClient(RestConfig(server=apiserver.url, token="sekrit"))
        items, _ = client_ok.list("Pod")
        assert items == []


class TestPaginatedList:
    """Chunked LIST via limit/continue (client-go pager semantics; the
    reference's client layer takes ListOptions on every List —
    throttle.go:82-103)."""

    def test_list_accumulates_across_pages(self, apiserver):
        for i in range(25):
            apiserver.store.create_namespace(Namespace(f"pg-{i:02d}"))
        client = ApiClient(RestConfig(server=apiserver.url), page_size=10)
        items, rv = client.list("Namespace")
        # 25 namespaces + the fixture's "default"
        assert len(items) == 26
        assert int(rv) > 0
        assert apiserver.max_list_page_items == 10  # never one giant body
        assert apiserver.list_requests == 3

    def test_list_pages_streams_with_constant_rv(self, apiserver):
        for i in range(7):
            apiserver.store.create_namespace(Namespace(f"st-{i}"))
        client = ApiClient(RestConfig(server=apiserver.url))
        pages = list(client.list_pages("Namespace", page_size=3))
        assert [len(p) for p, _ in pages] == [3, 3, 2]
        # every page reports the RV of the snapshot the first page was cut at
        assert len({rv for _, rv in pages}) == 1

    def test_expired_continue_token_410s(self, apiserver):
        for i in range(6):
            apiserver.store.create_namespace(Namespace(f"ex-{i}"))
        client = ApiClient(RestConfig(server=apiserver.url))
        pages = client.list_pages("Namespace", page_size=2)
        next(pages)  # first page cut, token outstanding
        assert apiserver.expire_continue_tokens() == 1
        with pytest.raises(GoneError):
            next(pages)

    def test_relist_survives_token_expiry_via_full_list_fallback(self):
        server = MockApiServer()
        for i in range(10):
            server.store.create_namespace(Namespace(f"fb-{i}"))
        server.start()
        try:
            client = ApiClient(RestConfig(server=server.url), page_size=4)
            sabotaged = client.list_pages

            def expiring_pages(kind, page_size=None):
                for page in sabotaged(kind, page_size):
                    yield page
                    server.expire_continue_tokens()  # token dies between pages

            client.list_pages = expiring_pages
            local = Store()
            refl = Reflector(client, "Namespace", local)
            refl._relist()  # paged relist 410s mid-way → unpaginated fallback
            assert len(local.list_namespaces()) == 10
            assert server.max_list_page_items == 10  # the fallback full LIST
        finally:
            server.stop()

    def test_streaming_relist_bounded_pages_at_scale(self, apiserver):
        # 5k objects through a 500-item pager: the reflector's memory
        # high-water is one page + the seen-key set, and the server never
        # serializes more than one page per response
        n = 5_000
        for i in range(n):
            apiserver.store.create_namespace(Namespace(f"big-{i:05d}"))
        client = ApiClient(RestConfig(server=apiserver.url))  # default 500/page
        local = Store()
        local.create_namespace(Namespace("stale-entry"))  # must be deleted
        refl = Reflector(client, "Namespace", local)
        rv = refl._relist()
        assert int(rv) > 0
        assert len(local.list_namespaces()) == n + 1  # n big + fixture default
        assert local.get_namespace("stale-entry") is None
        assert apiserver.max_list_page_items == 500
        assert apiserver.list_requests == (n + 1) // 500 + 1


class TestStatusWriter:
    def test_put_status_and_echo(self, apiserver):
        apiserver.store.create_throttle(_throttle("t1", {"a": "b"}, pod=5))
        local = Store()
        client = ApiClient(RestConfig(server=apiserver.url))
        versions = RemoteVersions()
        refl = Reflector(client, "Throttle", local, versions=versions)
        refl.start()
        try:
            assert refl.wait_for_sync(5)
            assert _wait(lambda: len(local.list_throttles()) == 1)
            writer = RemoteStatusWriter(client, versions)
            thr = local.get_throttle("default", "t1")
            new_status = replace(thr.status, used=ResourceAmount.of(pod=3))
            writer.update_throttle_status(thr.with_status(new_status))
            # the write lands on the REMOTE store...
            remote = apiserver.store.get_throttle("default", "t1")
            assert remote.status.used.resource_counts == 3
            # ...and echoes back into the local cache via the watch
            assert _wait(
                lambda: local.get_throttle("default", "t1").status.used.resource_counts
                == 3
            )
        finally:
            refl.stop()

    def test_stale_rv_conflicts(self, apiserver):
        apiserver.store.create_throttle(_throttle("t1", {"a": "b"}, pod=5))
        client = ApiClient(RestConfig(server=apiserver.url))
        versions = RemoteVersions()
        versions.set("Throttle", "default/t1", "999999")  # stale
        writer = RemoteStatusWriter(client, versions)
        thr = apiserver.store.get_throttle("default", "t1")
        with pytest.raises(ConflictError):
            writer.update_throttle_status(thr)


class TestWriteRateLimit:
    def test_token_bucket_burst_then_paced(self):
        from kube_throttler_tpu.client.transport import _TokenBucket

        tb = _TokenBucket(qps=100.0, burst=5)
        t0 = time.monotonic()
        for _ in range(5):
            tb.take()  # burst: immediate
        burst_t = time.monotonic() - t0
        assert burst_t < 0.05, f"burst takes should not wait ({burst_t:.3f}s)"
        t0 = time.monotonic()
        for _ in range(10):
            tb.take()  # drained: ~1/qps each
        paced_t = time.monotonic() - t0
        assert paced_t >= 0.08, f"drained takes must pace at qps ({paced_t:.3f}s)"

    def test_writes_pass_the_bucket_reads_do_not(self, apiserver):
        apiserver.store.create_throttle(_throttle("t1", {"a": "b"}, pod=5))
        client = ApiClient(RestConfig(server=apiserver.url), qps=10_000.0, burst=1)
        taken = []
        orig = client._write_bucket.take
        client._write_bucket.take = lambda: (taken.append(1), orig())[1]
        client.list("Throttle")  # read: no bucket
        assert taken == []
        thr = apiserver.store.get_throttle("default", "t1")
        # no tracked rv → the PUT omits resourceVersion (no optimistic check)
        RemoteStatusWriter(client, RemoteVersions()).update_throttle_status(thr)
        assert len(taken) == 1  # the PUT took a token

    def test_disabled_bucket(self, apiserver):
        client = ApiClient(RestConfig(server=apiserver.url), qps=None)
        assert client._write_bucket is None
        client.list("Throttle")  # still works


class TestRemoteModeGuards:
    def test_http_surface_refuses_local_writes_in_remote_mode(self, apiserver):
        import json as _json
        import urllib.request

        from kube_throttler_tpu.server import ThrottlerHTTPServer

        local = Store()
        session = RemoteSession(RestConfig(server=apiserver.url), local)
        session.start(sync_timeout=10)
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            local,
            use_device=False,
            status_writer=session.status_writer,
        )
        server = ThrottlerHTTPServer(plugin, port=0, remote=True)
        server.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/objects",
                data=_json.dumps(
                    {"kind": "Namespace", "metadata": {"name": "x"}}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req)
            assert exc.value.code == 409
            # admission endpoints still work
            body = {
                "kind": "Pod",
                "metadata": {"name": "p", "namespace": "default"},
                "spec": {"schedulerName": "my-scheduler", "containers": []},
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/prefilter",
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = _json.load(urllib.request.urlopen(req))
            assert resp["code"] == "Success"
        finally:
            server.stop()
            plugin.stop()
            session.stop()


class TestRemoteEvents:
    def test_warning_events_reach_the_apiserver(self, apiserver):
        """pod-requests-exceeds-threshold emits a Warning event; in remote
        mode it lands as a v1 Event on the cluster (plugin.go:190-201),
        with repeats aggregated into a count."""
        remote = apiserver.store
        remote.create_throttle(_throttle("t1", {"grp": "a"}, requests={"cpu": "100m"}))

        local = Store()
        session = RemoteSession(RestConfig(server=apiserver.url), local)
        session.start(sync_timeout=10)
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            local,
            use_device=True,
            event_recorder=session.event_recorder,
            status_writer=session.status_writer,
        )
        try:
            probe = make_pod("big", labels={"grp": "a"}, requests={"cpu": "5"})
            for _ in range(3):  # repeats aggregate, not multiply
                verdict = plugin.pre_filter(probe)
                assert not verdict.is_success()
            # emission is async (the hot path must never block on the
            # apiserver) — drain the recorder queue before asserting
            session.event_recorder.flush()
            # flush drains the queue; the last PUT may still be in flight —
            # wait on the observable count
            assert _wait(
                lambda: apiserver.events_in("default")
                and apiserver.events_in("default")[0].get("count") == 3
            )
            events = apiserver.events_in("default")
            assert len(events) == 1
            ev = events[0]
            assert ev["type"] == "Warning"
            assert ev["reason"] == "ResourceRequestsExceedsThrottleThreshold"
            assert ev["involvedObject"]["name"] == "big"
        finally:
            plugin.stop()
            session.stop()


class TestStandaloneWireServer:
    def test_daemon_serves_wire_protocol(self):
        """`serve --apiserver-port`: the standalone daemon's store doubles
        as a real list+watch control plane — a reflector client syncs its
        objects and observes the daemon's own status writes live."""
        import json as _json
        import re
        import subprocess
        import sys as _sys
        import urllib.request

        proc = subprocess.Popen(
            [
                _sys.executable, "-m", "kube_throttler_tpu.cli", "serve",
                "--name", "kube-throttler", "--target-scheduler-name", "my-scheduler",
                "--port", "0", "--apiserver-port", "0", "--no-device",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            from tests.conftest import ProcReader

            reader = ProcReader(proc)
            lines = reader.wait_for(r"serving on [^:]+:\d+", timeout_s=60)
            wire_port = api_port = None
            for line in lines:
                m = re.search(r"wire-protocol apiserver on [^:]+:(\d+)", line)
                if m:
                    wire_port = int(m.group(1))
                m = re.search(r"serving on [^:]+:(\d+)", line)
                if m:
                    api_port = int(m.group(1))
            assert wire_port and api_port, f"daemon did not start: {lines}"

            def post(doc):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{api_port}/v1/objects",
                    data=_json.dumps(doc).encode(),
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=10).read()

            post({
                "kind": "Throttle",
                "metadata": {"name": "t1", "namespace": "default"},
                "spec": {
                    "throttlerName": "kube-throttler",
                    "threshold": {"resourceRequests": {"cpu": "1"}},
                    "selector": {"selectorTerms": [{"podSelector": {"matchLabels": {"grp": "a"}}}]},
                },
            })
            post({
                "kind": "Pod",
                "metadata": {"name": "p1", "namespace": "default", "labels": {"grp": "a"}},
                "spec": {
                    "schedulerName": "my-scheduler", "nodeName": "node-1",
                    "containers": [{"resources": {"requests": {"cpu": "700m"}}}],
                },
                "status": {"phase": "Running"},
            })

            # a reflector client syncs from the daemon's wire server and
            # sees the daemon's OWN status write land
            local = Store()
            session = RemoteSession(
                RestConfig(server=f"http://127.0.0.1:{wire_port}"), local
            )
            session.start(sync_timeout=15)
            try:
                assert _wait(
                    lambda: local.list_throttles()
                    and local.list_throttles()[0].status.used.resource_counts == 1,
                    timeout=15,
                )
            finally:
                session.stop()
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestRemoteModeEndToEnd:
    def test_daemon_throttles_external_cluster(self, apiserver):
        """The VERDICT r2 task-2 done-bar: a daemon running against a
        simulated EXTERNAL cluster (over real HTTP list+watch) throttles its
        pods and writes status back to the remote status subresource."""
        remote = apiserver.store
        remote.create_throttle(_throttle("t1", {"grp": "a"}, requests={"cpu": "1"}))

        local = Store()
        session = RemoteSession(RestConfig(server=apiserver.url), local)
        session.start(sync_timeout=10)
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            local,
            use_device=True,
            start_workers=True,
            status_writer=session.status_writer,
        )
        try:
            # cache warmed by the reflectors
            assert local.get_namespace("default") is not None
            assert len(local.list_throttles()) == 1

            # a running pod appears on the REMOTE cluster
            remote.create_pod(
                _bound(make_pod("p1", labels={"grp": "a"}, requests={"cpu": "800m"}))
            )
            # ... flows to the local cache, reconciles, and the status write
            # lands on the REMOTE apiserver (used=800m, throttled=False).
            # 20s: a status-write Conflict (racing our own spec edit below
            # on a loaded host) takes a requeue-backoff + reflector-echo
            # round trip to converge — observed flaking at 10s under full
            # CPU contention
            assert _wait(
                lambda: remote.get_throttle("default", "t1").status.used.resource_counts
                == 1,
                timeout=20.0,
            )
            assert _wait(
                lambda: local.get_throttle("default", "t1").status.used.resource_counts
                == 1  # echo closed the loop
            )

            # admission: a 300m pod would exceed 1 cpu → insufficient
            verdict = plugin.pre_filter(
                make_pod("p2", labels={"grp": "a"}, requests={"cpu": "300m"})
            )
            assert not verdict.is_success()
            assert "throttle[insufficient]=default/t1" in verdict.reasons

            # threshold edit on the remote opens capacity
            t1 = remote.get_throttle("default", "t1")
            remote.update_throttle_spec(
                replace(
                    t1,
                    spec=replace(
                        t1.spec, threshold=ResourceAmount.of(requests={"cpu": "2"})
                    ),
                )
            )
            assert _wait(
                lambda: plugin.pre_filter(
                    make_pod("p2", labels={"grp": "a"}, requests={"cpu": "300m"})
                ).is_success(),
                timeout=20.0,
            )
        finally:
            plugin.stop()
            session.stop()


class TestClientConnectionConfig:
    def test_config_block_sets_defaults_flags_win(self):
        """KubeSchedulerConfiguration clientConnection.{qps,burst} parity."""
        import argparse

        import pytest as _pytest

        from kube_throttler_tpu.cli import _resolve_client_connection

        def fail(msg):
            raise AssertionError(msg)

        raw = {"clientConnection": {"qps": 25, "burst": 40}}
        ns = argparse.Namespace(api_qps=None, api_burst=None)  # flags unset
        _resolve_client_connection(raw, ns, fail)
        assert (ns.api_qps, ns.api_burst) == (25.0, 40)

        # an explicit flag wins EVEN at the default value (50)
        ns = argparse.Namespace(api_qps=50.0, api_burst=None)
        _resolve_client_connection(raw, ns, fail)
        assert (ns.api_qps, ns.api_burst) == (50.0, 40)

        ns = argparse.Namespace(api_qps=None, api_burst=None)
        _resolve_client_connection({}, ns, fail)  # no block: defaults
        assert (ns.api_qps, ns.api_burst) == (50.0, 100)

        # non-numeric values report through fail, not a raw traceback
        errs = []
        _resolve_client_connection(
            {"clientConnection": {"qps": "unlimited"}},
            argparse.Namespace(api_qps=None, api_burst=None),
            errs.append,
        )
        assert errs and "numeric" in errs[0]


class TestAsyncStatusCommitter:
    """AsyncStatusCommitter: coalescing, per-key ordering, conflict retry,
    drop-after-retries (transport.py; the remote-mode analog of the
    reference's synchronous UpdateStatus, throttle_controller.go:157-173)."""

    class _FakeWriter:
        """RemoteStatusWriter stand-in recording _put calls; can be armed
        to raise per-call."""

        def __init__(self):
            import threading

            self.calls = []  # (kind, key, status.used counts)
            self.fail_plan = {}  # key -> list of exceptions to raise first
            self.lock = threading.Lock()

        def _put(self, kind, obj):
            from kube_throttler_tpu.engine.store import key_of

            key = key_of(kind, obj)
            with self.lock:
                plan = self.fail_plan.get(key)
                if plan:
                    raise plan.pop(0)
                self.calls.append((kind, key, obj))

        def refresh_version(self, kind, obj):
            pass

    def _mk(self, **kw):
        from kube_throttler_tpu.client.transport import AsyncStatusCommitter

        w = self._FakeWriter()
        c = AsyncStatusCommitter(w, **kw)
        return w, c

    def _thr(self, name, pods):
        from kube_throttler_tpu.api import ResourceAmount, Throttle, ThrottleSpec
        from kube_throttler_tpu.api.types import ThrottleStatus

        return Throttle(
            name=name,
            namespace="default",
            spec=ThrottleSpec(throttler_name="kt"),
            status=ThrottleStatus(used=ResourceAmount.of(pod=pods)),
        )

    def test_newest_wins_coalescing(self):
        w, c = self._mk(workers=1)
        # submit 50 versions of one key BEFORE starting the worker: exactly
        # one PUT must go out, carrying the newest status
        for i in range(50):
            c.update_throttle_status(self._thr("a", pods=i))
        c.start()
        assert c.flush(5.0)
        c.stop()
        assert len(w.calls) == 1
        assert w.calls[0][2].status.used.resource_counts == 49

    def test_batch_interface_returns_all_keys(self):
        w, c = self._mk(workers=2)
        thrs = [self._thr(f"t{i}", pods=i) for i in range(8)]
        out = c.update_throttle_statuses(thrs)
        assert set(out) == {t.key for t in thrs}
        c.start()
        assert c.flush(5.0)
        c.stop()
        assert {k for (_, k, _) in w.calls} == {t.key for t in thrs}

    def test_per_key_ordering_single_worker_per_key(self):
        # keys hash to fixed shards: interleave two keys' submissions and
        # verify each key's PUT sequence is monotone in submission order
        w, c = self._mk(workers=4)
        c.start()
        for i in range(30):
            c.update_throttle_status(self._thr("x", pods=i))
            c.update_throttle_status(self._thr("y", pods=i))
        assert c.flush(5.0)
        c.stop()
        for key in ("default/x", "default/y"):
            seq = [o.status.used.resource_counts for (_, k, o) in w.calls if k == key]
            assert seq == sorted(seq), seq
            assert seq[-1] == 29  # newest landed last

    def test_conflict_retries_then_lands(self):
        from kube_throttler_tpu.engine.store import ConflictError
        from kube_throttler_tpu.metrics import Registry

        reg = Registry()
        w, c = self._mk(workers=1, metrics_registry=reg)
        w.fail_plan["default/a"] = [ConflictError("a"), ConflictError("a")]
        c.start()
        c.update_throttle_status(self._thr("a", pods=7))
        assert c.flush(5.0)
        c.stop()
        assert len(w.calls) == 1
        assert w.calls[0][2].status.used.resource_counts == 7
        counts = c._commits.collect()
        assert counts[("Throttle", "conflict")] == 2.0
        assert counts[("Throttle", "ok")] == 1.0

    def test_drop_after_retry_budget(self):
        w, c = self._mk(workers=1, max_retries=2)
        w.fail_plan["default/a"] = [RuntimeError("boom")] * 10
        c.start()
        c.update_throttle_status(self._thr("a", pods=1))
        assert c.flush(10.0)
        c.stop()
        assert w.calls == []  # dropped; resync re-plans it
