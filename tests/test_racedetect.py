"""Eraser lockset race detector: every planted race shape must fire at
the right file:line with both locksets, and the benign patterns must
stay silent — the dynamic analog of test_analysis.py's
fire-on-fixture/silent-on-repo contract.

All planted fixtures run inside ``racedetect.capture()`` so their
reports never leak into the suite-wide sessionfinish gate (which is
itself what turns a REAL race anywhere in the tier-1 run into a
failure). The suite arms ``KT_RACE_DETECT=1`` via conftest, so the
classes declared here get their tracking descriptors at decoration
time like any production class.
"""

from __future__ import annotations

import ast
import inspect
import os
import threading

import pytest

from kube_throttler_tpu.utils import lockorder, racedetect

pytestmark = pytest.mark.skipif(
    not racedetect.enabled(), reason="KT_RACE_DETECT off for this run"
)


def run_in_thread(fn, name="racer"):
    t = threading.Thread(target=fn, name=name)
    t.start()
    t.join()


def make_box():
    @lockorder.guard_attrs
    class Box:
        GUARDED_BY = {"_items": "self._lock", "_map": "self._lock"}

        def __init__(self):
            self._lock = lockorder.make_lock("racefix.box")
            self._items = []
            self._map = {}

    return Box()


class TestPlantedRaces:
    def test_write_write_fires_with_line(self):
        b = make_box()
        with racedetect.capture() as cap:
            b._items.append(1)  # main-thread unlocked write
            racy_line = inspect.currentframe().f_lineno + 1
            run_in_thread(lambda: b._items.append(2))
        assert len(cap.reports) == 1
        r = cap.reports[0]
        assert r.kind == "write/write"
        assert r.attr == "_items"
        assert r.qual.endswith("Box._items")
        # detection fires AT the second thread's access: the lambda on
        # the planted line
        assert f"test_racedetect.py:{racy_line}" in r.line
        assert r.held == () and r.prior_held == ()
        assert "test_racedetect.py" in r.site

    def test_read_write_fires(self):
        b = make_box()
        with racedetect.capture() as cap:
            def locked_write():
                with b._lock:
                    b._items.append(3)

            locked_write()
            run_in_thread(lambda: len(b._items))  # unlocked read
            locked_write()  # the write that empties C(v)
        assert [r.kind for r in cap.reports] == ["read/write"]
        r = cap.reports[0]
        assert "racefix.box" in r.held  # detecting write held the lock
        assert r.prior_held == ()  # the unlocked read emptied the set

    def test_lock_swap_fires(self):
        @lockorder.guard_attrs
        class Swap:
            GUARDED_BY = {"_d": ("self._la", "self._lb")}

            def __init__(self):
                self._la = lockorder.make_lock("racefix.swap.a")
                self._lb = lockorder.make_lock("racefix.swap.b")
                self._d = {}

        s = Swap()
        with racedetect.capture() as cap:
            def wa():
                with s._la:
                    s._d["a"] = 1  # STORE_SUBSCR classifies as write

            def wb():
                with s._lb:
                    s._d["b"] = 2

            wa()
            run_in_thread(wb)
            wa()
        assert len(cap.reports) == 1
        r = cap.reports[0]
        assert r.kind == "write/write"
        assert "racefix.swap.a" in r.held
        assert "racefix.swap.b" in r.prior_held

    def test_benign_initialization_is_silent(self):
        b = make_box()
        with racedetect.capture() as cap:
            # single-owner init writes, then handoff: every cross-thread
            # access holds the lock — Exclusive → Shared(-Modified) with
            # a stable nonempty candidate set
            b._items.append(0)
            b._map["seed"] = 1

            def locked_use():
                with b._lock:
                    b._items.append(9)
                    return len(b._items) + len(b._map)

            for _ in range(3):
                run_in_thread(locked_use)
        assert cap.reports == []

    def test_read_share_is_silent(self):
        # publish-then-read-only: unlocked reads from many threads never
        # report (the read-share state exists exactly for this pattern)
        b = make_box()
        with racedetect.capture() as cap:
            b._items.append(1)
            for i in range(3):
                run_in_thread(lambda: len(b._items), name=f"reader-{i}")
        assert cap.reports == []

    def test_one_report_per_attribute(self):
        b = make_box()
        with racedetect.capture() as cap:
            for i in range(5):
                run_in_thread(lambda: b._items.append(i))
        assert len(cap.reports) == 1  # first observation only


class TestWaivers:
    def test_waived_race_is_suppressed_and_counted(self, monkeypatch):
        b = make_box()
        qual = f"{type(b).__module__}.{type(b).__qualname__}._items"
        monkeypatch.setattr(racedetect, "_allow_cache", {qual: "test waiver"})
        with racedetect.capture() as cap:
            b._items.append(1)
            run_in_thread(lambda: b._items.append(2))
        assert cap.reports == []
        assert qual in racedetect.fired_waivers()

    def test_load_allow_parses_justifications(self, tmp_path):
        p = tmp_path / "race_allow.txt"
        p.write_text(
            "# comment\n"
            "engine.store.Store._objects  # GIL-atomic snapshot read\n"
            "metrics.Registry._vals\n"
        )
        allow = racedetect.load_allow(str(p))
        assert allow["engine.store.Store._objects"] == "GIL-atomic snapshot read"
        assert allow["metrics.Registry._vals"] == ""


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "kube_throttler_tpu")


def _guarded_attrs_in_repo():
    """{Class.attr} across every GUARDED_BY table in the package (AST —
    no imports, mirrors the static analyzer)."""
    out = set()
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                tree = ast.parse(open(path, encoding="utf-8").read())
            except SyntaxError:
                continue
            rel = os.path.relpath(path, PKG)[:-3].replace(os.sep, ".")
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    if (
                        isinstance(stmt, ast.Assign)
                        and any(
                            isinstance(t, ast.Name) and t.id == "GUARDED_BY"
                            for t in stmt.targets
                        )
                    ):
                        try:
                            table = ast.literal_eval(stmt.value)
                        except ValueError:
                            continue
                        for attr in table:
                            out.add(f"{rel}.{node.name}.{attr}")
    return out


class TestAllowFileHygiene:
    """The PR 10 stale-entry-is-an-error convention, enforced statically
    so it never depends on which tests ran this session."""

    def test_every_entry_justified_and_live(self):
        allow = racedetect.load_allow()
        guarded = _guarded_attrs_in_repo()
        stale = [k for k in allow if k not in guarded]
        unjustified = [k for k, why in allow.items() if not why.strip()]
        assert not unjustified, (
            f"race_allow.txt entries missing a justification: {unjustified}"
        )
        assert not stale, (
            "race_allow.txt entries naming no current GUARDED_BY attr "
            f"(waiver rot — delete them): {stale}"
        )


class TestMechanics:
    def test_descriptor_preserves_dict_shape(self):
        b = make_box()
        b._items.append(1)
        assert "_items" in b.__dict__  # storage under the plain key
        assert b.__dict__["_items"] == [1]

    def test_subscript_store_classified_as_write(self):
        b = make_box()
        with racedetect.capture() as cap:
            b._map["k"] = 1
            run_in_thread(lambda: b._map.update(j=2))
        assert [r.kind for r in cap.reports] == ["write/write"]

    def test_disabled_mode_installs_nothing(self):
        import subprocess
        import sys

        code = (
            "import os\n"
            "os.environ['KT_RACE_DETECT'] = '0'\n"
            "os.environ['KT_LOCK_ASSERT'] = '0'\n"
            "from kube_throttler_tpu.utils import lockorder\n"
            "@lockorder.guard_attrs\n"
            "class Box:\n"
            "    GUARDED_BY = {'_x': 'self._lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = lockorder.make_lock('b')\n"
            "        self._x = []\n"
            "assert not hasattr(type(Box.__dict__.get('_x', None)), 'qual')\n"
            "import threading\n"
            "assert isinstance(Box()._lock, type(threading.Lock()))\n"
            "print('ok')\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert r.returncode == 0 and "ok" in r.stdout, r.stderr

    def test_race_mode_alone_instruments_locks(self):
        import subprocess
        import sys

        code = (
            "import os\n"
            "os.environ['KT_RACE_DETECT'] = '1'\n"
            "os.environ['KT_LOCK_ASSERT'] = '0'\n"
            "from kube_throttler_tpu.utils import lockorder\n"
            "lk = lockorder.make_lock('x')\n"
            "assert type(lk).__name__ == '_InstrumentedLock', type(lk)\n"
            "with lk:\n"
            "    assert lockorder.held_names() == ('x',)\n"
            "print('ok')\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )
        assert r.returncode == 0 and "ok" in r.stdout, r.stderr
