"""Verdict-coherence assassin (utils/epochassert.py).

The epochs static checker proves every visible verdict-plane write is
dominated by a bump; these tests pin the runtime companion that keeps
that proof honest: a planted bump-free mutation MUST surface as a
StaleVerdict on the next sampled cache hit (must-fire, like the
lockorder/racedetect planted-bug suites), a clean stack must survive
shadow-recompute silently, and the report must carry the forensics an
operator needs — both epochs (equal: the smoking gun), both verdicts,
and the file:line of the mutation that skipped its bump.
"""

from __future__ import annotations

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.plugin.framework import Status, StatusCode
from kube_throttler_tpu.utils import epochassert


def _throttle(name="t1", cpu="200m", grp="a"):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": cpu}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        pod_selector=LabelSelector(match_labels={"grp": grp})
                    ),
                )
            ),
        ),
    )


def _stack():
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
    store.create_namespace(Namespace("default"))
    store.create_throttle(_throttle())
    plugin.run_pending_once()
    assert plugin.verdict_cache is not None
    assert plugin._epoch_assert, "conftest must arm KT_EPOCH_ASSERT before imports"
    return store, plugin


@pytest.fixture(autouse=True)
def _isolate():
    """Every-hit sampling inside these tests; restore the suite default
    (and drop first-observation state) on the way out so the rest of the
    armed suite is unaffected by what we plant here."""
    epochassert.reset()
    epochassert.set_sample(1)
    yield
    epochassert.reset()


def _plant_bump_free_flip(plugin):
    """The bug class itself: flip t1's throttled flags directly on the
    staging planes with correct dirty tracking (so the device sync sees
    it — a real buggy mutator would do this much) but WITHOUT the
    col_epoch bump the epoch contract demands."""
    ks = plugin.device_manager.throttle
    col = ks.index._thr_cols["default/t1"]
    before = (ks.tcap, ks.R)
    ks.st_cnt_throttled[col] = True
    ks.st_req_throttled[col, :] = True
    ks.st_req_flag_present[col, :] = True
    ks._note_thr_col(col, before)  # MISSING: ks.col_epoch[col] += 1
    return col


class TestAssassin:
    def test_clean_hits_survive_shadow_recompute(self):
        _, plugin = _stack()
        pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
        first = plugin.pre_filter(pod)
        assert first.code is StatusCode.SUCCESS
        # sample=1: every one of these hits is shadow-recomputed
        for _ in range(5):
            assert plugin.pre_filter(pod) is first
        assert epochassert.reports() == []

    def test_planted_missed_bump_fires_staleverdict(self):
        _, plugin = _stack()
        pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
        assert plugin.pre_filter(pod).code is StatusCode.SUCCESS  # interned
        _plant_bump_free_flip(plugin)
        with pytest.raises(epochassert.StaleVerdict) as ei:
            plugin.pre_filter(pod)
        msg = str(ei.value)
        # the smoking gun: the fingerprint did NOT move
        assert "(UNCHANGED)" in msg
        assert "cached verdict" in msg and "oracle verdict" in msg
        # mutation provenance: _note_thr_col recorded the planter's frame
        assert "test_epochassert.py" in msg, msg
        assert len(epochassert.reports()) == 1

    def test_first_observation_only_per_key(self):
        _, plugin = _stack()
        pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
        plugin.pre_filter(pod)
        _plant_bump_free_flip(plugin)
        with pytest.raises(epochassert.StaleVerdict):
            plugin.pre_filter(pod)
        # same stale key again: already reported — the hit is served
        # without a second raise (one report per distinct missed bump,
        # not one per probe)
        st = plugin.pre_filter(pod)
        assert st.code is StatusCode.SUCCESS  # still the stale intern
        assert len(epochassert.reports()) == 1

    def test_error_recompute_is_not_coherence_evidence(self):
        _, plugin = _stack()
        pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
        first = plugin.pre_filter(pod)
        plugin._pre_filter_uncached = lambda p, emit_events=True: Status(
            StatusCode.ERROR, ("device transiently down",)
        )
        assert plugin.pre_filter(pod) is first  # hit survives, no report
        assert epochassert.reports() == []

    def test_sampling_counter_is_every_nth(self):
        epochassert.set_sample(3)
        got = [epochassert.should_check() for _ in range(7)]
        assert got == [False, False, True, False, False, True, False]

    def test_malformed_sample_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("KT_EPOCH_ASSERT_SAMPLE", "every-other")
        epochassert.reset()  # re-reads the env; malformed → default 7
        got = [epochassert.should_check() for _ in range(7)]
        assert got == [False] * 6 + [True]

    def test_note_mutation_bounded_and_newest_last(self):
        for _ in range(20):
            epochassert.note_mutation(depth=1)
        _, plugin = _stack()
        pod = make_pod("p", labels={"grp": "a"}, requests={"cpu": "100m"})
        plugin.pre_filter(pod)
        _plant_bump_free_flip(plugin)
        with pytest.raises(epochassert.StaleVerdict) as ei:
            plugin.pre_filter(pod)
        # the deque is bounded: the 20 synthetic sites did not crowd out
        # the planted mutation (newest entries win)
        assert "_plant_bump_free_flip" in str(ei.value)
