"""Ring-rotation sweep vs the single-device step on the 8-device virtual
CPU mesh, plus the hybrid-mesh/distributed helpers (single-process mode)."""

import random

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kube_throttler_tpu.parallel import (
    full_update_step,
    hybrid_mesh,
    init_distributed,
    make_ring_mesh,
    ring_full_update,
    shard_global_array,
)
from tests.test_parallel import _build_inputs


@pytest.mark.parametrize("seed,P_,T_", [(0, 32, 16), (7, 16, 8), (11, 64, 8)])
def test_ring_matches_single_device(seed, P_, T_):
    assert len(jax.devices()) == 8
    rng = random.Random(seed)
    inputs = _build_inputs(rng, P_, T_)

    single = full_update_step(*inputs)
    mesh = make_ring_mesh(8)
    ringed = ring_full_update(mesh)(*inputs)

    for got, want in zip(ringed, single):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ring_asymmetric_flags():
    # the Throttle-kind step3 asymmetry must survive the ring decomposition
    rng = random.Random(3)
    inputs = _build_inputs(rng, 16, 8)
    mesh = make_ring_mesh(8)
    for on_equal, s3 in [(True, True), (False, False), (True, False)]:
        single = full_update_step(*inputs, on_equal=on_equal, step3_on_equal=s3)
        ringed = ring_full_update(mesh, on_equal=on_equal, step3_on_equal=s3)(*inputs)
        for got, want in zip(ringed, single):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_init_distributed_single_process_noop():
    assert init_distributed() is False  # no coordinator configured → no-op


def test_hybrid_mesh_single_process():
    mesh = hybrid_mesh()
    assert mesh.axis_names == ("pods", "throttles")
    assert mesh.devices.size == 8


def test_shard_global_array_single_process():
    mesh = hybrid_mesh(ici_shape=(4, 2))
    arr = np.arange(32, dtype=np.int64).reshape(8, 4)
    out = shard_global_array(mesh, P("pods", None), arr)
    np.testing.assert_array_equal(np.asarray(out), arr)
    assert len(out.sharding.device_set) == 8 or out.sharding.is_fully_replicated is False
