"""Randomized differential soak: two full stacks — device-kernel-served and
host-oracle — consume an identical random event stream; every pod's
PreFilter verdict and every throttle's reconciled status must agree at
every checkpoint.

This is the strongest end-to-end equivalence artifact: it exercises the
whole pipeline (store → watch events → selector index (native C++ row tier
on one side) → device mirror → packed indexed check kernel) against the
pure-Python reference semantics, over object shapes unit tests don't
enumerate (matchExpressions columns, label moves, unknown namespaces,
overrides straddling the fake clock, reservations, deletes).
"""

from __future__ import annotations

import os
import random
from dataclasses import replace
from datetime import datetime, timedelta, timezone

import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    LabelSelectorRequirement,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import NotFoundError, Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.utils.clock import FakeClock

NOW = datetime(2024, 3, 1, 12, 0, 0, tzinfo=timezone.utc)


def _rfc(dt):
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _stack(use_device: bool):
    store = Store()
    clock = FakeClock(NOW)
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}),
        store,
        clock=clock,
        use_device=use_device,
    )
    return store, plugin, clock


def _rand_expression(rng):
    op = rng.choice(["In", "NotIn", "Exists", "DoesNotExist"])
    return LabelSelectorRequirement(
        key=rng.choice("abc"),
        operator=op,
        values=(rng.choice("xyz"),) if op in ("In", "NotIn") else (),
    )


def _rand_selector(rng, cluster: bool):
    terms = []
    for _ in range(rng.randint(0, 2)):
        pod_sel = LabelSelector(
            match_labels={rng.choice("abc"): rng.choice("xyz") for _ in range(rng.randint(0, 2))},
            match_expressions=(
                (_rand_expression(rng),) if rng.random() < 0.3 else ()
            ),
        )
        if cluster:
            terms.append(
                ClusterThrottleSelectorTerm(
                    pod_selector=pod_sel,
                    namespace_selector=LabelSelector(
                        match_labels={"env": rng.choice("pq")} if rng.random() < 0.4 else {}
                    ),
                )
            )
        else:
            terms.append(ThrottleSelectorTerm(pod_selector=pod_sel))
    if cluster:
        return ClusterThrottleSelector(selector_terms=tuple(terms))
    return ThrottleSelector(selector_terms=tuple(terms))


def _rand_threshold(rng):
    reqs = {}
    if rng.random() < 0.8:
        reqs["cpu"] = f"{rng.randint(1, 9)}00m"
    if rng.random() < 0.5:
        reqs["memory"] = f"{rng.randint(1, 8)}Gi"
    return ResourceAmount.of(
        pod=rng.randint(1, 4) if rng.random() < 0.6 else None, requests=reqs or None
    )


def _rand_overrides(rng):
    out = []
    for _ in range(rng.randint(0, 2)):
        active = rng.random() < 0.5
        begin = NOW - timedelta(hours=1) if active else NOW + timedelta(hours=1)
        out.append(
            TemporaryThresholdOverride(
                begin=_rfc(begin),
                end=_rfc(begin + timedelta(hours=2)),
                threshold=_rand_threshold(rng),
            )
        )
    return tuple(out)


from conftest import normalize_reasons as _normalize_reasons


def _status_dict(thr):
    return {
        "used": thr.status.used.to_dict(),
        "throttled": thr.status.throttled.to_dict(),
        "threshold": thr.status.calculated_threshold.threshold.to_dict(),
    }


#  seed 20 found the reservation-outlives-throttle-recreation divergence
#  (device under-counted reserved after delete+recreate) — keep it pinned
@pytest.mark.parametrize("seed", [1, 2, 3, 20])
def test_device_and_host_stacks_agree_under_random_churn(seed):
    rng = random.Random(seed)
    (store_d, plug_d, clock_d), (store_h, plug_h, clock_h) = _stack(True), _stack(False)

    namespaces = ["default", "ns1", "ns2"]
    pods: list = []

    def both(fn):
        fn(store_d)
        fn(store_h)

    # two namespaces known from the start; ns2 arrives (or not) mid-stream
    for ns in namespaces[:2]:
        labels = {"env": rng.choice("pq")}
        both(lambda s, ns=ns, labels=labels: s.create_namespace(Namespace(ns, labels=dict(labels))))

    def checkpoint():
        plug_d.run_pending_once()
        plug_h.run_pending_once()
        # every pod's PreFilter verdict agrees
        for pod in pods:
            sd = plug_d.pre_filter(pod)
            sh = plug_h.pre_filter(pod)
            assert sd.code == sh.code, (pod.key, sd.reasons, sh.reasons)
            assert _normalize_reasons(sd.reasons) == _normalize_reasons(sh.reasons), pod.key
        # every throttle's reconciled status agrees
        for thr_d in store_d.list_throttles():
            thr_h = store_h.get_throttle(thr_d.namespace, thr_d.name)
            assert _status_dict(thr_d) == _status_dict(thr_h), thr_d.key
        for ct_d in store_d.list_cluster_throttles():
            ct_h = store_h.get_cluster_throttle(ct_d.name)
            assert _status_dict(ct_d) == _status_dict(ct_h), ct_d.key

    for step in range(120):
        op = rng.random()
        if op < 0.25:  # (re)apply a Throttle
            name, ns = f"t{rng.randint(0, 6)}", rng.choice(namespaces)
            thr = Throttle(
                name=name,
                namespace=ns,
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=_rand_threshold(rng),
                    temporary_threshold_overrides=_rand_overrides(rng),
                    selector=_rand_selector(rng, cluster=False),
                ),
            )

            def apply_thr(s, thr=thr):
                try:
                    s.create_throttle(thr)
                except ValueError:
                    cur = s.get_throttle(thr.namespace, thr.name)
                    s.update_throttle(replace(thr, status=cur.status))

            both(apply_thr)
        elif op < 0.4:  # (re)apply a ClusterThrottle
            ct = ClusterThrottle(
                name=f"ct{rng.randint(0, 4)}",
                spec=ClusterThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=_rand_threshold(rng),
                    temporary_threshold_overrides=_rand_overrides(rng),
                    selector=_rand_selector(rng, cluster=True),
                ),
            )

            def apply_ct(s, ct=ct):
                try:
                    s.create_cluster_throttle(ct)
                except ValueError:
                    cur = s.get_cluster_throttle(ct.name)
                    s.update_cluster_throttle(replace(ct, status=cur.status))

            both(apply_ct)
        elif op < 0.65 or not pods:  # create a pod (sometimes already bound)
            name, ns = f"p{step}", rng.choice(namespaces)
            pod = make_pod(
                name,
                namespace=ns,
                labels={rng.choice("abc"): rng.choice("xyz") for _ in range(rng.randint(0, 2))},
                requests={"cpu": f"{rng.randint(1, 6)}00m"},
                scheduler_name="my-scheduler" if rng.random() < 0.9 else "other",
                node_name="n1" if rng.random() < 0.5 else "",
            )
            pods.append(pod)
            both(lambda s, pod=pod: s.create_pod(pod))
        elif op < 0.75:  # label move on a random pod
            old = rng.choice(pods)
            moved = replace(
                old, labels={rng.choice("abc"): rng.choice("xyz")}
            )
            pods[pods.index(old)] = moved

            def upd(s, moved=moved):
                try:
                    s.update_pod(moved)
                except NotFoundError:
                    pass

            both(upd)
        elif op < 0.82:  # reserve / unreserve a pod (scheduler cycle)
            pod = rng.choice(pods)
            if rng.random() < 0.6:
                sd, sh = plug_d.reserve(pod), plug_h.reserve(pod)
                assert sd.code == sh.code, (pod.key, sd.reasons, sh.reasons)
            else:
                plug_d.unreserve(pod)
                plug_h.unreserve(pod)
        elif op < 0.87:  # delete a throttle — exercises column free/reuse
            if rng.random() < 0.5:
                name, ns = f"t{rng.randint(0, 6)}", rng.choice(namespaces)

                def rm_thr(s, name=name, ns=ns):
                    try:
                        s.delete_throttle(ns, name)
                    except NotFoundError:
                        pass

                both(rm_thr)
            else:
                name = f"ct{rng.randint(0, 4)}"

                def rm_ct(s, name=name):
                    try:
                        s.delete_cluster_throttle(name)
                    except NotFoundError:
                        pass

                both(rm_ct)
        elif op < 0.93:  # delete a pod
            pod = pods.pop(rng.randrange(len(pods)))

            def rm(s, pod=pod):
                try:
                    s.delete_pod(pod.namespace, pod.name)
                except NotFoundError:
                    pass

            both(rm)
        else:  # late namespace arrival / label change
            ns = rng.choice(namespaces)
            labels = {"env": rng.choice("pq")}

            def upsert_ns(s, ns=ns, labels=labels):
                try:
                    s.create_namespace(Namespace(ns, labels=dict(labels)))
                except ValueError:
                    s.update_namespace(Namespace(ns, labels=dict(labels)))

            both(upsert_ns)

        if step == 60:
            # advance both clocks past every override window boundary so the
            # next reconciles flip active → expired (and future → active)
            clock_d.advance(timedelta(hours=1, minutes=30))
            clock_h.advance(timedelta(hours=1, minutes=30))
            # re-reconcile every override-bearing throttle at the new time
            for s, p in ((store_d, plug_d), (store_h, plug_h)):
                for thr in s.list_throttles():
                    s.update_throttle(thr)
                for ct in s.list_cluster_throttles():
                    s.update_cluster_throttle(ct)

        if step % 12 == 11:
            checkpoint()

    checkpoint()


@pytest.mark.skipif(
    not os.environ.get("KT_SOAK_SEEDS"),
    reason="set KT_SOAK_SEEDS=lo:hi for the wide randomized soak",
)
def test_wide_soak_seed_range():
    """Opt-in wide soak (KT_SOAK_SEEDS=4:200 validated this round; seed 20
    found the reservation-outlives-recreation divergence). Each seed is an
    independent 120-step churn differential between the device and host
    stacks."""
    lo, hi = (int(x) for x in os.environ["KT_SOAK_SEEDS"].split(":"))
    for seed in range(lo, hi):
        test_device_and_host_stacks_agree_under_random_churn(seed)
