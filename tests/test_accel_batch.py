"""Accel-class resolution on the batch-triage surfaces (PR 8 satellite).

The single-pod path has routed accel-class pods to the class-aware host
oracle since PR 7; ``pre_filter_batch`` and the sharded tick classified
them against the device planes' BASE thresholds. These pin the regression
contract: batch and single-pod verdicts agree for accel-class pods
whenever any mirrored throttle declares ``accelClassThresholds``.
"""

from __future__ import annotations

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    AccelClassThreshold,
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.plugin.framework import StatusCode


def _throttle(name, pod=None, accel=()):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(pod=pod),
            accel_class_thresholds=tuple(accel),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        LabelSelector(match_labels={"throttle": name})
                    ),
                )
            ),
        ),
    )


def _build():
    store = Store()
    store.create_namespace(Namespace("default"))
    store.create_throttle(
        _throttle(
            "t1",
            pod=10,
            accel=[AccelClassThreshold("v5e", ResourceAmount.of(pod=0))],
        )
    )
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
    )
    store.create_pod(make_pod("plain", labels={"throttle": "t1"}))
    store.create_pod(
        make_pod("accel", labels={"throttle": "t1"}, accel_class="v5e")
    )
    plugin.run_pending_once()
    return store, plugin


class TestAccelClassBatchSurfaces:
    def test_batch_agrees_with_single_pod_for_accel_pods(self):
        store, plugin = _build()
        try:
            per_pod = {
                p.key: plugin.pre_filter(p).code == StatusCode.SUCCESS
                for p in store.list_pods()
            }
            # the single-pod route resolves the v5e pod=0 replacement: the
            # accel pod is blocked, the plain pod is not
            assert per_pod["default/plain"] is True
            assert per_pod["default/accel"] is False

            batch = plugin.pre_filter_batch()["schedulable"]
            assert batch == per_pod
        finally:
            plugin.stop()

    def test_sharded_tick_agrees_for_accel_pods(self):
        store, plugin = _build()
        try:
            out = plugin.full_tick_sharded(n_devices=1)
            assert out["schedulable"]["default/accel"] is False
            assert out["schedulable"]["default/plain"] is True
        finally:
            plugin.stop()

    def test_no_accel_thresholds_means_zero_override_work(self):
        # with no accelClassThresholds mirrored, the override pass is a
        # no-op even for pods carrying an accel class annotation
        store = Store()
        store.create_namespace(Namespace("default"))
        store.create_throttle(_throttle("t1", pod=10))
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            store,
            use_device=True,
        )
        try:
            store.create_pod(
                make_pod("accel", labels={"throttle": "t1"}, accel_class="v5e")
            )
            plugin.run_pending_once()
            batch = plugin.pre_filter_batch()["schedulable"]
            assert batch["default/accel"] is True
        finally:
            plugin.stop()
