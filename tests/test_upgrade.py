"""Rolling-upgrade safety: the version/capability handshake
(version.py ↔ sharding/ipc.py ↔ sharding/worker.py), the durable
FORMAT_REGISTRY contract (journal/snapshot refusals by name, never
silent corruption-skips), the replication protocol stamp, the
supervisor's crash-loop backoff guard, the build_info exposition, and
the committed pre-bump journal fixture's bit-identical replay.

The live subprocess roll (front-first / worker-first orders, mid-roll
SIGKILL, incompatible-major refusal under storm load) is
``tools/upgradetest.py`` (``make upgrade-test``; smoke tier in
hack/ci.sh) — this file covers the deterministic in-process layers.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

import pytest

import tools.harness as H
from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.engine.journal import (
    JournalFormatError,
    attach,
)
from kube_throttler_tpu.engine.replication import (
    PROTO_HEADER,
    ReplicationDiverged,
    ReplicationServer,
    ReplicationSource,
    SliceChunkSink,
    SliceChunkSource,
    StandbyReplicator,
)
from kube_throttler_tpu.engine.snapshot import (
    SnapshotManager,
    SUPPORTED_SNAPSHOT_VERSIONS,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.metrics import Registry, register_build_metrics
from kube_throttler_tpu.sharding.front import AdmissionFront
from kube_throttler_tpu.sharding.ipc import VersionMismatch
from kube_throttler_tpu.sharding.supervisor import ShardSupervisor
from kube_throttler_tpu.version import (
    BUILD_ID,
    CAPABILITIES,
    FORMAT_REGISTRY,
    NegotiationError,
    PROTO_MAJOR,
    PROTO_VERSION,
    advertised_capabilities,
    local_hello,
    local_proto_version,
    min_reader_version,
    negotiate,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

NS_OBJ = {
    "apiVersion": "v1",
    "kind": "Namespace",
    "metadata": {"name": "default", "uid": "uid-1"},
}


def _write_journal(path, lines):
    with open(path, "w", encoding="utf-8") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")


# --------------------------------------------------------------------------
# negotiation unit contract (version.py)
# --------------------------------------------------------------------------


class TestNegotiate:
    def test_minor_negotiates_down_caps_intersect(self):
        proto, caps = negotiate(
            (1, 3), {"a", "b", "c"}, [1, 1], ["b", "c", "d"]
        )
        assert proto == (1, 1)
        assert caps == frozenset({"b", "c"})

    def test_no_hello_is_the_zero_cap_baseline(self):
        proto, caps = negotiate((1, 2), CAPABILITIES, None, None)
        assert proto == (1, 0)
        assert caps == frozenset()

    def test_major_mismatch_refused(self):
        with pytest.raises(NegotiationError, match="incompatible protocol major"):
            negotiate((1, 1), CAPABILITIES, [2, 0], [])

    def test_malformed_hello_refused(self):
        with pytest.raises(NegotiationError, match="malformed"):
            negotiate((1, 1), CAPABILITIES, "banana", [])

    def test_non_string_caps_dropped(self):
        _, caps = negotiate((1, 1), {"x"}, [1, 1], ["x", 7, None])
        assert caps == frozenset({"x"})

    def test_env_caps_mask(self):
        assert advertised_capabilities({}) == CAPABILITIES
        assert advertised_capabilities({"KT_PROTO_CAPS_MASK": ""}) == frozenset()
        assert advertised_capabilities(
            {"KT_PROTO_CAPS_MASK": "evt-columnar"}
        ) == frozenset({"evt-columnar"})
        # unknown names mask to nothing extra — the intersection with
        # CAPABILITIES is what the hello carries
        assert advertised_capabilities(
            {"KT_PROTO_CAPS_MASK": "warp-drive"}
        ) == frozenset()

    def test_env_major_override(self):
        assert local_proto_version({}) == PROTO_VERSION
        assert local_proto_version({"KT_PROTO_MAJOR": "99"})[0] == 99
        # a non-integer override is ignored, never a crash
        assert local_proto_version({"KT_PROTO_MAJOR": "banana"}) == PROTO_VERSION

    def test_local_hello_shape(self):
        hello = local_hello({})
        assert hello["proto"] == [PROTO_MAJOR, PROTO_VERSION[1]]
        assert hello["caps"] == sorted(CAPABILITIES)
        assert hello["build"] == BUILD_ID

    def test_registry_covers_durable_formats(self):
        from kube_throttler_tpu.engine.journal import _KNOWN_LINE_TYPES

        for ctype in _KNOWN_LINE_TYPES - {"ADDED", "MODIFIED", "DELETED"}:
            assert min_reader_version("journal", ctype) == 1, ctype
        for v in SUPPORTED_SNAPSHOT_VERSIONS:
            assert min_reader_version("snapshot", v) == 1, v
        assert min_reader_version("frame", "hello") == 1
        assert min_reader_version("frame", "warp") is None
        # durable rows only ever ADD — this count can grow, never shrink
        assert len(FORMAT_REGISTRY) >= 11


# --------------------------------------------------------------------------
# journal: unknown-but-versioned control lines refuse replay by name
# --------------------------------------------------------------------------


class TestJournalFormatRefusal:
    def test_unknown_control_line_stops_replay(self, tmp_path):
        path = str(tmp_path / "store.journal")
        _write_journal(path, [
            {"type": "EPOCH", "epoch": 1},
            {"type": "ADDED", "kind": "Namespace", "object": NS_OBJ},
            {"type": "QUORUM", "op": "begin", "minReader": "2.0"},
            {
                "type": "ADDED",
                "kind": "Namespace",
                "object": {
                    "apiVersion": "v1",
                    "kind": "Namespace",
                    "metadata": {"name": "late"},
                },
            },
        ])
        store = Store()
        j = attach(store, path)
        try:
            assert j.format_refused == 1
            assert "QUORUM" in j.format_refused_reason
            assert "2.0" in j.format_refused_reason  # the named demand
            # replay stopped AT the boundary: the prefix applied, the
            # suffix did not (skipping it could misapply semantics the
            # refused control line was meant to bracket)
            assert store.get_namespace("default") is not None
            assert store.get_namespace("late") is None
            state, detail = j.health_state()
            assert state == "down"
            assert "QUORUM" in detail["formatRefusedReason"]
            # accounted position still covers the whole file, so a later
            # (upgraded) attach replays from genesis, not mid-file
            assert j.position()[0] == os.path.getsize(path)
        finally:
            j.close()

    def test_corruption_still_skips_not_refuses(self, tmp_path):
        path = str(tmp_path / "store.journal")
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"type": "ADDED", "kind": "Namespace", "object": NS_OBJ}
            ) + "\n")
            f.write("this is not json\n")  # bit rot: skip and count
            # unknown uppercase type WITH an object payload is an event
            # from an unknown kind, not a control line: corruption-skip
            f.write(json.dumps({"type": "ZAPPED", "object": {"x": 1}}) + "\n")
            f.write(json.dumps(
                {
                    "type": "ADDED",
                    "kind": "Namespace",
                    "object": {
                        "apiVersion": "v1",
                        "kind": "Namespace",
                        "metadata": {"name": "after"},
                    },
                }
            ) + "\n")
        store = Store()
        j = attach(store, path)
        try:
            assert j.format_refused == 0
            assert j.replay_skipped == 2
            assert store.get_namespace("after") is not None
            assert j.health_state()[0] != "down"
        finally:
            j.close()

    def test_non_string_type_is_corruption_not_a_crash(self, tmp_path):
        path = str(tmp_path / "store.journal")
        _write_journal(path, [
            {"type": 5, "zap": 1},
            {"type": "ADDED", "kind": "Namespace", "object": NS_OBJ},
        ])
        store = Store()
        j = attach(store, path)
        try:
            assert j.format_refused == 0
            assert store.get_namespace("default") is not None
        finally:
            j.close()


class TestJournalPrebumpFixture:
    def test_prebump_journal_replays_bit_identically(self, tmp_path):
        """The committed pre-bump journal (every v1 line type: the three
        watch events plus EPOCH/GANG/PREEMPT control lines) must replay
        cleanly — zero skips, zero refusals — into the same store twice
        over, with the accounted (offset, sha) exactly the file's bytes.
        Re-keying or dropping a FORMAT_REGISTRY row breaks this forever."""
        import shutil

        fixture = os.path.join(FIXTURES, "journal-v1-prebump")
        with open(fixture, "rb") as f:
            raw = f.read()
        dumps = []
        for d in ("a", "b"):
            wdir = tmp_path / d
            wdir.mkdir()
            path = str(wdir / "store.journal")
            shutil.copy(fixture, path)
            store = Store()
            j = attach(store, path)
            try:
                assert j.format_refused == 0
                assert j.replay_skipped == 0 and j.torn_tails == 0
                assert j.last_epoch == 4
                assert j.gang_ops["default/gang-a"]["op"] == "commit"
                assert j.gang_ops["default/gang-a"]["members"] == ["default/p0"]
                assert j.preempt_ops["preempt-7"]["op"] == "commit"
                # the replayed state: p1 was preempted away, p0 moved nodes
                assert store.get_namespace("default") is not None
                assert store.get_throttle("default", "t0") is not None
                keys = {p.key for p in store.list_pods()}
                assert keys == {"default/p0"}
                assert store.get_pod("default", "p0").spec.node_name == "node-3"
                offset, sha = j.position()
                assert offset == len(raw)
                assert sha == hashlib.sha256(raw).hexdigest()
            finally:
                j.close()
            dumps.append(H.dump_store(store))
        assert dumps[0] == dumps[1]

    def test_prebump_pair_is_committed(self):
        # the PAIR the upgrade contract pins: one pre-bump snapshot, one
        # pre-bump journal, both under tests/fixtures/
        assert os.path.exists(os.path.join(FIXTURES, "snapshot-v1-prebump.ktsnap"))
        assert os.path.exists(os.path.join(FIXTURES, "journal-v1-prebump"))


# --------------------------------------------------------------------------
# replication: proto stamp + snapshot/control-line refusals (satellite:
# unsupported-snapshot bootstrap fails FAST with the version named)
# --------------------------------------------------------------------------


def _standby(tmp_path, name="standby"):
    sdir = tmp_path / name
    sdir.mkdir()
    store = Store()
    journal = attach(store, str(sdir / "store.journal"))
    rep = StandbyReplicator(
        store, journal, "http://127.0.0.1:1", poll_interval=0.02
    )
    return store, journal, rep


def _v99_snapshot_bytes():
    body = json.dumps({"objects": [], "rv": 1}).encode()
    header = json.dumps({
        "format": "kube-throttler-snapshot",
        "version": 99,
        "sha256": hashlib.sha256(body).hexdigest(),
        "length": len(body),
    }).encode()
    return header + b"\n" + body + b"\n"


class TestReplicationSkew:
    def test_bootstrap_unsupported_snapshot_fails_fast(self, tmp_path, monkeypatch):
        _, journal, rep = _standby(tmp_path)
        blob = _v99_snapshot_bytes()
        calls = []

        def fake_get(path):
            calls.append(path)
            return 200, blob, {PROTO_HEADER: "%d.%d" % local_proto_version()}

        monkeypatch.setattr(rep, "_get", fake_get)
        t0 = time.monotonic()
        try:
            assert rep.bootstrap(deadline_s=30.0) is False
            # deterministic refusal: ONE fetch, no retry-until-deadline
            # (every retry would fetch the same bytes and then report a
            # generic timeout instead of the named version)
            assert time.monotonic() - t0 < 5.0
            assert calls == ["/v1/replication/snapshot"]
            assert rep.format_refused == 1
            assert "unsupported snapshot version" in rep.format_refused_reason
            assert "99" in rep.format_refused_reason
            state, detail = rep.health_state()
            assert state == "down"
            assert "format refused" in detail["error"]
            assert "99" in detail["error"]
        finally:
            journal.close()

    def test_bootstrap_incompatible_proto_major_refused(self, tmp_path, monkeypatch):
        _, journal, rep = _standby(tmp_path)

        monkeypatch.setattr(
            rep, "_get", lambda path: (200, b"", {PROTO_HEADER: "99.0"})
        )
        try:
            assert rep.bootstrap(deadline_s=30.0) is False
            assert "incompatible major" in rep.format_refused_reason
            assert "99.0" in rep.format_refused_reason
            assert rep.health_state()[0] == "down"
        finally:
            journal.close()

    def test_poll_refuses_major_before_offset_advances(self, tmp_path, monkeypatch):
        _, journal, rep = _standby(tmp_path)
        line = json.dumps(
            {"type": "ADDED", "kind": "Namespace", "object": NS_OBJ}
        ).encode() + b"\n"
        monkeypatch.setattr(
            rep, "_get",
            lambda path: (200, line, {PROTO_HEADER: "99.0", "X-KT-Position": "64"}),
        )
        try:
            with pytest.raises(OSError, match="replication refused"):
                rep.poll_once()
            assert rep.consumed_offset() == 0  # nothing half-applied
            assert rep.format_refused >= 1
            assert rep.health_state()[0] == "down"
        finally:
            journal.close()

    def test_missing_or_malformed_stamp_is_baseline_not_refusal(self, tmp_path):
        _, journal, rep = _standby(tmp_path)
        try:
            assert rep._proto_refusal({}) is None
            assert rep._proto_refusal({PROTO_HEADER: "banana"}) is None
            assert rep._proto_refusal(
                {PROTO_HEADER: "%d.7" % PROTO_MAJOR}
            ) is None
            assert rep._proto_refusal({PROTO_HEADER: "99.0"}) is not None
        finally:
            journal.close()

    def test_unknown_control_line_in_stream_refused(self, tmp_path):
        _, journal, rep = _standby(tmp_path)
        data = (
            json.dumps({"type": "ADDED", "kind": "Namespace", "object": NS_OBJ})
            + "\n"
            + json.dumps({"type": "QUORUM", "op": "begin", "minReader": "2.0"})
            + "\n"
        ).encode()
        try:
            with pytest.raises(JournalFormatError):
                rep._apply_lines(data)
            assert "QUORUM" in rep.format_refused_reason
            assert rep.lines_skipped == 0  # refused, NOT corruption-skipped
            assert rep.health_state()[0] == "down"
        finally:
            journal.close()

    def test_replication_server_stamps_proto(self, tmp_path):
        ldir = tmp_path / "leader"
        ldir.mkdir()
        store = Store()
        journal = attach(store, str(ldir / "store.journal"))
        store.create_namespace(Namespace("default"))
        from kube_throttler_tpu.engine.replication import FencingEpoch

        source = ReplicationSource(str(ldir), journal, FencingEpoch(str(ldir)))
        server = ReplicationServer(source)
        server.start()
        try:
            from http.client import HTTPConnection

            conn = HTTPConnection("127.0.0.1", server.port, timeout=5.0)
            conn.request("GET", "/v1/replication/status")
            resp = conn.getresponse()
            resp.read()
            stamp = resp.getheader(PROTO_HEADER)
            conn.close()
            assert stamp == "%d.%d" % local_proto_version()
        finally:
            server.stop()
            journal.close()

    def test_slice_stream_stamps_and_refuses_major(self):
        blob = b"x" * 5000
        source = SliceChunkSource(blob, max_chunk=2048)
        sink = SliceChunkSink()
        while not sink.done:
            sink.feed(source.chunk(sink.offset(), sink.sha_hex()))
        assert sink.payload() == blob
        # a chunk stamped with a foreign major aborts back to the source
        bad = source.chunk(0)
        bad["proto"] = [99, 0]
        with pytest.raises(ReplicationDiverged, match="incompatible major"):
            SliceChunkSink().feed(bad)
        # an UNSTAMPED chunk is the pre-versioning baseline: accepted
        old = source.chunk(0)
        del old["proto"]
        assert SliceChunkSink().feed(old) == 2048


# --------------------------------------------------------------------------
# supervisor crash-loop guard + build_info exposition
# --------------------------------------------------------------------------


class TestRestartBackoff:
    def _supervisor(self):
        front = AdmissionFront(2)
        return ShardSupervisor(front, use_device=False,
                               restart_backoff=0.25, restart_backoff_cap=4.0)

    def test_backoff_grows_and_resets(self):
        sup = self._supervisor()
        delays = [sup._restart_delay(0) for _ in range(6)]
        assert all(0.0 < d <= 4.0 for d in delays)
        # jittered-exponential: by the 5th consecutive death the delay
        # has left the base band; shard 1's pacing is independent
        assert max(delays) > 0.5
        assert delays[-1] >= delays[0]
        assert sup.backoff_seconds()[0] == delays[-1]
        assert sup.backoff_seconds()[1] == 0.0
        sup._reset_backoff(0)
        assert sup.backoff_seconds()[0] == 0.0
        # post-reset the guard restarts from the base band
        assert sup._restart_delay(0) <= 0.5

    def test_backoff_metric_exported(self):
        sup = self._supervisor()
        sup._restart_delay(1)
        registry = Registry()
        register_build_metrics(registry, role="front", front=sup.front)
        text = registry.exposition()
        assert "kube_throttler_shard_restart_backoff_seconds" in text
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("kube_throttler_shard_restart_backoff_seconds")
            and 'shard="1"' in ln
        )
        assert float(line.rsplit(" ", 1)[1]) > 0.0


class TestBuildInfo:
    def test_build_info_row_for_this_process(self):
        registry = Registry()
        register_build_metrics(registry, role="worker")
        text = registry.exposition()
        assert "kube_throttler_build_info" in text
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("kube_throttler_build_info")
        )
        assert BUILD_ID in line
        assert 'role="worker"' in line
        assert "%d.%d" % local_proto_version() in line

    def test_per_shard_rows_and_mismatch_counter(self):
        from types import SimpleNamespace

        handle = SimpleNamespace(
            negotiated_proto=(1, 0), negotiated_caps=frozenset({"build-info"}),
            peer_build="kube-throttler-tpu/old", version_mismatches=3,
        )
        front = SimpleNamespace(
            n_shards=1, shards={0: handle}, supervisor_ref=None
        )
        registry = Registry()
        register_build_metrics(registry, role="front", front=front)
        text = registry.exposition()
        assert 'proto="1.0"' in text
        assert "kube-throttler-tpu/old" in text
        mline = next(
            ln for ln in text.splitlines()
            if ln.startswith("kube_throttler_shard_version_mismatch_total")
            and 'shard="0"' in ln
        )
        assert float(mline.rsplit(" ", 1)[1]) == 3.0


# --------------------------------------------------------------------------
# wire handshake over real TCP: refusal, fallback, and skew equivalence
# --------------------------------------------------------------------------

from test_net_transport import (  # noqa: E402
    WorkerRig,
    build_tcp_front,
    settle,
    teardown_tcp_front,
    wait_until,
)


class TestWireHandshake:
    def test_incompatible_major_typed_refusal_no_crash_loop(self, monkeypatch):
        import kube_throttler_tpu.sharding.ipc as ipc_mod

        real_hello = ipc_mod.local_hello
        monkeypatch.setattr(
            ipc_mod, "local_hello",
            lambda env=None: {"proto": [99, 0], "caps": [], "build": "test-skew"},
        )
        rig = WorkerRig()
        try:
            skewed = rig.client()
            wait_until(lambda: skewed.version_refused is not None,
                       msg="typed refusal")
            assert "VersionMismatch" in skewed.version_refused
            assert "99" in skewed.version_refused
            assert skewed.version_mismatches >= 1
            with pytest.raises(VersionMismatch):
                skewed.request("ping", timeout=2.0)
            assert rig.core.version_mismatches >= 1
            # the refusal killed ONE lane, not the process: a compatible
            # client handshakes and serves on the same listener
            monkeypatch.setattr(ipc_mod, "local_hello", real_hello)
            healthy = rig.client()
            wait_until(lambda: healthy.negotiated_proto is not None,
                       msg="healthy handshake")
            assert healthy.negotiated_proto == PROTO_VERSION
            assert healthy.negotiated_caps == CAPABILITIES
            assert healthy.peer_build == BUILD_ID
            assert healthy.request("ping", timeout=5.0)
        finally:
            rig.close()

    def test_front_health_names_the_version_mismatch(self, monkeypatch):
        import kube_throttler_tpu.sharding.ipc as ipc_mod

        monkeypatch.setattr(
            ipc_mod, "local_hello",
            lambda env=None: {"proto": [99, 0], "caps": [], "build": "test-skew"},
        )
        rig = WorkerRig()
        front = AdmissionFront(1)
        try:
            front.attach_shard(0, rig.client())
            wait_until(
                lambda: front.shards[0].version_refused is not None,
                msg="refusal recorded",
            )
            state, detail = front._shards_health()
            assert state != "ok"
            assert "version-mismatch" in detail["shard-0"]
            assert "99" in detail["shard-0"]  # the refusal names the major
        finally:
            front.stop()
            rig.close()

    @pytest.mark.parametrize("seed", [0])
    def test_masked_caps_fleet_matches_oracle(self, seed, monkeypatch):
        """A fleet rolled back to the zero-capability 1.0 baseline
        (KT_PROTO_CAPS_MASK="") must produce verdicts identical to the
        full-capability fleet and the single-process oracle: capabilities
        gate ENCODINGS, never admission semantics."""
        from test_sharding import apply_population, seeded_population

        ops = seeded_population(seed)
        oracle_store = Store()
        apply_population(oracle_store, ops)
        oracle = H.build_plugin(oracle_store)
        oracle.run_pending_once()

        monkeypatch.setenv("KT_PROTO_CAPS_MASK", "")
        old_front, old_cores, old_servers = build_tcp_front(2)
        monkeypatch.delenv("KT_PROTO_CAPS_MASK")
        new_front, new_cores, new_servers = build_tcp_front(2)
        try:
            for h in old_front.shards.values():
                wait_until(lambda h=h: h.negotiated_proto is not None,
                           msg="old fleet handshake")
                assert h.negotiated_caps == frozenset()
            for h in new_front.shards.values():
                wait_until(lambda h=h: h.negotiated_proto is not None,
                           msg="new fleet handshake")
                assert h.negotiated_caps == CAPABILITIES
            for front in (old_front, new_front):
                apply_population(front.store, ops)
                settle(front)
            for pod in oracle_store.list_pods():
                want = oracle.pre_filter(pod)
                for label, front in (("masked", old_front), ("full", new_front)):
                    got = front.pre_filter(pod)
                    assert got.code == want.code, (label, pod.key, got.reasons)
                    assert H.normalized_reasons(got.reasons) == (
                        H.normalized_reasons(want.reasons)
                    ), (label, pod.key)
        finally:
            oracle.stop()
            teardown_tcp_front(old_front, old_cores, old_servers)
            teardown_tcp_front(new_front, new_cores, new_servers)

    def test_reservations_survive_masked_caps(self, monkeypatch):
        monkeypatch.setenv("KT_PROTO_CAPS_MASK", "")
        front, cores, servers = build_tcp_front(2)
        try:
            front.store.create_namespace(Namespace("default"))
            for i in range(4):
                front.store.create_throttle(H.make_throttle(i))
            settle(front)
            held = [
                make_pod(f"r{i}", labels={"grp": "g0"}, requests={"cpu": "600m"})
                for i in range(2)
            ]
            for pod in held:
                assert front.reserve(pod).is_success()
            probe = make_pod("probe", labels={"grp": "g0"},
                             requests={"cpu": "600m"})
            throttled = front.pre_filter(probe)  # 1.2 reserved > t0's 1 cpu
            for pod in held:
                front.unreserve(pod)
            released = front.pre_filter(probe)
            # the reserves were visible downstream and the unreserves undid
            # them — two-phase reservation does not ride any minor capability
            assert not throttled.is_success()
            assert released.is_success()
        finally:
            teardown_tcp_front(front, cores, servers)
