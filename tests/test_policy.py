"""Preemption & policy engine (policy/, ops/victim_select.py, journal
PREEMPT lines, scheduler preemption hook + rank-aware placement, workqueue
re-prioritize, policy-weighted flip promotion).

The hypothesis equivalence property (batched kernel ≡ sequential oracle)
lives in tests/test_victim_property.py; the SIGKILL crash coverage for
``crash.preempt.partial_evict`` in tools/crashtest.py (smoke in
tests/test_crash_recovery.py). This file is the deterministic tier.
"""

from __future__ import annotations

import json
from datetime import datetime, timezone

import numpy as np
import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    AccelClassThreshold,
    LabelSelector,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.journal import (
    attach,
    rollback_uncommitted_preempts,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.engine.workqueue import RateLimitingQueue
from kube_throttler_tpu.ops.victim_select import victim_select
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args
from kube_throttler_tpu.policy import (
    EvictionUnit,
    PolicyEngine,
    PolicySpec,
    build_selection_problem,
    compute_gang_deficits,
    policy_spec_from_dict,
    rank_eviction_units,
    sequential_victim_select,
)
from kube_throttler_tpu.scheduler import Node, Scheduler
from kube_throttler_tpu.utils.clock import FakeClock


def _throttle(name, cpu_m, labels=None, accel=()):
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": f"{cpu_m}m"}),
            accel_class_thresholds=tuple(accel),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        LabelSelector(match_labels=labels or {"grp": name})
                    ),
                )
            ),
        ),
    )


PREEMPT_POLICY = {
    "name": "test",
    "preemptionEnabled": True,
    "minPriorityGap": 1,
    "classWeights": [{"accelClass": "gold", "weight": 2.0}],
}


def _setup(policies=None, nodes=None):
    store = Store()
    store.create_namespace(Namespace("default"))
    config = {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
    if policies is not None:
        config["policies"] = policies
    plugin = KubeThrottler(decode_plugin_args(config), store, use_device=True)
    sched = Scheduler(plugin, store, nodes=nodes)
    return store, plugin, sched


# ------------------------------------------------------------------ spec


class TestPolicySpec:
    def test_decode_and_defaults(self):
        spec = policy_spec_from_dict(PREEMPT_POLICY)
        assert spec.preemption_enabled and spec.min_priority_gap == 1
        assert spec.weight_for("gold") == 2.0
        assert spec.weight_for("silver") == 1.0  # default weight
        assert spec.weight_for(None) == 1.0
        assert spec.rank_aware_placement

    @pytest.mark.parametrize(
        "bad",
        [
            {"unknownKnob": 1},
            {"maxVictimsPerCycle": 0},
            {"preemptCooldownSeconds": -1},
            {"minPriorityGap": -2},
            {"defaultWeight": -0.5},
            {"classWeights": [{"weight": 1.0}]},
            {"classWeights": [{"accelClass": "a", "weight": -1.0}]},
            {"classWeights": [{"accelClass": "a", "typo": 1}]},
        ],
    )
    def test_decode_rejects(self, bad):
        with pytest.raises(ValueError):
            policy_spec_from_dict(bad)

    def test_promotion_priority_scales_weight_margin(self):
        spec = policy_spec_from_dict(PREEMPT_POLICY)
        assert spec.promotion_priority(["gold"]) == 100
        assert spec.promotion_priority(["silver"]) == 0
        assert spec.promotion_priority([]) == 0

    def test_activation_window_is_override_machinery(self):
        spec = PolicySpec(
            begin="2026-08-05T00:00:00Z", end="2026-08-05T12:00:00Z"
        )
        inside = datetime(2026, 8, 5, 6, tzinfo=timezone.utc)
        outside = datetime(2026, 8, 5, 13, tzinfo=timezone.utc)
        assert spec.is_active(inside)
        assert not spec.is_active(outside)
        # boundaries inclusive, like TemporaryThresholdOverride
        assert spec.is_active(datetime(2026, 8, 5, 12, tzinfo=timezone.utc))


class TestPolicyEngine:
    def test_first_active_wins_and_hot_swap(self):
        clock = FakeClock(datetime(2026, 8, 5, 6, tzinfo=timezone.utc))
        engine = PolicyEngine(
            specs=(
                PolicySpec(name="night", begin="2026-08-05T18:00:00Z"),
                PolicySpec(name="day", preemption_enabled=True),
            ),
            clock=clock,
        )
        assert engine.active().name == "day"
        clock.set(datetime(2026, 8, 5, 19, tzinfo=timezone.utc))
        assert engine.active().name == "night"  # window opened: first wins
        gen = engine.set_specs((PolicySpec(name="swapped"),))
        assert engine.active().name == "swapped"
        assert engine.set_specs(()) == gen + 1
        assert engine.active().name == "default"  # built-in fallback

    def test_bad_window_skipped(self):
        engine = PolicyEngine(
            specs=(
                PolicySpec(name="broken", begin="not-a-time"),
                PolicySpec(name="good"),
            )
        )
        assert engine.active().name == "good"


# ------------------------------------------------- ranking + oracle


class TestVictimRanking:
    def test_weight_then_priority_then_age_desc(self):
        units = [
            EvictionUnit(unit_key="heavy", pods=(), weight=2.0, priority=0, age_s=99),
            EvictionUnit(unit_key="young", pods=(), weight=1.0, priority=0, age_s=1),
            EvictionUnit(unit_key="old", pods=(), weight=1.0, priority=0, age_s=50),
            EvictionUnit(unit_key="hiprio", pods=(), weight=1.0, priority=3, age_s=99),
        ]
        order = [u.unit_key for u in rank_eviction_units(units)]
        # weight asc first, then priority asc, then age DESC (oldest first)
        assert order == ["old", "young", "hiprio", "heavy"]

    def test_unknown_age_ranks_oldest(self):
        units = [
            EvictionUnit(unit_key="known", pods=(), age_s=1e6),
            EvictionUnit(unit_key="unknown", pods=(), age_s=float("inf")),
        ]
        assert [u.unit_key for u in rank_eviction_units(units)][0] == "unknown"


class TestSequentialOracle:
    def test_skips_non_contributors_and_stops_early(self):
        deficit = np.array([2], dtype=np.int64)
        contrib = np.array([[0], [1], [0], [1], [5]], dtype=np.int64)
        ok, sel, rem = sequential_victim_select(deficit, contrib)
        assert ok and sel == [1, 3] and rem[0] == 0

    def test_infeasible_reports_remaining(self):
        deficit = np.array([10], dtype=np.int64)
        contrib = np.array([[3], [3]], dtype=np.int64)
        ok, sel, rem = sequential_victim_select(deficit, contrib)
        assert not ok and sel == [0, 1] and rem[0] == 4

    def test_victim_cap(self):
        deficit = np.array([3], dtype=np.int64)
        contrib = np.array([[1], [1], [1]], dtype=np.int64)
        ok, sel, _ = sequential_victim_select(deficit, contrib, max_victims=2)
        assert not ok and sel == [0, 1]

    def test_inputs_unmutated(self):
        deficit = np.array([2, 2], dtype=np.int64)
        contrib = np.array([[2, 2]], dtype=np.int64)
        sequential_victim_select(deficit, contrib)
        assert deficit.tolist() == [2, 2]


class TestKernelOracleSeeded:
    """Deterministic mini-twin of tests/test_victim_property.py (which
    needs hypothesis): 40 seeded random selection problems, batched
    kernel ≡ sequential oracle on BOTH the verdict and the selected set,
    caps included."""

    def test_randomized_problems(self):
        import random

        rng = random.Random(20260805)
        for case in range(40):
            n = rng.randint(1, 40)
            m = rng.randint(1, 8)
            cap = rng.choice([0, 0, rng.randint(1, n)])
            contrib = np.array(
                [
                    [rng.choice([0, 0, 0, 1, 2, 5, 100, 333, 1000]) for _ in range(m)]
                    for _ in range(n)
                ],
                dtype=np.int64,
            )
            deficit = np.array(
                [rng.choice([0, 1, 4, 250, 900, 2000]) for _ in range(m)],
                dtype=np.int64,
            )
            ok_s, sel_s, rem_s = sequential_victim_select(
                deficit, contrib, max_victims=cap
            )
            sel_k, ok_k, rem_k = victim_select(contrib, deficit, max_victims=cap)
            got = list(np.nonzero(np.asarray(sel_k))[0])
            assert (bool(np.asarray(ok_k)), got) == (ok_s, sel_s), (
                f"case {case}: kernel=({bool(np.asarray(ok_k))}, {got}) "
                f"oracle=({ok_s}, {sel_s}) cap={cap}\n{deficit}\n{contrib}"
            )
            taken = np.asarray(rem_k)
            assert taken.tolist() == rem_s.tolist()

    def test_padded_rows_and_dims_are_inert(self):
        deficit = np.array([5, 0, 0, 0], dtype=np.int64)
        contrib = np.zeros((8, 4), dtype=np.int64)
        contrib[2, 0] = 5
        sel, ok, _ = victim_select(contrib, deficit, max_victims=0)
        assert bool(np.asarray(ok))
        assert list(np.nonzero(np.asarray(sel))[0]) == [2]


# ------------------------------------------------------------- deficits


class TestGangDeficits:
    def _stack(self):
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        store.create_throttle(_throttle("t1", 400, labels={"grp": "a"}))
        for i in range(4):
            store.create_pod(
                make_pod(
                    f"res{i}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    node_name="node-1", phase="Running", priority=0,
                )
            )
        sched.run_until_idle()
        return store, plugin, sched

    def _kcs(self, plugin):
        return (
            ("throttle", plugin.throttle_ctr),
            ("clusterthrottle", plugin.cluster_throttle_ctr),
        )

    def test_exact_capacity_deficit(self):
        store, plugin, sched = self._stack()
        members = [
            make_pod(f"m{i}", labels={"grp": "a"}, requests={"cpu": "100m"})
            for i in range(2)
        ]
        deficits = compute_gang_deficits(members, self._kcs(plugin))
        assert deficits == {("throttle", "default/t1", "cpu"): 200}
        plugin.stop()

    def test_member_exceeds_is_unpreemptable(self):
        store, plugin, sched = self._stack()
        members = [make_pod("big", labels={"grp": "a"}, requests={"cpu": "500m"})]
        assert compute_gang_deficits(members, self._kcs(plugin)) is None
        plugin.stop()

    def test_no_deficit_when_group_fits(self):
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        store.create_throttle(_throttle("t1", 400, labels={"grp": "a"}))
        members = [make_pod("m0", labels={"grp": "a"}, requests={"cpu": "100m"})]
        assert compute_gang_deficits(members, self._kcs(plugin)) == {}
        plugin.stop()

    def test_selection_problem_flattens_contribs(self):
        deficits = {("throttle", "default/t1", "cpu"): 200}
        unit = EvictionUnit(unit_key="u", pods=())
        unit.add_pod_contrib(
            "throttle", "default/t1",
            make_pod("v", requests={"cpu": "100m"}),
        )
        dims, deficit, contrib = build_selection_problem(deficits, [unit])
        assert dims == [("throttle", "default/t1", "cpu")]
        assert deficit.tolist() == [200] and contrib.tolist() == [[100]]


# ------------------------------------------------------ e2e preemption


class TestGangPreemption:
    def _residents(self, store, gang_first=True):
        """One throttle (400m) saturated by 4 running 100m pods: a gang
        of two (created FIRST — oldest, so victim rank prefers it) plus
        two singles."""
        store.create_throttle(_throttle("t1", 400, labels={"grp": "a"}))
        keys = {"gang": [], "single": []}
        for i in range(2):
            p = make_pod(
                f"vg{i}", labels={"grp": "a"}, requests={"cpu": "100m"},
                node_name="node-1", phase="Running", priority=0,
                group="victims", group_size=2,
            )
            store.create_pod(p)
            keys["gang"].append(p.key)
        for i in range(2):
            p = make_pod(
                f"vs{i}", labels={"grp": "a"}, requests={"cpu": "100m"},
                node_name="node-1", phase="Running", priority=1,
            )
            store.create_pod(p)
            keys["single"].append(p.key)
        return keys

    def test_high_priority_gang_preempts_and_admits(self):
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        keys = self._residents(store)
        sched.run_until_idle()
        for r in range(2):
            store.create_pod(
                make_pod(
                    f"hi-r{r}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    group="hi", group_size=2, priority=5,
                )
            )
        sched.run_until_idle()
        # the gang admitted — every rank bound
        for r in range(2):
            assert store.get_pod("default", f"hi-r{r}").spec.node_name != ""
        # exactly the deficit's worth of victims evicted (200m = 2 pods)
        live = {p.key for p in store.list_pods("default")}
        assert plugin.preempt.cycles_total == 1
        assert plugin.preempt.victims_total == 2
        # whole-gang atomicity: the victim gang is all-present or all-gone
        gang_present = [k in live for k in keys["gang"]]
        assert all(gang_present) or not any(gang_present)
        plugin.stop()

    def test_victim_gang_evicts_whole_and_ledger_rolls_back(self):
        """Force the gang unit to be chosen (it is the only eligible
        victim class) and pin: both members die, none half-evicted, and a
        pending ledger record for it is rolled back."""
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        store.create_throttle(_throttle("t1", 200, labels={"grp": "a"}))
        for i in range(2):
            store.create_pod(
                make_pod(
                    f"vg{i}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    node_name="node-1", phase="Running", priority=0,
                    group="victims", group_size=2,
                )
            )
        sched.run_until_idle()
        for r in range(2):
            store.create_pod(
                make_pod(
                    f"hi-r{r}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    group="hi", group_size=2, priority=5,
                )
            )
        sched.run_until_idle()
        live = {p.key for p in store.list_pods("default")}
        assert "default/vg0" not in live and "default/vg1" not in live
        assert store.get_pod("default", "hi-r0").spec.node_name != ""
        plugin.stop()

    def test_disabled_policy_never_evicts(self):
        store, plugin, sched = _setup()  # no policies: built-in default
        self._residents(store)
        sched.run_until_idle()
        for r in range(2):
            store.create_pod(
                make_pod(
                    f"hi-r{r}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    group="hi", group_size=2, priority=5,
                )
            )
        sched.run_until_idle(max_cycles=60)
        assert plugin.preempt.victims_total == 0
        assert len(store.list_pods("default")) == 6  # nobody evicted
        assert store.get_pod("default", "hi-r0").spec.node_name == ""
        plugin.stop()

    def test_priority_gap_protects_equal_priority_work(self):
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        store.create_throttle(_throttle("t1", 200, labels={"grp": "a"}))
        for i in range(2):
            store.create_pod(
                make_pod(
                    f"res{i}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    node_name="node-1", phase="Running", priority=5,
                )
            )
        sched.run_until_idle()
        for r in range(2):
            store.create_pod(
                make_pod(
                    f"hi-r{r}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    group="hi", group_size=2, priority=5,  # equal, gap 1
                )
            )
        sched.run_until_idle(max_cycles=60)
        assert plugin.preempt.victims_total == 0
        assert plugin.preempt.infeasible_total >= 1
        plugin.stop()

    def test_cooldown_skips_repeat_cycles(self):
        store, plugin, sched = _setup(
            policies=[dict(PREEMPT_POLICY, preemptCooldownSeconds=3600.0)]
        )
        members = [make_pod("m0", labels={"grp": "a"}, priority=5)]
        first = plugin.preempt.preempt_for_gang("default/g", members, mono=100.0)
        again = plugin.preempt.preempt_for_gang("default/g", members, mono=101.0)
        assert first["reason"] != "cooldown"
        assert again["reason"] == "cooldown"
        assert plugin.preempt.cooldown_skipped_total == 1
        plugin.stop()

    def test_metrics_families_export(self):
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        self._residents(store)
        sched.run_until_idle()
        for r in range(2):
            store.create_pod(
                make_pod(
                    f"hi-r{r}", labels={"grp": "a"}, requests={"cpu": "100m"},
                    group="hi", group_size=2, priority=5,
                )
            )
        sched.run_until_idle()
        text = plugin.metrics_registry.exposition()
        assert "kube_throttler_preempt_cycles_total 1" in text
        assert "kube_throttler_preempt_victims_total 2" in text
        assert "kube_throttler_preempt_select_duration_seconds_count" in text
        plugin.stop()


# --------------------------------------------------- journal PREEMPT


class TestPreemptJournal:
    def _evicted_store(self, tmp_path, commit: bool):
        from kube_throttler_tpu.api.serialization import object_to_dict

        store = Store()
        path = str(tmp_path / "store.journal")
        journal = attach(store, path)
        store.create_namespace(Namespace("default"))
        victim = make_pod(
            "victim", labels={"grp": "a"}, node_name="node-1", phase="Running"
        )
        store.create_pod(victim)
        journal.append_preempt(
            "begin", "default/p#1",
            victims=[victim.key], objects=[object_to_dict(victim)],
        )
        store.delete_pod("default", "victim")
        if commit:
            journal.append_preempt("commit", "default/p#1")
        journal.close()
        return path

    def test_uncommitted_preempt_rolls_back_to_zero_evictions(self, tmp_path):
        path = self._evicted_store(tmp_path, commit=False)
        store2 = Store()
        journal2 = attach(store2, path)
        # attach's full replay rolled the open preemption back: the
        # victim is restored, the entry stamped rollback
        assert store2.get_pod("default", "victim").name == "victim"
        assert journal2.preempts_rolled_back == 1
        assert journal2.preempt_victims_restored == 1
        assert journal2.preempt_ops["default/p#1"]["op"] == "rollback"
        journal2.close()
        # idempotent: a THIRD replay sees the rollback stamp and restores
        # nothing new (the restored ADDED line re-journaled the victim)
        store3 = Store()
        journal3 = attach(store3, path)
        assert journal3.preempts_rolled_back == 0
        assert store3.get_pod("default", "victim").name == "victim"
        journal3.close()

    def test_committed_preempt_stays_evicted(self, tmp_path):
        path = self._evicted_store(tmp_path, commit=True)
        store2 = Store()
        journal2 = attach(store2, path)
        assert journal2.preempts_rolled_back == 0
        with pytest.raises(KeyError):
            store2.get_pod("default", "victim")
        journal2.close()

    def test_compaction_re_emits_open_preempt(self, tmp_path):
        from kube_throttler_tpu.api.serialization import object_to_dict

        store = Store()
        path = str(tmp_path / "store.journal")
        journal = attach(store, path)
        store.create_namespace(Namespace("default"))
        victim = make_pod("victim", node_name="node-1", phase="Running")
        store.create_pod(victim)
        journal.append_preempt(
            "begin", "default/p#1",
            victims=[victim.key], objects=[object_to_dict(victim)],
        )
        store.delete_pod("default", "victim")
        # compaction rewrites the log from the store — the open (begin)
        # marker must survive the rewrite WITH its rollback payload
        journal.compact()
        journal.close()
        with open(path) as f:
            ops = [json.loads(line) for line in f if '"PREEMPT"' in line]
        assert [o["op"] for o in ops] == ["begin"]
        assert ops[0]["victims"] == ["default/victim"]
        assert ops[0]["victimObjects"]
        # and a post-compaction replay still rolls back to zero evictions
        store2 = Store()
        journal2 = attach(store2, path)
        assert store2.get_pod("default", "victim").name == "victim"
        assert journal2.preempts_rolled_back == 1
        journal2.close()

    def test_open_preempts_probe_and_snapshot_payload(self, tmp_path):
        from kube_throttler_tpu.api.serialization import object_to_dict
        from kube_throttler_tpu.engine.snapshot import SnapshotManager, load_snapshot

        store = Store()
        path = str(tmp_path / "store.journal")
        journal = attach(store, path)
        store.create_namespace(Namespace("default"))
        victim = make_pod("v", node_name="node-1", phase="Running")
        store.create_pod(victim)
        journal.append_preempt(
            "begin", "p#9", victims=[victim.key], objects=[object_to_dict(victim)]
        )
        assert set(journal.open_preempts()) == {"p#9"}
        snapshotter = SnapshotManager(str(tmp_path), store)
        snapshotter.journal = journal
        snap_path = snapshotter.write(reason="test")
        payload = load_snapshot(snap_path)
        assert "p#9" in (payload.get("preempts") or {})
        journal.append_preempt("commit", "p#9")
        assert journal.open_preempts() == {}
        journal.close()

    def test_rollback_merges_snapshot_extras(self, tmp_path):
        """Tail-mode shape: the journal never saw the begin line — the
        snapshot's open-preempt payload alone drives the restore."""
        from kube_throttler_tpu.api.serialization import object_to_dict

        store = Store()
        journal = attach(store, str(tmp_path / "j"))
        store.create_namespace(Namespace("default"))
        victim = make_pod("v", node_name="node-1", phase="Running")
        extras = {
            "p#7": {
                "op": "begin",
                "victims": [victim.key],
                "victimObjects": [object_to_dict(victim)],
            }
        }
        rolled, restored = rollback_uncommitted_preempts(
            store, journal, extra_ops=extras
        )
        assert (rolled, restored) == (1, 1)
        assert store.get_pod("default", "v").name == "v"
        journal.close()

    def test_standby_forwards_preempt_lines(self, tmp_path):
        from kube_throttler_tpu.engine.replication import StandbyReplicator

        store = Store()
        journal = attach(store, str(tmp_path / "j"))
        rep = StandbyReplicator(store, journal, "http://127.0.0.1:1")
        line = json.dumps(
            {"type": "PREEMPT", "op": "begin", "id": "p#3", "victims": ["d/x"]}
        ).encode()
        applied = rep._apply_lines(line + b"\n")
        assert applied == 0 and rep.lines_skipped == 0
        assert journal.preempt_ops["p#3"]["op"] == "begin"
        journal.close()


# ------------------------------------------- workqueue re-prioritize


class TestWorkqueueReprioritize:
    def test_update_reorders_queued_item(self):
        q = RateLimitingQueue("t")
        q.add_all_priority(["a"], priorities={"a": 1})
        q.add_all_priority(["b"], priorities={"b": 3})
        assert len(q) == 2
        q.add_all_priority(["a"], priorities={"a": 5})  # the update
        assert len(q) == 2  # still queued once (lane-global dedup)
        assert q.get() == "a"  # 5 > 3: the update took effect
        q.done("a")
        assert q.get() == "b"
        q.done("b")
        # the superseded heap entry drains as nothing
        assert q.try_get() is None
        q.shut_down()

    def test_downgrade_also_reorders(self):
        q = RateLimitingQueue("t")
        q.add_all_priority(["a"], priorities={"a": 5})
        q.add_all_priority(["b"], priorities={"b": 3})
        q.add_all_priority(["a"], priorities={"a": 1})
        assert q.get() == "b"
        q.done("b")
        assert q.get() == "a"
        q.done("a")
        q.shut_down()

    def test_same_priority_readd_is_noop(self):
        q = RateLimitingQueue("t")
        q.add_all_priority(["a", "b"], priorities={"a": 2, "b": 2})
        q.add_all_priority(["a"], priorities={"a": 2})
        # age order preserved: the no-op re-add must not reset a's seq
        assert q.get() == "a"
        q.done("a")
        q.shut_down()

    def test_timeout_after_stale_only_heap(self):
        q = RateLimitingQueue("t")
        q.add_all_priority(["a"], priorities={"a": 1})
        q.add_all_priority(["a"], priorities={"a": 5})
        assert q.get() == "a"
        q.done("a")
        with pytest.raises(TimeoutError):
            q.get(timeout=0.05)
        q.shut_down()

    def test_processing_reprioritize_latest_wins(self):
        q = RateLimitingQueue("t")
        q.add("a")
        assert q.get() == "a"  # in processing
        q.add_all_priority(["a"], priorities={"a": 2})
        q.add_all_priority(["a"], priorities={"a": 7})
        q.add_all_priority(["b"], priorities={"b": 5})
        q.done("a")  # re-queued hi at the LATEST recorded priority (7)
        assert q.get() == "a"
        q.done("a")
        q.shut_down()


class TestSchedulerPriorityUpdate:
    def test_annotation_update_reorders_parked_pods(self):
        from dataclasses import replace

        from kube_throttler_tpu.api.pod import PRIORITY_ANNOTATION

        store, plugin, sched = _setup()
        store.create_throttle(
            Throttle(
                name="t1",
                spec=ThrottleSpec(
                    throttler_name="kube-throttler",
                    threshold=ResourceAmount.of(pod=0),
                    selector=ThrottleSelector(
                        selector_terms=(
                            ThrottleSelectorTerm(
                                LabelSelector(match_labels={"grp": "a"})
                            ),
                        )
                    ),
                ),
            )
        )
        store.create_pod(make_pod("old-low", labels={"grp": "a"}, priority=0))
        store.create_pod(make_pod("young-high", labels={"grp": "a"}, priority=5))
        assert sched.run_until_idle(max_cycles=50) == 0
        # the annotation update: old-low becomes the highest priority
        pod = store.get_pod("default", "old-low")
        ann = dict(pod.annotations)
        ann[PRIORITY_ANNOTATION] = "9"
        store.update_pod(replace(pod, annotations=ann))
        thr = store.get_throttle("default", "t1")
        store.update_throttle_spec(
            replace(thr, spec=replace(thr.spec, threshold=ResourceAmount.of(pod=1)))
        )
        assert sched.run_until_idle() == 1
        assert store.get_pod("default", "old-low").spec.node_name != ""
        assert store.get_pod("default", "young-high").spec.node_name == ""
        plugin.stop()


# ------------------------------------- policy-weighted flip promotion


class TestPolicyFlipPromotion:
    def test_flip_priorities_from_accel_weights(self):
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        store.create_throttle(
            _throttle(
                "gold-t", 400, labels={"grp": "g"},
                accel=(AccelClassThreshold("gold", ResourceAmount.of(pod=3)),),
            )
        )
        store.create_throttle(_throttle("plain-t", 400, labels={"grp": "p"}))
        pri = plugin.throttle_ctr.flip_priorities(
            ["default/gold-t", "default/plain-t", "default/ghost"]
        )
        assert pri == {"default/gold-t": 100}
        plugin.stop()

    def test_weighted_promotion_orders_hi_lane(self):
        store, plugin, sched = _setup(policies=[dict(PREEMPT_POLICY)])
        ctr = plugin.throttle_ctr
        ctr.workqueue.add_all_priority(
            ["default/plain"], priorities=ctr.flip_priorities(["default/plain"])
        )
        store.create_throttle(
            _throttle(
                "gold-t", 400, labels={"grp": "g"},
                accel=(AccelClassThreshold("gold", ResourceAmount.of(pod=3)),),
            )
        )
        ctr.workqueue.add_all_priority(
            ["default/gold-t"],
            priorities=ctr.flip_priorities(["default/gold-t"]),
        )
        # the gold throttle enqueued LATER but drains FIRST (weight 2.0)
        assert ctr.workqueue.get() == "default/gold-t"
        ctr.workqueue.done("default/gold-t")
        plugin.stop()


# --------------------------------------------- rank-aware placement


class TestRankAwarePlacement:
    """Three nodes: n0 has room only for SMALL ranks, n1/n2 are roomy.
    Gang ranks request [1.5, 0.5, 1.5] cpu (name order == admission
    order). First-fit fragments rank 1 back onto n0; contiguity keeps it
    with rank 0 on n1 — the topology-adjacent placement."""

    def _nodes(self):
        return [
            Node("n0", allocatable={"cpu": "1"}),
            Node("n1", allocatable={"cpu": "4"}),
            Node("n2", allocatable={"cpu": "4"}),
        ]

    def _gang(self, store):
        for r, cpu in enumerate(["1500m", "500m", "1500m"]):
            store.create_pod(
                make_pod(
                    f"g-r{r}", labels={"grp": "a"}, requests={"cpu": cpu},
                    group="g", group_size=3,
                )
            )

    def test_gang_lands_contiguous(self):
        store, plugin, sched = _setup(nodes=self._nodes())
        self._gang(store)
        sched.run_until_idle()
        placed = [
            store.get_pod("default", f"g-r{r}").spec.node_name for r in range(3)
        ]
        assert placed == ["n1", "n1", "n1"]
        plugin.stop()

    def test_policy_can_disable_contiguity(self):
        store, plugin, sched = _setup(
            policies=[{"name": "flat", "rankAwarePlacement": False}],
            nodes=self._nodes(),
        )
        assert sched._placement_rank_aware() is False
        self._gang(store)
        sched.run_until_idle()
        placed = [
            store.get_pod("default", f"g-r{r}").spec.node_name for r in range(3)
        ]
        # original first-fit: the small rank fragments back onto n0
        assert placed == ["n1", "n0", "n1"]
        plugin.stop()
