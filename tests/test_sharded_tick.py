"""The LIVE multi-chip serving path: DeviceStateManager.full_tick_sharded /
plugin.full_tick_sharded / POST /v1/tick on the 8-device virtual CPU mesh.

On a static (fully reconciled) store the fused tick's classification must
agree cell-for-cell with the dense written-status check (check_batch_all),
and its recomputed ``used`` must equal the written ``status.used`` — the
same SPMD partitioner TPU uses, so mesh-placement bugs surface here.
"""

import random
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

from kube_throttler_tpu.api.pod import Namespace, make_pod
from kube_throttler_tpu.api.types import (
    LabelSelector,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.parallel import make_mesh
from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args


def rfc(dt):
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _throttle(name, groups=8, i=0, pod_cap=None, cpu=None, overrides=()):
    threshold = ResourceAmount.of(
        pod=pod_cap, requests={"cpu": cpu} if cpu else None
    )
    return Throttle(
        name=name,
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=threshold,
            temporary_threshold_overrides=overrides,
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        pod_selector=LabelSelector(
                            match_labels={"grp": f"g{i % groups}"}
                        )
                    ),
                )
            ),
        ),
    )


@pytest.fixture()
def stack():
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
        start_workers=False,
    )
    store.create_namespace(Namespace("default"))
    return store, plugin


def _populate(store, rng, n_thr=24, n_pods=96, groups=8):
    for i in range(n_thr):
        kind = i % 3
        if kind == 0:
            thr = _throttle(f"t{i}", groups, i, cpu="100")  # wide open
        elif kind == 1:
            thr = _throttle(f"t{i}", groups, i, cpu=f"{(i % 5 + 1)}00m")  # tight
        else:
            thr = _throttle(f"t{i}", groups, i, pod_cap=(i % 7) + 1)
        store.create_throttle(thr)
    for i in range(n_pods):
        store.create_pod(
            make_pod(
                f"p{i}",
                labels={"grp": f"g{rng.randrange(groups)}"},
                requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
                node_name="node-1",
                phase="Running",
            )
        )
    # a guaranteed 'insufficient' cell on a dedicated group: used 800m of
    # 1000m, plus a pending 300m pod (alone ≤ threshold, used+pod over it)
    ins = _throttle("t-ins", 1, 0, cpu="1000m")
    ins_sel = ThrottleSelector(
        selector_terms=(
            ThrottleSelectorTerm(
                pod_selector=LabelSelector(match_labels={"grp": "gins"})
            ),
        )
    )
    from dataclasses import replace as _replace

    store.create_throttle(_replace(ins, spec=_replace(ins.spec, selector=ins_sel)))
    store.create_pod(
        make_pod(
            "p-ins-run",
            labels={"grp": "gins"},
            requests={"cpu": "800m"},
            node_name="node-1",
            phase="Running",
        )
    )
    store.create_pod(
        make_pod("p-ins-pending", labels={"grp": "gins"}, requests={"cpu": "300m"})
    )


class TestFullTickSharded:
    def test_matches_dense_check_on_static_store(self, stack):
        store, plugin = stack
        _populate(store, random.Random(0))
        plugin.run_pending_once()  # statuses converge (single-threaded)

        mesh = make_mesh(8, (4, 2))
        tick = plugin.device_manager.full_tick_sharded(mesh, on_equal=False)
        dense = plugin.device_manager.check_batch_all(False)

        for kind in ("throttle", "clusterthrottle"):
            counts_t, ok_t, rows_t, used_cnt, used_req, col_map = tick[kind]
            counts_d, ok_d, rows_d = dense[kind]
            assert rows_t == rows_d
            rows = sorted(rows_t.values())
            np.testing.assert_array_equal(
                np.asarray(counts_t)[rows], np.asarray(counts_d)[rows]
            )
            np.testing.assert_array_equal(
                np.asarray(ok_t)[rows], np.asarray(ok_d)[rows]
            )
            # recomputed used == written status.used
            for col, key in col_map.items():
                ns, _, name = key.partition("/")
                thr = store.get_throttle(ns, name)
                want = thr.status.used.resource_counts or 0
                assert int(used_cnt[col]) == want, key

        # the scenario must be non-degenerate: all verdict classes appear
        counts = np.asarray(tick["throttle"][0])
        rows = sorted(tick["throttle"][2].values())
        assert (counts[rows].sum(axis=0) > 0).all(), "expected all 4 classes"

    def test_single_device_mesh(self, stack):
        store, plugin = stack
        _populate(store, random.Random(1), n_thr=8, n_pods=24)
        plugin.run_pending_once()
        tick = plugin.device_manager.full_tick_sharded(make_mesh(1, (1, 1)))
        dense = plugin.device_manager.check_batch_all(False)
        for kind in ("throttle", "clusterthrottle"):
            _, ok_t, rows, *_ = tick[kind]
            _, ok_d, _ = dense[kind]
            idx = sorted(rows.values())
            np.testing.assert_array_equal(
                np.asarray(ok_t)[idx], np.asarray(ok_d)[idx]
            )

    def test_sparse_single_device_matches_sharded_mesh(self, stack):
        """The 1×1-mesh tick routes through the sparse [P,K] gather step
        (full_update_step_gather — no [P,T] tensor at all); its counts,
        verdicts, and recomputed used must match the dense 8-device
        shard_map program cell-for-cell."""
        store, plugin = stack
        # sized for sparse eligibility: ~12 matches/pod pads to the K=16
        # rung, which needs tcap ≥ 128 (the K*4 < tcap ladder policy)
        _populate(store, random.Random(2), n_thr=96, n_pods=200, groups=8)
        plugin.run_pending_once()
        dm = plugin.device_manager

        t1 = dm.full_tick_sharded(make_mesh(1, (1, 1)))
        # the scenario must actually exercise the sparse path: enough
        # throttles that the [P,K] companion is the chosen batch shape
        with dm._lock:
            dm.throttle.device_pods(need_mask=False)
            assert dm.throttle.device_cols() is not None, (
                "test state too small: cols ladder opted out, sparse tick "
                "not exercised"
            )
        t8 = dm.full_tick_sharded(make_mesh(8, (4, 2)))

        for kind in ("throttle", "clusterthrottle"):
            counts_1, ok_1, rows_1, used_cnt_1, used_req_1, cols_1 = t1[kind]
            counts_8, ok_8, rows_8, used_cnt_8, used_req_8, cols_8 = t8[kind]
            assert rows_1 == rows_8
            rows = sorted(rows_1.values())
            np.testing.assert_array_equal(
                np.asarray(counts_1)[rows], np.asarray(counts_8)[rows]
            )
            np.testing.assert_array_equal(
                np.asarray(ok_1)[rows], np.asarray(ok_8)[rows]
            )
            cols = sorted(cols_1)
            np.testing.assert_array_equal(
                np.asarray(used_cnt_1)[cols], np.asarray(used_cnt_8)[cols]
            )
            np.testing.assert_array_equal(
                np.asarray(used_req_1)[cols], np.asarray(used_req_8)[cols]
            )

    def test_sparse_sharded_matches_dense_sharded(self, stack):
        """The multi-chip SPARSE tick (sharded_full_update_gather: [P,K]
        global-id cols rebased per throttle tile, two psums) must match
        the dense [P/dp,T/tp] shard_map program cell-for-cell on the same
        8-device mesh — counts, verdicts, and recomputed used."""
        store, plugin = stack
        _populate(store, random.Random(5), n_thr=96, n_pods=200, groups=8)
        # _populate creates only namespaced Throttles; the cluster kind
        # needs its own population large enough for cols eligibility or
        # its half of this parity loop would silently run dense-vs-dense
        from kube_throttler_tpu.api.types import (
            ClusterThrottle,
            ClusterThrottleSelector,
            ClusterThrottleSelectorTerm,
            ClusterThrottleSpec,
        )

        for i in range(96):
            store.create_cluster_throttle(
                ClusterThrottle(
                    name=f"ct{i}",
                    spec=ClusterThrottleSpec(
                        throttler_name="kube-throttler",
                        threshold=ResourceAmount.of(
                            pod=(i % 7) + 1,
                            requests={"cpu": f"{(i % 5 + 1)}00m"},
                        ),
                        selector=ClusterThrottleSelector(
                            selector_terms=(
                                ClusterThrottleSelectorTerm(
                                    pod_selector=LabelSelector(
                                        match_labels={"grp": f"g{i % 8}"}
                                    ),
                                ),
                            )
                        ),
                    ),
                )
            )
        plugin.run_pending_once()
        dm = plugin.device_manager

        mesh = make_mesh(8, (4, 2))
        sparse = dm.full_tick_sharded(mesh)
        with dm._lock:
            for ks in (dm.throttle, dm.clusterthrottle):
                ks.device_pods(need_mask=False)
                assert ks.device_cols() is not None, (
                    f"test state too small: {ks.kind} cols ladder opted out, "
                    "sparse-sharded tick not exercised for that kind"
                )
        dense = dm.full_tick_sharded(mesh, dense_mesh=True)

        for kind in ("throttle", "clusterthrottle"):
            counts_s, ok_s, rows_s, used_cnt_s, used_req_s, cols_s = sparse[kind]
            counts_d, ok_d, rows_d, used_cnt_d, used_req_d, cols_d = dense[kind]
            assert rows_s == rows_d
            rows = sorted(rows_s.values())
            np.testing.assert_array_equal(
                np.asarray(counts_s)[rows], np.asarray(counts_d)[rows]
            )
            np.testing.assert_array_equal(
                np.asarray(ok_s)[rows], np.asarray(ok_d)[rows]
            )
            cols = sorted(cols_s)
            np.testing.assert_array_equal(
                np.asarray(used_cnt_s)[cols], np.asarray(used_cnt_d)[cols]
            )
            np.testing.assert_array_equal(
                np.asarray(used_req_s)[cols], np.asarray(used_req_d)[cols]
            )

    def test_active_override_resolved_on_device(self, stack):
        """An active temporary override must shape the tick's thresholds:
        spec cpu=100m would throttle the 200m pod, but the active override
        lifts it to 10 CPUs — the tick must classify it schedulable."""
        store, plugin = stack
        now = datetime.now(timezone.utc)
        ov = TemporaryThresholdOverride(
            begin=rfc(now - timedelta(hours=1)),
            end=rfc(now + timedelta(hours=1)),
            threshold=ResourceAmount.of(requests={"cpu": "10"}),
        )
        store.create_throttle(_throttle("t0", 1, 0, cpu="100m", overrides=(ov,)))
        store.create_pod(
            make_pod(
                "p-running",
                labels={"grp": "g0"},
                requests={"cpu": "200m"},
                node_name="node-1",
                phase="Running",
            )
        )
        store.create_pod(make_pod("p-pending", labels={"grp": "g0"}, requests={"cpu": "200m"}))
        plugin.run_pending_once()
        tick = plugin.device_manager.full_tick_sharded(make_mesh(8, (4, 2)), now=now)
        _, ok, rows, used_cnt, _, col_map = tick["throttle"]
        assert bool(np.asarray(ok)[rows["default/p-pending"]])
        (col,) = [c for c, k in col_map.items() if k == "default/t0"]
        assert int(used_cnt[col]) == 1  # only the Running pod counts

        # without the override (past window) the same pod is blocked
        ov2 = TemporaryThresholdOverride(
            begin=rfc(now - timedelta(hours=3)),
            end=rfc(now - timedelta(hours=2)),
            threshold=ResourceAmount.of(requests={"cpu": "10"}),
        )
        from dataclasses import replace

        cur = store.get_throttle("default", "t0")
        store.update_throttle(
            replace(cur, spec=replace(cur.spec, temporary_threshold_overrides=(ov2,)))
        )
        plugin.run_pending_once()
        tick = plugin.device_manager.full_tick_sharded(make_mesh(8, (4, 2)), now=now)
        _, ok, rows, *_ = tick["throttle"]
        assert not bool(np.asarray(ok)[rows["default/p-pending"]])

    def test_tick_races_live_churn(self, stack):
        """full_tick_sharded snapshots under the main lock while store
        events mutate rows/columns concurrently: ticks must never crash and
        every verdict map must cover exactly the pods of SOME point in the
        event stream (keys are a superset of never-deleted pods)."""
        import threading

        store, plugin = stack
        rng = random.Random(3)
        _populate(store, rng, n_thr=12, n_pods=40)
        plugin.run_pending_once()
        mesh = make_mesh(8, (4, 2))
        # compile the shard_map programs BEFORE the race window, so the
        # churn genuinely overlaps snapshot/tick work rather than one
        # multi-second first-call compilation
        plugin.device_manager.full_tick_sharded(mesh, on_equal=False)
        stable = {p.key for p in store.list_pods()}  # never deleted below

        errors = []
        results = []
        started = threading.Event()

        def churner():
            started.wait(10)
            try:
                for i in range(300):
                    store.create_pod(
                        make_pod(
                            f"churn{i}",
                            labels={"grp": f"g{rng.randrange(8)}"},
                            requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
                            node_name="node-1",
                            phase="Running",
                        )
                    )
                    if i % 3 == 0 and i:
                        store.delete_pod("default", f"churn{i - 1}")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=churner)
        t.start()
        try:
            started.set()
            ticks = 0
            while t.is_alive() or ticks < 3:  # guaranteed overlap while alive
                out = plugin.device_manager.full_tick_sharded(mesh, on_equal=False)
                results.append(out)
                ticks += 1
                if ticks > 50:
                    break
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            t.join()
        assert not errors, errors
        assert len(results) >= 3
        for out in results:
            for kind in ("throttle", "clusterthrottle"):
                _, ok, rows, *_ = out[kind]
                assert stable <= set(rows), "tick lost stable pods"
                # snapshot coherence: rows index into the verdict array,
                # one row per pod (a torn snapshot could alias rows)
                vals = list(rows.values())
                assert max(vals) < len(ok)
                assert len(set(vals)) == len(vals), "aliased mask rows"

    def test_plugin_surface_and_http(self, stack):
        store, plugin = stack
        _populate(store, random.Random(2), n_thr=8, n_pods=24)
        plugin.run_pending_once()
        out = plugin.full_tick_sharded(8, (4, 2))
        assert out["mesh"] == [4, 2]
        assert set(out["schedulable"]) == {p.key for p in store.list_pods()}
        batch = plugin.pre_filter_batch()
        assert out["schedulable"] == batch["schedulable"]
        assert out["used"]["throttle"], "per-throttle used counts exposed"

        # over the wire: POST /v1/tick
        import json
        from http.client import HTTPConnection

        from kube_throttler_tpu.server import ThrottlerHTTPServer

        server = ThrottlerHTTPServer(plugin, port=0)
        server.start()
        try:
            conn = HTTPConnection("127.0.0.1", server.port, timeout=30)
            conn.request(
                "POST",
                "/v1/tick",
                json.dumps({"devices": 8, "shape": [4, 2]}),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            wire = json.loads(resp.read())
            assert resp.status == 200
            assert wire["mesh"] == [4, 2]
            assert wire["schedulable"] == {
                k: bool(v) for k, v in out["schedulable"].items()
            }
        finally:
            server.stop()
