"""ThrottleController — namespaced reconciler (reference
throttle_controller.go).

Responsibilities, mirroring the Go controller one-for-one:

- ``reconcile(key)``          (throttle_controller.go:84-211)
- ``affected_pods``           (221-246; the terminated-slice append bug at
                               241 is deliberately FIXED here)
- ``affected_throttles``      (248-269)
- ``reserve`` / ``unreserve`` (271-347)
- ``check_throttled``         (349-397)
- event handlers incl. the symmetric-difference reservation move on pod
  label changes (400-536)
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..api.pod import Pod
from ..api.types import (
    CalculatedThreshold,
    ResourceAmount,
    Throttle,
    ThrottleStatus,
    resource_amount_of_pod,
)
from ..engine.devicestate import DeviceStateManager
from ..engine.reservations import ReservedResourceAmounts
from ..engine.store import Event, EventType, NotFoundError, Store
from ..utils.clock import Clock
from .base import ControllerBase

logger = logging.getLogger(__name__)


class ThrottleController(ControllerBase):
    KIND = "throttle"

    def __init__(
        self,
        throttler_name: str,
        target_scheduler_name: str,
        store: Store,
        clock: Optional[Clock] = None,
        threadiness: int = 1,
        num_key_mutex: int = 128,
        device_manager: Optional[DeviceStateManager] = None,
        metrics_recorder=None,
        resync_interval=None,
        listers=None,
        informers=None,
        status_writer=None,
        reservation_ttl=None,
    ):
        """``listers`` (client.listers.Listers) routes every read through the
        indexer-backed lister layer and ``informers`` (SharedInformerFactory)
        sources events from shared informers instead of raw store handlers —
        the reference's composition (plugin.go:76-88). Without them the
        controller falls back to direct store access (standalone/unit use).
        ``status_writer`` is where status updates go: the store (default) or
        a RemoteStatusWriter PUTting the real apiserver's status
        subresource (throttle_controller.go:170)."""
        super().__init__(
            name="ThrottleController",
            target_kind="Throttle",
            throttler_name=throttler_name,
            target_scheduler_name=target_scheduler_name,
            clock=clock,
            threadiness=threadiness,
            resync_interval=resync_interval,
        )
        self.store = store
        self.listers = listers
        self.informers = informers
        self.status_writer = status_writer if status_writer is not None else store
        # reservation ledger shares the controller clock so TTL expiry is
        # deterministic under FakeClock tests and rebases correctly on
        # crash recovery (engine/recovery.py)
        self.cache = ReservedResourceAmounts(num_key_mutex, clock=self.clock)
        self.reservation_ttl = reservation_ttl
        # gang ledger (engine/gang.py), wired by the plugin: the
        # unreserve-on-observe handshake notifies it as members' per-pod
        # reservations release into status.used
        self.gang_ledger = None
        self.device_manager = device_manager
        self.metrics_recorder = metrics_recorder
        self.reconcile_func = self.reconcile
        self.reconcile_batch_func = self.reconcile_batch
        self.list_keys_func = self._list_responsible_keys
        self._setup_event_handlers()

    # ------------------------------------------------------------- data reads
    # (lister-backed when wired, plugin.go:76-88; store fallback otherwise)

    def _get_throttle(self, namespace: str, name: str) -> Throttle:
        if self.listers is not None:
            try:
                return self.listers.throttles.throttles(namespace).get(name)
            except KeyError:
                raise NotFoundError(f"Throttle {namespace}/{name} not found")
        return self.store.get_throttle(namespace, name)

    def _list_throttles(self, namespace: Optional[str] = None) -> List[Throttle]:
        if self.listers is not None:
            if namespace is None:
                return self.listers.throttles.list()
            return self.listers.throttles.throttles(namespace).list()
        return self.store.list_throttles(namespace)

    def _list_pods(self, namespace: str) -> List[Pod]:
        if self.listers is not None:
            # the namespace-indexed pod lister — the very indexer the
            # reference builds its second informer factory for
            # (plugin.go:81-84)
            return self.listers.pods.pods(namespace).list()
        return self.store.list_pods(namespace)

    def _list_responsible_keys(self) -> List[str]:
        return [t.key for t in self._list_throttles() if self.is_responsible_for(t)]

    # ------------------------------------------------------------ predicates

    def is_responsible_for(self, thr: Throttle) -> bool:
        return self.throttler_name == thr.spec.throttler_name

    def should_count_in(self, pod: Pod) -> bool:
        return (
            pod.spec.scheduler_name == self.target_scheduler_name and pod.is_scheduled()
        )

    # ------------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> None:
        errors = self.reconcile_batch([key])
        if errors:
            raise errors[key]

    def reconcile_batch(self, keys: List[str]) -> Dict[str, Exception]:
        """Reconcile a drained batch of keys in three phases: with a device
        manager, ONE flush+gather of the used-aggregates serves every key
        (the streaming data plane — no per-throttle pod scan); all changed
        statuses then land in ONE batched store write (a per-key write
        contends with the event-ingest threads for the store lock hundreds
        of times per drain at saturation); finally the per-key post-write
        work (metrics, unreserve-on-observe, override wakeups) runs for the
        keys whose write — if any — succeeded. Returns failures for
        requeue."""
        now = self.clock.now()
        thrs: Dict[str, Throttle] = {}
        for key in dict.fromkeys(keys):
            namespace, _, name = key.partition("/")
            try:
                thrs[key] = self._get_throttle(namespace, name)
            except NotFoundError:
                pass  # deleted — nothing to do (throttle_controller.go:96-99)
        if not thrs:
            return {}
        errors: Dict[str, Exception] = {}
        used_map = None
        flips: dict = {}
        dm = self.device_manager
        if dm is not None:
            # on breaker-open/failure this batch reconciles via the host
            # walk below (matched_pods reads the host-side mask, no
            # device), so statuses keep converging through a device outage
            reserved = {key: self.cache.reserved_pod_keys(key) for key in thrs}
            used_map = dm.guarded(
                "reconcile", dm.aggregate_used_for, self.KIND, list(thrs),
                reserved, flips_out=flips,
            )
        promote = flips.get("promote")
        if promote:
            # keys OUTSIDE this drain whose published throttled flags
            # disagree with the fresh aggregates (the classification
            # delta): jump them to the queue front so their flip publishes
            # next drain instead of after a full refresh-backlog cycle —
            # policy-weighted (valued accel classes drain first)
            self.workqueue.add_all_priority(
                promote, priorities=self.flip_priorities(promote)
            )
        drained_flips = flips.get("drained", frozenset())
        # phase 1: pure status computation + the unreserve sets
        plans = []  # (key, thr, new_thr | None, unreserve_list)
        flip_keys = set()
        for key, thr in thrs.items():
            try:
                if used_map is not None:
                    used, unreserve_pods = used_map[key]
                else:
                    non_terminated, terminated = self.affected_pods(thr)
                    used = ResourceAmount()
                    for p in non_terminated:
                        used = used.add(resource_amount_of_pod(p))
                    unreserve_pods = non_terminated + terminated
                new_status = self._planned_status(thr, used, now)
                new_thr = (
                    thr.with_status(new_status)
                    if new_status != thr.status
                    else None
                )
                if new_thr is not None and (
                    thr.key in drained_flips
                    # _planned_status reuses the status object when the
                    # calculated threshold is unchanged, so identity is the
                    # zero-cost change check
                    or new_status.calculated_threshold
                    is not thr.status.calculated_threshold
                    # host-walk fallback (breaker open): no classification
                    # delta — fall back to the direct flag compare
                    or (
                        used_map is None
                        and new_status.throttled != thr.status.throttled
                    )
                ):
                    flip_keys.add(key)
                plans.append((key, thr, new_thr, unreserve_pods))
            except Exception as e:
                errors[key] = e
        # phases 2+3: batched write + post-write work (base helper; remote
        # mode interleaves per key so the double-count window stays one PUT)
        self._commit_reconcile_plans(plans, now, errors, flip_keys=flip_keys)
        return errors

    # lane-aware batch writer method (the AsyncStatusCommitter's duck type);
    # resolved by the base commit helper, absent on the plain Store
    _prioritized_batch_attr = "update_throttle_statuses_prioritized"

    def _write_status(self, thr: Throttle) -> None:
        self.status_writer.update_throttle_status(thr)

    def _batch_write_statuses(self, thrs):
        batch = getattr(self.status_writer, "update_throttle_statuses", None)
        return None if batch is None else batch(thrs)

    @staticmethod
    def _store_key(thr: Throttle) -> str:
        return thr.key

    def _planned_status(self, thr: Throttle, used: ResourceAmount, now) -> ThrottleStatus:
        calculated = thr.spec.calculate_threshold(now)
        new_calculated = thr.status.calculated_threshold
        if (
            thr.status.calculated_threshold.threshold != calculated.threshold
            or thr.status.calculated_threshold.messages != calculated.messages
        ):
            # only adopt the fresh calculatedAt when the content changed —
            # otherwise every reconcile would differ by timestamp alone
            # (throttle_controller.go:123-132)
            new_calculated = calculated
        throttled = new_calculated.threshold.is_throttled(used, True)
        return ThrottleStatus(
            calculated_threshold=new_calculated, throttled=throttled, used=used
        )

    # ----------------------------------------------------------- collections

    def affected_pods(self, thr: Throttle) -> Tuple[List[Pod], List[Pod]]:
        non_terminated: List[Pod] = []
        terminated: List[Pod] = []
        if self.device_manager is not None:
            # selector part answered by the incremental mask column — only
            # matched pods are touched, never the whole namespace
            pods = self.device_manager.matched_pods(self.KIND, thr.key)
            pods = [p for p in pods if p.namespace == thr.namespace]
        else:
            pods = [
                p
                for p in self._list_pods(thr.namespace)
                if thr.spec.selector.matches_to_pod(p)
            ]
        for pod in pods:
            if not self.should_count_in(pod):
                continue
            if pod.is_not_finished():
                non_terminated.append(pod)
            else:
                terminated.append(pod)
        return non_terminated, terminated

    def affected_throttle_keys(self, pod: Pod) -> List[str]:
        if self.device_manager is not None:
            return self.device_manager.affected_throttle_keys(self.KIND, pod)
        return [t.key for t in self.affected_throttles(pod)]

    def throttle_by_key(self, key: str) -> Throttle:
        namespace, _, name = key.partition("/")
        return self._get_throttle(namespace, name)

    def affected_throttles(self, pod: Pod) -> List[Throttle]:
        if self.device_manager is not None:
            affected = []
            for key in self.device_manager.affected_throttle_keys(self.KIND, pod):
                namespace, _, name = key.partition("/")
                try:
                    thr = self._get_throttle(namespace, name)
                except NotFoundError:
                    continue
                if self.is_responsible_for(thr):
                    affected.append(thr)
            return affected
        affected = []
        for thr in self._list_throttles(pod.namespace):
            if not self.is_responsible_for(thr):
                continue
            if thr.spec.selector.matches_to_pod(pod):
                affected.append(thr)
        return affected

    # ----------------------------------------------------------- reservation

    def reserve(self, pod: Pod) -> None:
        for thr in self.affected_throttles(pod):
            self.reserve_on_throttle(pod, thr)

    def reserve_on_throttle(self, pod: Pod, thr: Throttle) -> bool:
        added = self.cache.add_pod(thr.key, pod, ttl=self.reservation_ttl)
        if added and self.device_manager is not None:
            self.device_manager.on_reservation_change(self.KIND, thr.key, self.cache)
        return added

    def unreserve(self, pod: Pod) -> None:
        for thr in self.affected_throttles(pod):
            self.unreserve_on_throttle(pod, thr)

    def unreserve_on_throttle(self, pod: Pod, thr: Throttle) -> bool:
        removed = self.cache.remove_pod(thr.key, pod)
        if removed and self.device_manager is not None:
            self.device_manager.on_reservation_change(self.KIND, thr.key, self.cache)
        if removed and self.gang_ledger is not None:
            self.gang_ledger.note_unreserved(self.KIND, thr.key, pod.key)
        return removed

    # ----------------------------------------------------------------- check

    def check_throttled(
        self, pod: Pod, is_throttled_on_equal: bool
    ) -> Tuple[List[Throttle], List[Throttle], List[Throttle], List[Throttle]]:
        """→ (active, insufficient, pod-requests-exceeds, affected)
        (throttle_controller.go:349-397).

        With a device manager the classification runs as one kernel call
        over the mirrored tensors; otherwise — or while the device circuit
        breaker is open after a dispatch failure (backend/tunnel death) —
        the host oracle loops, so a device outage degrades latency, never
        availability. An accel-class pod takes the host oracle whenever
        any mirrored throttle declares accelClassThresholds: the device
        planes carry only the base thresholds, and the host oracle is
        where the per-class replacement resolves (api/types.py)."""
        from ..api.pod import accel_class_of

        accel = accel_class_of(pod)
        dm = self.device_manager
        if dm is not None and not (accel and dm.has_accel_thresholds(self.KIND)):
            results = dm.guarded("check", dm.check_pod, pod, self.KIND, is_throttled_on_equal)
            if results is not None:
                return self.classify_from_map(results)
        throttles = self.affected_throttles(pod)
        active: List[Throttle] = []
        insufficient: List[Throttle] = []
        exceeds: List[Throttle] = []
        for thr in throttles:
            reserved, _ = self.cache.reserved_resource_amount(thr.key)
            status = thr.check_throttled_for(
                pod, reserved, is_throttled_on_equal, accel_class=accel
            )
            if status == "active":
                active.append(thr)
            elif status == "insufficient":
                insufficient.append(thr)
            elif status == "pod-requests-exceeds-threshold":
                exceeds.append(thr)
        return active, insufficient, exceeds, throttles

    def classify_from_map(self, results: Dict[str, str]):
        """Device classification map {throttle_key: status} → the
        check_throttled 4-tuple. Shared by the per-pod device path and the
        micro-batching pre_filter front-end (one fused dispatch produces
        many pods' maps; each composes reasons through this same code).

        Object resolution is BULK (one indexer lock hold for all K keys):
        the per-key lister chain (namespace-lister alloc + lock + dict
        layers) measured ~3µs × ~20 affected keys × 2 kinds per decision
        at the 100k×10k scale — a third of the served p50. A key whose
        object vanished between the device snapshot and here (concurrent
        delete) is skipped: a deleted throttle cannot block scheduling,
        matching the lister-backed affectedThrottles behavior
        (throttle_controller.go:221-269 drops not-found keys)."""
        active, insufficient, exceeds, affected = [], [], [], []
        if self.listers is not None:
            objs = self.listers.throttles.get_by_keys(list(results.keys()))
        else:
            objs = []
            for key in results:
                try:
                    objs.append(self.store.get_throttle(*key.split("/", 1)))
                except NotFoundError:
                    objs.append(None)
        for (key, status), thr in zip(results.items(), objs):
            if thr is None:
                continue
            affected.append(thr)
            if status == "active":
                active.append(thr)
            elif status == "insufficient":
                insufficient.append(thr)
            elif status == "pod-requests-exceeds-threshold":
                exceeds.append(thr)
        return active, insufficient, exceeds, affected

    # ---------------------------------------------------------- event wiring

    def _setup_event_handlers(self) -> None:
        from .base import _BatchEventHandler

        if self.informers is not None:
            # shared-informer subscription (mustSetupEventHandler,
            # throttle_controller.go:400): the informer mirrors the store
            # into its indexer BEFORE fanning out, so lister reads from a
            # handler always observe a cache >= the event. The batch
            # wrappers let a micro-batched ingest burst fan out as ONE call
            # with one workqueue lock hold (informers.on_batch probes for
            # on_events).
            self.informers.throttles().add_event_handler(
                _BatchEventHandler(self._on_throttle_event, self._on_throttle_events)
            )
            self.informers.pods().add_event_handler(
                _BatchEventHandler(self._on_pod_event, self._on_pod_events)
            )
        else:
            self.store.add_event_handler("Throttle", self._on_throttle_event)
            self.store.add_event_handler("Pod", self._on_pod_event)

    def _throttle_event_key(self, event: Event) -> Optional[str]:
        thr = event.obj
        if not self.is_responsible_for(thr):
            return None
        if self._is_self_status_echo(event):
            return None  # our own in-flight status write; reconciling it is a no-op
        return thr.key

    def _on_throttle_event(self, event: Event) -> None:
        key = self._throttle_event_key(event)
        if key is not None:
            self.enqueue(key)

    def _on_throttle_events(self, events) -> None:
        keys = [k for k in map(self._throttle_event_key, events) if k is not None]
        if keys:
            self.enqueue_all(keys)

    def _pod_event_keys(self, event: Event):
        """Per-event pod handling: reservation side effects run inline;
        the keys to enqueue are RETURNED so the batch fan-out can union a
        whole ingest burst into one workqueue lock hold."""
        if event.type == EventType.ADDED:
            pod = event.obj
            if not self.should_count_in(pod):
                return None
            return self.affected_throttle_keys(pod)
        elif event.type == EventType.MODIFIED:
            old_pod, new_pod = event.old_obj, event.obj
            if not self.should_count_in(old_pod) and not self.should_count_in(new_pod):
                return None
            if self._selector_inputs_unchanged(old_pod, new_pod):
                return self.affected_throttle_keys(new_pod)
            old_keys = set(self.affected_throttle_keys(old_pod))
            new_keys = set(self.affected_throttle_keys(new_pod))
            moved_from = old_keys - new_keys
            moved_to = new_keys - old_keys
            if moved_from or moved_to:
                # atomic reservation move on label change
                # (throttle_controller.go:469-500)
                self.cache.move_throttle_assignment(new_pod, moved_from, moved_to)
                if self.device_manager is not None:
                    for key in moved_from | moved_to:
                        self.device_manager.on_reservation_change(self.KIND, key, self.cache)
            return old_keys | new_keys
        else:  # DELETED
            pod = event.obj
            if not self.should_count_in(pod):
                return None
            if pod.is_scheduled():
                # the deleted pod may still hold reservations
                # (throttle_controller.go:508-519)
                try:
                    self.unreserve(pod)
                except Exception:
                    logger.exception("failed to unreserve deleted pod %s", pod.key)
            return self.affected_throttle_keys(pod)

    def _on_pod_event(self, event: Event) -> None:
        keys = self._pod_event_keys(event)
        if keys:
            self.enqueue_all(keys)

    def _on_pod_events(self, events) -> None:
        union: set = set()
        for event in events:
            keys = self._pod_event_keys(event)
            if keys:
                union.update(keys)
        if union:
            self.enqueue_all(union)
