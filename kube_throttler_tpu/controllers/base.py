"""Shared controller worker-pool (reference controller.go:34-122).

N worker threads drain a rate-limiting workqueue; reconcile errors re-queue
with exponential backoff (controller.go:106-108); success forgets the key.
``enqueue_after`` drives override-boundary self-wakeups.

A periodic **resync** (``resync_interval`` + ``list_keys_func``) re-enqueues
every live key on a fixed cadence — the eventual-consistency backstop the
reference gets from its 5-minute informer resync (plugin.go:77,86): any
status left stale by a missed/unwirable event converges within one interval.
It rides the same delayed-queue machinery as ``enqueue_after`` via a
reserved sentinel key, so FakeClock tests drive it deterministically.
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import timedelta
from typing import Callable, Dict, List, Optional

from ..engine.store import EventType
from ..engine.workqueue import RateLimitingQueue, ShutDown
from ..utils.tracing import NoopTracer, vlog
from ..utils.clock import Clock, RealClock

logger = logging.getLogger(__name__)

# Reserved workqueue key that triggers a full re-enqueue of live keys.
# "\x00" cannot appear in a Kubernetes object name, so it can never collide
# with a real reconcile key.
RESYNC_KEY = "\x00resync"


class _BatchEventHandler:
    """Informer handler carrying a batch fast path.

    ``__call__`` keeps the plain per-event contract; ``on_events`` receives
    a whole ordered batch in one call (SharedIndexInformer.on_batch probes
    for the attribute). Bound methods cannot carry attributes, hence this
    two-slot wrapper — the controllers register it so a micro-batched
    ingest burst costs them ONE handler invocation and ONE workqueue lock
    hold instead of N."""

    __slots__ = ("_per_event", "on_events")

    def __init__(self, per_event, on_events):
        self._per_event = per_event
        self.on_events = on_events

    def __call__(self, event) -> None:
        self._per_event(event)


class ControllerBase:
    def __init__(
        self,
        name: str,
        target_kind: str,
        throttler_name: str,
        target_scheduler_name: str,
        clock: Optional[Clock] = None,
        threadiness: int = 1,
        resync_interval: Optional[timedelta] = None,
    ):
        self.name = name
        self.target_kind = target_kind
        self.throttler_name = throttler_name
        self.target_scheduler_name = target_scheduler_name
        self.clock = clock or RealClock()
        self.threadiness = threadiness
        self.workqueue = RateLimitingQueue(name, clock=self.clock)
        self.reconcile_func: Callable[[str], None] = lambda key: None
        # optional batched reconcile: a worker drains up to batch_max ready
        # keys and hands them over in one call, so a shared step (the device
        # used-aggregate flush+gather) is paid once per drain, not per key.
        # Returns {key: exception} for the keys to requeue.
        self.reconcile_batch_func: Optional[Callable[[List[str]], dict]] = None
        # 96, down from 256: a promoted flip waits out the IN-FLIGHT normal
        # drain before its express drain runs, so the drain size bounds the
        # flip tail — at full scale 256-key drains held flips ~100-500ms
        # p99, 96-key drains ~65ms, while the extra aggregate flushes are
        # noise (the steady-state steal is a list swap; measured sustained
        # ingest was unchanged)
        self.batch_max = 96
        # phase tracer (utils.tracing.PhaseTracer); set by the plugin so
        # reconcile latency lands in the same histogram family as the hot path
        self.tracer = NoopTracer()
        # periodic resync: every resync_interval, every key returned by
        # list_keys_func is re-enqueued (dedup'd by the workqueue)
        self.resync_interval = resync_interval
        self.list_keys_func: Optional[Callable[[], List[str]]] = None
        self._threads: List[threading.Thread] = []
        self._started = False
        # {store_key: id(status)} of writes in flight — see
        # _commit_reconcile_plans (self-echo suppression)
        self._inflight_status_echoes: Dict[str, int] = {}
        # StatusLagMetrics for LOCAL publication (set by the plugin when a
        # registry exists); remote publication is observed by the async
        # committer at PUT completion instead
        self.lag_metrics = None
        # policy-weighted flip promotion (docs/policy.md): key → hi-lane
        # priority, wired by the plugin from the policy engine's accel-
        # class value weights. None/0 keeps the lane's original FIFO.
        self.flip_priority_fn: Optional[Callable[[str], int]] = None
        if self.resync_interval is not None:
            self.workqueue.add_after(RESYNC_KEY, self.resync_interval)

    def _is_self_status_echo(self, event) -> bool:
        """True for the MODIFIED echo of a status THIS controller is
        writing right now. The store dispatches handlers synchronously
        inside the write, ON THE WRITER'S OWN THREAD — so the signature is
        (writing thread, key, identity of the exact status object passed
        in). The thread check closes the race where a CONCURRENT
        spec-update write from another thread re-attaches the stored
        (still-marked) status object via with_status while our marker is
        live: that event dispatches on the other thread and must still
        enqueue. Re-enqueueing a true self-echo is a guaranteed no-op
        reconcile — the write carried no information the reconcile that
        produced it hadn't already observed."""
        obj = event.obj
        return (
            event.type == EventType.MODIFIED
            and self._inflight_status_echoes.get(self._store_key(obj))
            == (threading.get_ident(), id(obj.status))
        )

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self.threadiness):
            t = threading.Thread(
                target=self._run_worker, name=f"{self.name}-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        logger.info("Started %s workers name=%s threadiness=%d", self.name, self.throttler_name, self.threadiness)

    def stop(self) -> None:
        self.workqueue.shut_down()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        self._started = False

    def enqueue(self, key: str) -> None:
        self.workqueue.add(key)

    def enqueue_all(self, keys) -> None:
        self.workqueue.add_all(keys)

    @staticmethod
    def _selector_inputs_unchanged(old_pod, new_pod) -> bool:
        """Selector matching reads only labels + namespace, so an unchanged
        pair means the affected-throttle set cannot have moved — pod
        MODIFIED handlers take a single-lookup fast path with no
        reservation-move bookkeeping (the dominant churn shape:
        requests/status-only updates)."""
        return (
            old_pod is not None
            and old_pod.labels == new_pod.labels
            and old_pod.namespace == new_pod.namespace
        )

    def enqueue_after(self, key: str, duration: timedelta) -> None:
        self.workqueue.add_after(key, duration)

    def flip_priorities(self, keys) -> Optional[Dict[str, int]]:
        """Policy promotion priorities for a flip-promoted key set (the
        workqueue's (-priority, seq) hi-lane ordering input). None — the
        original FIFO — when no policy fn is wired or no key carries a
        non-zero weight, so the default path allocates nothing."""
        fn = self.flip_priority_fn
        if fn is None:
            return None
        out: Dict[str, int] = {}
        for key in keys:
            try:
                p = fn(key)
            except Exception:  # pragma: no cover — policy must not stall flips
                p = 0
            if p:
                out[key] = p
        return out or None

    def throttle_by_key(self, key: str):
        """Kind-specific store lookup by queue/store key (implemented by
        each controller; used by policy flip weighting and the preemption
        coordinator's candidate gathering)."""
        raise NotImplementedError

    # ------------------------------------------------- batched-drain commit

    def _commit_reconcile_plans(self, plans, now, errors, flip_keys=frozenset()) -> None:
        """Phases 2+3 of a batched reconcile drain, shared by both kinds'
        controllers (they differ only in writer methods and key forms).

        ``plans`` is ``[(queue_key, thr, new_thr | None, unreserve_pods)]``
        from the controller's compute phase. With a batch-capable status
        writer (the in-memory Store), every changed status lands in ONE
        store-lock hold — at drain saturation, per-key writes contend with
        the event-ingest threads for that lock hundreds of times per drain
        — and the post-write work runs afterwards (the used-vs-reserved
        double-count window is the few ms the batch write takes). Without
        one (remote mode, one HTTP PUT per object regardless), write and
        post-write work stay INTERLEAVED per key so the double-count
        window stays one PUT wide, exactly like the pre-batch code — a
        drain of slow PUTs must not delay key #1's unreserve to the end.

        ``flip_keys`` (queue keys) are statuses whose ``throttled`` flags
        or ``calculatedThreshold`` changed — the two-lane split: they are
        committed FIRST in every path (batch write order == store event
        dispatch order == watch order; interleaved PUTs go flips-first),
        and a lane-aware writer (the AsyncStatusCommitter) additionally
        routes them to its priority lane. Per-key ordering is unaffected:
        a key appears in ``plans`` once, so reordering between keys can
        never reorder writes of the same key.

        Controllers provide ``_write_status(thr)``,
        ``_batch_write_statuses(thrs) -> {store_key: obj|Exception} | None``
        (None ⇒ unsupported), ``_store_key(thr)``, and
        ``_prioritized_batch_attr`` (the lane-aware writer method name).
        """
        if flip_keys:
            plans = [p for p in plans if p[0] in flip_keys] + [
                p for p in plans if p[0] not in flip_keys
            ]
        changed = {key: new for key, _, new, _ in plans if new is not None}
        # event→publication lag inputs, keyed by STORE key: the enqueue
        # timestamp of the event that made each written key dirty
        event_ts: Dict[str, float] = {}
        flip_store_keys = set()
        for key, new in changed.items():
            sk = self._store_key(new)
            ts = self.workqueue.claim_ts(key)
            if ts is not None:
                event_ts[sk] = ts
            if key in flip_keys:
                flip_store_keys.add(sk)
        # self-echo suppression: the store dispatches our own MODIFIED echo
        # synchronously INSIDE the write below, and _on_throttle_event
        # re-enqueued the key on every one — at drain saturation ~half of
        # all drained keys were these no-op self-echo reconciles. Mark the
        # exact status objects about to be written (identity, per key) so
        # the handler can recognize and drop the echo; entries are removed
        # the moment the write returns. Remote-mode echoes arrive later as
        # freshly-decoded objects (different identity) and still enqueue —
        # the reference's watch-observe loop is preserved on the wire.
        me = threading.get_ident()
        for new in changed.values():
            self._inflight_status_echoes[self._store_key(new)] = (me, id(new.status))
        async_lanes = False
        try:
            if not changed:
                batched = {}
            else:
                pri = getattr(self.status_writer, self._prioritized_batch_attr, None)
                if pri is not None:
                    # lane-aware writer (AsyncStatusCommitter): flips take
                    # the priority PUT lane; it observes the lag histograms
                    # itself at PUT completion (publication is async here)
                    batched = pri(list(changed.values()), flip_store_keys, event_ts)
                    async_lanes = True
                else:
                    batched = self._batch_write_statuses(list(changed.values()))
        finally:
            for new in changed.values():
                self._inflight_status_echoes.pop(self._store_key(new), None)
        if batched is None:  # no batch writer: interleave per key
            for key, thr, new_thr, unreserve_pods in plans:
                try:
                    if new_thr is not None:
                        self._write_status(new_thr)
                        self._observe_lag(
                            event_ts, flip_store_keys, self._store_key(new_thr)
                        )
                    self._post_write(key, thr, new_thr, unreserve_pods, now)
                except Exception as e:  # noqa: BLE001 — requeued per key
                    errors[key] = e
            return
        if not async_lanes and batched and self.lag_metrics is not None:
            # local batched publication: the write above made every status
            # visible (store event dispatched inside the write, flips first)
            for sk, r in batched.items():
                if not isinstance(r, Exception):
                    self._observe_lag(event_ts, flip_store_keys, sk)
        store_to_queue = {self._store_key(new): key for key, new in changed.items()}
        write_errors = {
            store_to_queue.get(k, k): r
            for k, r in batched.items()
            if isinstance(r, Exception)
        }
        for key, thr, new_thr, unreserve_pods in plans:
            if key in write_errors:
                errors[key] = write_errors[key]
                continue
            try:
                self._post_write(key, thr, new_thr, unreserve_pods, now)
            except Exception as e:  # noqa: BLE001 — requeued per key
                errors[key] = e

    def _observe_lag(self, event_ts, flip_store_keys, store_key) -> None:
        if self.lag_metrics is None:
            return
        ts = event_ts.get(store_key)
        if ts is not None:
            self.lag_metrics.observe(
                self.target_kind,
                time.monotonic() - ts,
                store_key in flip_store_keys,
            )

    def _post_write(self, key, thr, new_thr, unreserve_pods, now) -> None:
        """Per-key work that must follow the status write: metrics record,
        unreserve-on-observe (throttle_controller.go:135-155 — the device
        path's set is snapshot-coherent with the aggregate; unreserve is a
        no-op for non-reserved pods), and the next override-boundary
        wakeup."""
        if self.metrics_recorder is not None:
            self.metrics_recorder.record(new_thr if new_thr is not None else thr)
        for p in unreserve_pods:
            self.unreserve_on_throttle(p, thr)
        next_in = thr.spec.next_override_happens_in(now)
        if next_in is not None:
            self.enqueue_after(key, next_in)

    def _resync(self) -> None:
        """Re-enqueue every live key, then re-arm the next tick. Errors in
        ``list_keys_func`` skip one tick but never kill the cadence."""
        try:
            if self.list_keys_func is not None:
                keys = self.list_keys_func()
                vlog(4, "%s: periodic resync, re-enqueuing %d keys", self.name, len(keys))
                for key in keys:
                    self.workqueue.add(key)
        except Exception:
            logger.exception("%s: resync key listing failed", self.name)
        finally:
            self.workqueue.forget(RESYNC_KEY)
            self.workqueue.done(RESYNC_KEY)
            if self.resync_interval is not None:
                self.workqueue.add_after(RESYNC_KEY, self.resync_interval)

    def _process_batch(self, keys: List[str]) -> None:
        """Run the (batched) reconcile for drained keys; requeue failures
        rate-limited (controller.go:106-108), forget successes."""
        if RESYNC_KEY in keys:
            keys = [k for k in keys if k != RESYNC_KEY]
            self._resync()
            if not keys:
                return
        failures: dict = {}
        try:
            vlog(4, "%s: reconciling batch %r", self.name, keys)
            with self.tracer.trace("reconcile"):
                if self.reconcile_batch_func is not None:
                    failures = self.reconcile_batch_func(keys) or {}
                else:
                    for key in keys:
                        try:
                            self.reconcile_func(key)
                        except Exception as e:
                            failures[key] = e
        except Exception as e:  # batch-level crash fails every key
            failures = {key: e for key in keys}
        for key in keys:
            if key in failures:
                self.workqueue.add_rate_limited(key)
                logger.error(
                    "error reconciling %r, requeuing", key, exc_info=failures[key]
                )
            else:
                self.workqueue.forget(key)
            self.workqueue.done(key)

    def _drain_more(self, first: str, first_hi: bool = False) -> List[str]:
        """Extend a drain batch. A PRIORITY first key takes the flip
        express: the drain extends with priority-lane keys ONLY, so a flip
        publication pays a few-key drain (aggregate flush + a handful of
        writes) instead of riding a full ``batch_max`` refresh cycle — at
        full scale that is the difference between ~20ms and ~100ms+ of
        flip lag. Refresh keys wait for the next normal drain; the lane is
        almost always near-empty, so express drains are tiny and cheap."""
        keys = [first]
        if self.reconcile_batch_func is not None:
            while len(keys) < self.batch_max:
                nxt = self.workqueue.try_get(hi_only=first_hi)
                if nxt is None:
                    break
                keys.append(nxt)
        return keys

    def _run_worker(self) -> None:
        while True:
            try:
                key, was_hi = self.workqueue.get_lane()
            except ShutDown:
                return
            # loop-level routing (threads checker): per-key reconcile
            # errors are requeued inside _process_batch; this backstop is
            # for the UNEXPECTED — a worker dying here would silently
            # stop reconciliation for its share of the queue while every
            # probe stayed green (the PR 6 silent-death class)
            try:
                self._process_batch(self._drain_more(key, first_hi=was_hi))
            except Exception:  # noqa: BLE001 — keep the worker alive
                logger.exception(
                    "%s worker: unexpected reconcile-batch failure (key=%s)",
                    self.name, key,
                )

    def run_pending_once(self, max_items: int = 10000) -> int:
        """Synchronously drain currently-ready queue items on the calling
        thread (deterministic tests / single-threaded embedding). Returns the
        number of reconciles executed."""
        n = 0
        while len(self.workqueue) > 0 and n < max_items:
            key = self.workqueue.get(timeout=0.01)
            keys = self._drain_more(key)
            self._process_batch(keys)
            n += len(keys)
        return n
