"""Shared controller worker-pool (reference controller.go:34-122).

N worker threads drain a rate-limiting workqueue; reconcile errors re-queue
with exponential backoff (controller.go:106-108); success forgets the key.
``enqueue_after`` drives override-boundary self-wakeups.
"""

from __future__ import annotations

import logging
import threading
from datetime import timedelta
from typing import Callable, List, Optional

from ..engine.workqueue import RateLimitingQueue, ShutDown
from ..utils.tracing import NoopTracer, vlog
from ..utils.clock import Clock, RealClock

logger = logging.getLogger(__name__)


class ControllerBase:
    def __init__(
        self,
        name: str,
        target_kind: str,
        throttler_name: str,
        target_scheduler_name: str,
        clock: Optional[Clock] = None,
        threadiness: int = 1,
    ):
        self.name = name
        self.target_kind = target_kind
        self.throttler_name = throttler_name
        self.target_scheduler_name = target_scheduler_name
        self.clock = clock or RealClock()
        self.threadiness = threadiness
        self.workqueue = RateLimitingQueue(name, clock=self.clock)
        self.reconcile_func: Callable[[str], None] = lambda key: None
        # phase tracer (utils.tracing.PhaseTracer); set by the plugin so
        # reconcile latency lands in the same histogram family as the hot path
        self.tracer = NoopTracer()
        self._threads: List[threading.Thread] = []
        self._started = False

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self.threadiness):
            t = threading.Thread(
                target=self._run_worker, name=f"{self.name}-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        logger.info("Started %s workers name=%s threadiness=%d", self.name, self.throttler_name, self.threadiness)

    def stop(self) -> None:
        self.workqueue.shut_down()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        self._started = False

    def enqueue(self, key: str) -> None:
        self.workqueue.add(key)

    def enqueue_after(self, key: str, duration: timedelta) -> None:
        self.workqueue.add_after(key, duration)

    def _run_worker(self) -> None:
        while True:
            try:
                key = self.workqueue.get()
            except ShutDown:
                return
            try:
                vlog(4, "%s: reconciling %r", self.name, key)
                with self.tracer.trace("reconcile"):
                    self.reconcile_func(key)
            except Exception:
                # error → rate-limited requeue (controller.go:106-108)
                self.workqueue.add_rate_limited(key)
                logger.exception("error reconciling %r, requeuing", key)
            else:
                self.workqueue.forget(key)
            finally:
                self.workqueue.done(key)

    def run_pending_once(self, max_items: int = 10000) -> int:
        """Synchronously drain currently-ready queue items on the calling
        thread (deterministic tests / single-threaded embedding). Returns the
        number of reconciles executed."""
        n = 0
        while len(self.workqueue) > 0 and n < max_items:
            key = self.workqueue.get(timeout=0.01)
            try:
                with self.tracer.trace("reconcile"):
                    self.reconcile_func(key)
            except Exception:
                self.workqueue.add_rate_limited(key)
                logger.exception("error reconciling %r, requeuing", key)
            else:
                self.workqueue.forget(key)
            finally:
                self.workqueue.done(key)
            n += 1
        return n
