"""ClusterThrottleController — cluster-scoped twin (reference
clusterthrottle_controller.go).

Differences from ThrottleController, all mirrored from the reference:

- selector terms AND a namespaceSelector (affected_pods iterates matched
  namespaces — clusterthrottle_controller.go:224-270);
- ``affected_cluster_throttles`` requires the pod's Namespace object; a
  missing namespace is an error, not a silent no-match (273-276);
- ``check_throttled`` passes the caller's onEqual through to step 3 of the
  4-state check (via ClusterThrottle.check_throttled_for —
  clusterthrottle_types.go:45);
- the reference watches the namespace informer with NO handlers
  (clusterthrottle_controller.go:429) and relies on the 5-minute informer
  resync (plugin.go:77) to eventually repair statuses after a namespace
  relabel. This build diverges DELIBERATELY: ``_on_namespace_event``
  enqueues every responsible ClusterThrottle whose namespaceSelector match
  flipped, so ``status.used`` converges immediately instead of within 5
  minutes; the periodic resync (ControllerBase.resync_interval) remains the
  backstop.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..api.pod import Pod
from ..api.types import (
    ClusterThrottle,
    ResourceAmount,
    ThrottleStatus,
    resource_amount_of_pod,
)
from ..engine.devicestate import DeviceStateManager
from ..engine.reservations import ReservedResourceAmounts
from ..engine.store import Event, EventType, NotFoundError, Store
from ..utils.clock import Clock
from .base import ControllerBase

logger = logging.getLogger(__name__)


class ClusterThrottleController(ControllerBase):
    KIND = "clusterthrottle"

    def __init__(
        self,
        throttler_name: str,
        target_scheduler_name: str,
        store: Store,
        clock: Optional[Clock] = None,
        threadiness: int = 1,
        num_key_mutex: int = 128,
        device_manager: Optional[DeviceStateManager] = None,
        metrics_recorder=None,
        resync_interval=None,
        listers=None,
        informers=None,
        status_writer=None,
        reservation_ttl=None,
    ):
        """See ThrottleController.__init__ for the listers / informers /
        status_writer contract (plugin.go:76-88 composition)."""
        super().__init__(
            name="ClusterThrottleController",
            target_kind="ClusterThrottle",
            throttler_name=throttler_name,
            target_scheduler_name=target_scheduler_name,
            clock=clock,
            threadiness=threadiness,
            resync_interval=resync_interval,
        )
        self.store = store
        self.listers = listers
        self.informers = informers
        self.status_writer = status_writer if status_writer is not None else store
        # reservation ledger shares the controller clock so TTL expiry is
        # deterministic under FakeClock tests and rebases correctly on
        # crash recovery (engine/recovery.py)
        self.cache = ReservedResourceAmounts(num_key_mutex, clock=self.clock)
        self.reservation_ttl = reservation_ttl
        # gang ledger (engine/gang.py), wired by the plugin — see
        # ThrottleController.gang_ledger
        self.gang_ledger = None
        self.device_manager = device_manager
        self.metrics_recorder = metrics_recorder
        self.reconcile_func = self.reconcile
        self.reconcile_batch_func = self.reconcile_batch
        self.list_keys_func = self._list_responsible_keys
        self._setup_event_handlers()

    # ------------------------------------------------------------- data reads
    # (lister-backed when wired, plugin.go:76-88; store fallback otherwise)

    def _get_cluster_throttle(self, name: str) -> ClusterThrottle:
        if self.listers is not None:
            try:
                return self.listers.cluster_throttles.get(name)
            except KeyError:
                raise NotFoundError(f"ClusterThrottle {name!r} not found")
        return self.store.get_cluster_throttle(name)

    def _list_cluster_throttles(self) -> List[ClusterThrottle]:
        if self.listers is not None:
            return self.listers.cluster_throttles.list()
        return self.store.list_cluster_throttles()

    def _get_namespace(self, name: str):
        if self.listers is not None:
            try:
                return self.listers.namespaces.get(name)
            except KeyError:
                return None
        return self.store.get_namespace(name)

    def _list_namespaces(self):
        if self.listers is not None:
            return self.listers.namespaces.list()
        return self.store.list_namespaces()

    def _list_pods(self, namespace: str) -> List[Pod]:
        if self.listers is not None:
            return self.listers.pods.pods(namespace).list()
        return self.store.list_pods(namespace)

    def _list_responsible_keys(self) -> List[str]:
        return [
            t.key for t in self._list_cluster_throttles() if self.is_responsible_for(t)
        ]

    def is_responsible_for(self, thr: ClusterThrottle) -> bool:
        return self.throttler_name == thr.spec.throttler_name

    def should_count_in(self, pod: Pod) -> bool:
        return (
            pod.spec.scheduler_name == self.target_scheduler_name and pod.is_scheduled()
        )

    # ------------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> None:
        errors = self.reconcile_batch([key])
        if errors:
            raise errors[key]

    def reconcile_batch(self, keys: List[str]) -> Dict[str, Exception]:
        """Batched twin of ThrottleController.reconcile_batch: one device
        flush+gather of the used-aggregates serves the whole drained batch."""
        now = self.clock.now()
        thrs: Dict[str, ClusterThrottle] = {}
        for key in dict.fromkeys(keys):
            try:
                thrs[key] = self._get_cluster_throttle(key.lstrip("/"))
            except NotFoundError:
                pass
        if not thrs:
            return {}
        errors: Dict[str, Exception] = {}
        used_map = None
        flips: dict = {}
        dm = self.device_manager
        if dm is not None:
            # on breaker-open/failure reconcile falls to the host walk
            # below (the mask read is host-side); statuses keep converging
            reserved = {
                t.key: self.cache.reserved_pod_keys(t.key) for t in thrs.values()
            }
            used_map = dm.guarded(
                "reconcile",
                dm.aggregate_used_for,
                self.KIND,
                [t.key for t in thrs.values()],
                reserved,
                flips_out=flips,
            )
        promote = flips.get("promote")
        if promote:
            # classification-delta flips outside this drain: queue-front
            # promotion, policy-weighted (see ThrottleController.
            # reconcile_batch)
            self.workqueue.add_all_priority(
                promote, priorities=self.flip_priorities(promote)
            )
        drained_flips = flips.get("drained", frozenset())
        # three-phase drain, mirroring ThrottleController.reconcile_batch:
        # compute → one batched status write → per-key post-write work
        plans = []  # (key, thr, new_thr | None, unreserve_list)
        flip_keys = set()
        for key, thr in thrs.items():
            try:
                if used_map is not None:
                    used, unreserve_pods = used_map[thr.key]
                else:
                    non_terminated, terminated = self.affected_pods(thr)
                    used = ResourceAmount()
                    for p in non_terminated:
                        used = used.add(resource_amount_of_pod(p))
                    unreserve_pods = non_terminated + terminated
                new_status = self._planned_status(thr, used, now)
                new_thr = (
                    thr.with_status(new_status)
                    if new_status != thr.status
                    else None
                )
                if new_thr is not None and (
                    thr.key in drained_flips
                    or new_status.calculated_threshold
                    is not thr.status.calculated_threshold
                    or (
                        used_map is None
                        and new_status.throttled != thr.status.throttled
                    )
                ):
                    flip_keys.add(key)
                plans.append((key, thr, new_thr, unreserve_pods))
            except Exception as e:
                errors[key] = e
        self._commit_reconcile_plans(plans, now, errors, flip_keys=flip_keys)
        return errors

    # lane-aware batch writer method (AsyncStatusCommitter duck type)
    _prioritized_batch_attr = "update_cluster_throttle_statuses_prioritized"

    def _write_status(self, thr: ClusterThrottle) -> None:
        self.status_writer.update_cluster_throttle_status(thr)

    def _batch_write_statuses(self, thrs):
        batch = getattr(
            self.status_writer, "update_cluster_throttle_statuses", None
        )
        return None if batch is None else batch(thrs)

    @staticmethod
    def _store_key(thr: ClusterThrottle) -> str:
        # the store keys ClusterThrottles by bare name; the workqueue key
        # is mapped back by the base commit helper
        return thr.name

    def _planned_status(
        self, thr: ClusterThrottle, used: ResourceAmount, now
    ) -> ThrottleStatus:
        calculated = thr.spec.calculate_threshold(now)
        new_calculated = thr.status.calculated_threshold
        if (
            thr.status.calculated_threshold.threshold != calculated.threshold
            or thr.status.calculated_threshold.messages != calculated.messages
        ):
            new_calculated = calculated
        throttled = new_calculated.threshold.is_throttled(used, True)
        return ThrottleStatus(
            calculated_threshold=new_calculated, throttled=throttled, used=used
        )


    # ----------------------------------------------------------- collections

    def affected_pods(self, thr: ClusterThrottle) -> Tuple[List[Pod], List[Pod]]:
        non_terminated: List[Pod] = []
        terminated: List[Pod] = []
        if self.device_manager is not None:
            # the mask column already ANDs podSelector ∧ namespaceSelector ∧
            # namespace-existence (clusterthrottle_selector.go:112-141)
            pods = self.device_manager.matched_pods(self.KIND, thr.key)
        else:
            ns_map = {}
            pods = []
            for ns in self._list_namespaces():
                if not thr.spec.selector.matches_to_namespace(ns):
                    continue
                ns_map[ns.name] = ns
                pods.extend(self._list_pods(ns.name))
            pods = [
                p
                for p in pods
                if thr.spec.selector.matches_to_pod(p, ns_map[p.namespace])
            ]
        for pod in pods:
            if not self.should_count_in(pod):
                continue
            if pod.is_not_finished():
                non_terminated.append(pod)
            else:
                terminated.append(pod)
        return non_terminated, terminated

    def throttle_by_key(self, key: str) -> ClusterThrottle:
        # cluster keys carry the NamespacedName leading "/" (api/types.py)
        return self._get_cluster_throttle(key.lstrip("/"))

    def affected_cluster_throttle_keys(self, pod: Pod) -> List[str]:
        ns = self._get_namespace(pod.namespace)
        if ns is None:
            # Go: lister Get error propagates (clusterthrottle_controller.go:273-276)
            raise NotFoundError(f"namespace {pod.namespace!r} not found")
        if self.device_manager is not None:
            return self.device_manager.affected_throttle_keys(self.KIND, pod)
        return [t.key for t in self._scan_cluster_throttles(pod, ns)]

    def affected_cluster_throttles(self, pod: Pod) -> List[ClusterThrottle]:
        ns = self._get_namespace(pod.namespace)
        if ns is None:
            # Go: lister Get error propagates (clusterthrottle_controller.go:273-276)
            raise NotFoundError(f"namespace {pod.namespace!r} not found")
        if self.device_manager is not None:
            affected = []
            for key in self.device_manager.affected_throttle_keys(self.KIND, pod):
                try:
                    thr = self._get_cluster_throttle(key.lstrip("/"))
                except NotFoundError:
                    continue
                if self.is_responsible_for(thr):
                    affected.append(thr)
            return affected
        return self._scan_cluster_throttles(pod, ns)

    # kind-agnostic alias: the gang oracle (engine/gang.py
    # sequential_gang_check) and other cross-kind walkers iterate both
    # controllers through one method name
    def affected_throttles(self, pod: Pod) -> List[ClusterThrottle]:
        return self.affected_cluster_throttles(pod)

    def _scan_cluster_throttles(self, pod: Pod, ns) -> List[ClusterThrottle]:
        affected = []
        for thr in self._list_cluster_throttles():
            if not self.is_responsible_for(thr):
                continue
            if thr.spec.selector.matches_to_pod(pod, ns):
                affected.append(thr)
        return affected

    # ----------------------------------------------------------- reservation

    def reserve(self, pod: Pod) -> None:
        for thr in self.affected_cluster_throttles(pod):
            self.reserve_on_throttle(pod, thr)

    def reserve_on_throttle(self, pod: Pod, thr: ClusterThrottle) -> bool:
        added = self.cache.add_pod(thr.key, pod, ttl=self.reservation_ttl)
        if added and self.device_manager is not None:
            self.device_manager.on_reservation_change(self.KIND, thr.key, self.cache)
        return added

    def unreserve(self, pod: Pod) -> None:
        for thr in self.affected_cluster_throttles(pod):
            self.unreserve_on_throttle(pod, thr)

    def unreserve_on_throttle(self, pod: Pod, thr: ClusterThrottle) -> bool:
        removed = self.cache.remove_pod(thr.key, pod)
        if removed and self.device_manager is not None:
            self.device_manager.on_reservation_change(self.KIND, thr.key, self.cache)
        if removed and self.gang_ledger is not None:
            self.gang_ledger.note_unreserved(self.KIND, thr.key, pod.key)
        return removed

    # ----------------------------------------------------------------- check

    def check_throttled(
        self, pod: Pod, is_throttled_on_equal: bool
    ) -> Tuple[
        List[ClusterThrottle], List[ClusterThrottle], List[ClusterThrottle], List[ClusterThrottle]
    ]:
        from ..api.pod import accel_class_of

        accel = accel_class_of(pod)
        dm = self.device_manager
        if dm is not None and not (accel and dm.has_accel_thresholds(self.KIND)):
            # the missing-namespace error contract holds on the device path
            # too (clusterthrottle_controller.go:273-276); with the breaker
            # open the host path below enforces it itself
            if dm.device_available() and self._get_namespace(pod.namespace) is None:
                raise NotFoundError(f"namespace {pod.namespace!r} not found")
            results = dm.guarded("check", dm.check_pod, pod, self.KIND, is_throttled_on_equal)
            if results is not None:
                return self.classify_from_map(results)
        throttles = self.affected_cluster_throttles(pod)
        active: List[ClusterThrottle] = []
        insufficient: List[ClusterThrottle] = []
        exceeds: List[ClusterThrottle] = []
        for thr in throttles:
            reserved, _ = self.cache.reserved_resource_amount(thr.key)
            status = thr.check_throttled_for(
                pod, reserved, is_throttled_on_equal, accel_class=accel
            )
            if status == "active":
                active.append(thr)
            elif status == "insufficient":
                insufficient.append(thr)
            elif status == "pod-requests-exceeds-threshold":
                exceeds.append(thr)
        return active, insufficient, exceeds, throttles

    def classify_from_map(self, results: Dict[str, str]):
        """See ThrottleController.classify_from_map (cluster keys carry no
        namespace prefix; same bulk resolution + skip-deleted semantics)."""
        active, insufficient, exceeds, affected = [], [], [], []
        if self.listers is not None:
            objs = self.listers.cluster_throttles.get_by_names(
                [key.lstrip("/") for key in results]
            )
        else:
            objs = []
            for key in results:
                try:
                    objs.append(self.store.get_cluster_throttle(key.lstrip("/")))
                except NotFoundError:
                    objs.append(None)
        for (key, status), thr in zip(results.items(), objs):
            if thr is None:
                continue
            affected.append(thr)
            if status == "active":
                active.append(thr)
            elif status == "insufficient":
                insufficient.append(thr)
            elif status == "pod-requests-exceeds-threshold":
                exceeds.append(thr)
        return active, insufficient, exceeds, affected

    # ---------------------------------------------------------- event wiring

    def _setup_event_handlers(self) -> None:
        # The reference watches namespaces with NO handlers
        # (clusterthrottle_controller.go:429) and leans on the 5-min informer
        # resync; here a namespace event whose selector match flips enqueues
        # the affected clusterthrottles directly (no replay: preexisting
        # namespaces carry no pending status change).
        from .base import _BatchEventHandler

        if self.informers is not None:
            self.informers.cluster_throttles().add_event_handler(
                _BatchEventHandler(self._on_throttle_event, self._on_throttle_events)
            )
            self.informers.pods().add_event_handler(
                _BatchEventHandler(self._on_pod_event, self._on_pod_events)
            )
            self.informers.namespaces().add_event_handler(
                self._on_namespace_event, replay=False
            )
        else:
            self.store.add_event_handler("ClusterThrottle", self._on_throttle_event)
            self.store.add_event_handler("Pod", self._on_pod_event)
            self.store.add_event_handler(
                "Namespace", self._on_namespace_event, replay=False
            )

    def _on_namespace_event(self, event: Event) -> None:
        """Enqueue responsible clusterthrottles whose namespaceSelector match
        for this namespace changed. A relabel that un-matches a selector
        flips many device-mask rows at once (devicestate._on_namespace); this
        is the enqueue that makes the flipped aggregate land in status —
        without it, ``status.used`` stays wrong until a pod event or resync.

        A namespace label change affects all pods of the namespace uniformly
        within one selector term (the term is namespaceSelector ∧
        podSelector, clusterthrottle_selector.go:112-141), so membership can
        only change when some TERM's namespace-side match flips. The check
        must be per-term, not on the OR-aggregate: a relabel that moves the
        namespace from term A to term B keeps the aggregate True on both
        sides while the counted pod set (term A's podSelector vs term B's)
        changes completely.
        """
        old_ns = event.old_obj if event.type == EventType.MODIFIED else (
            event.obj if event.type == EventType.DELETED else None
        )
        new_ns = event.obj if event.type != EventType.DELETED else None
        for thr in self._list_cluster_throttles():
            if not self.is_responsible_for(thr):
                continue
            for term in thr.spec.selector.selector_terms:
                old_match = old_ns is not None and term.matches_to_namespace(old_ns)
                new_match = new_ns is not None and term.matches_to_namespace(new_ns)
                if old_match != new_match:
                    self.enqueue(thr.key)
                    break

    def _throttle_event_key(self, event: Event) -> Optional[str]:
        thr = event.obj
        if not self.is_responsible_for(thr):
            return None
        if self._is_self_status_echo(event):
            return None  # our own in-flight status write; reconciling it is a no-op
        return thr.key

    def _on_throttle_event(self, event: Event) -> None:
        key = self._throttle_event_key(event)
        if key is not None:
            self.enqueue(key)

    def _on_throttle_events(self, events) -> None:
        keys = [k for k in map(self._throttle_event_key, events) if k is not None]
        if keys:
            self.enqueue_all(keys)

    def _pod_event_keys(self, event: Event):
        """Per-event pod handling with the enqueue keys RETURNED (see
        ThrottleController._pod_event_keys — the batch fan-out unions a
        whole ingest burst into one workqueue lock hold)."""
        if event.type == EventType.ADDED:
            pod = event.obj
            if not self.should_count_in(pod):
                return None
            return self._affected_keys_or_log(pod)
        elif event.type == EventType.MODIFIED:
            old_pod, new_pod = event.old_obj, event.obj
            if not self.should_count_in(old_pod) and not self.should_count_in(new_pod):
                return None
            if self._selector_inputs_unchanged(old_pod, new_pod):
                return self._affected_keys_or_log(new_pod)
            try:
                old_keys = set(self.affected_cluster_throttle_keys(old_pod))
                new_keys = set(self.affected_cluster_throttle_keys(new_pod))
            except NotFoundError:
                logger.exception("failed to get affected clusterthrottles for %s", new_pod.key)
                return None
            moved_from = old_keys - new_keys
            moved_to = new_keys - old_keys
            if moved_from or moved_to:
                self.cache.move_throttle_assignment(new_pod, moved_from, moved_to)
                if self.device_manager is not None:
                    for key in moved_from | moved_to:
                        self.device_manager.on_reservation_change(self.KIND, key, self.cache)
            return old_keys | new_keys
        else:  # DELETED
            pod = event.obj
            if not self.should_count_in(pod):
                return None
            if pod.is_scheduled():
                try:
                    self.unreserve(pod)
                except Exception:
                    logger.exception("failed to unreserve deleted pod %s", pod.key)
            return self._affected_keys_or_log(pod)

    def _on_pod_event(self, event: Event) -> None:
        keys = self._pod_event_keys(event)
        if keys:
            self.enqueue_all(keys)

    def _on_pod_events(self, events) -> None:
        union: set = set()
        for event in events:
            keys = self._pod_event_keys(event)
            if keys:
                union.update(keys)
        if union:
            self.enqueue_all(union)

    def _affected_keys_or_log(self, pod: Pod) -> List[str]:
        try:
            return self.affected_cluster_throttle_keys(pod)
        except NotFoundError:
            logger.exception("failed to get affected clusterthrottles for %s", pod.key)
            return []
