"""Interned-verdict cache: the serving hot path as a hash probe.

PR 11's arena proved the admission workload is massively degenerate —
100k pods collapse to ~7 request shapes and ~500 label shapes — and a
PreFilter verdict is a pure function of (request-shape id, accel class,
matched-throttle cols, per-col state). This module memoizes that pure
function behind an epoch-versioned key:

- **key** = (request-shape id, accel class, throttle-cols bytes,
  clusterthrottle-cols bytes) — the function's domain, produced by
  ``DeviceStateManager.verdict_fingerprint`` (or the front's routing
  mirror);
- **version** = the epoch-sum over the key's cols (+ both kinds' global
  epochs). Every mutation that can change a verdict bumps a covered
  epoch under the owner's main lock (row encodes, removals, reservation
  writes, namespace events), and epochs are monotonic, so for a fixed
  cols set an equal sum proves elementwise equality — stale entries are
  unreachable by construction, not by eviction ("invalidation by
  epoch"). Eviction exists only to bound memory.

Concurrency: probes are LOCK-FREE — a probe is one ``dict.get`` per
segment (atomic under the GIL; CPython never leaves a dict observable
mid-resize), so readers never serialize behind each other or behind
inserts. Inserts take a small lock only to keep the size/rotation
bookkeeping coherent. Callers must follow the **validate-after-compute**
protocol: read ``(key, esum)``, compute the verdict OUTSIDE any lock,
re-read the fingerprint, and insert only if the sum is unchanged — a
concurrent mutation then suppresses the insert instead of poisoning the
cache.

Eviction is two-generation rotation (LRU-ish, O(1), no per-probe
bookkeeping): inserts fill the ``new`` segment; when it reaches half the
capacity the segments rotate (``new`` → ``old``, fresh ``new``, previous
``old`` dropped). Probes check ``new`` then ``old`` and promote old hits
forward, so keys hot across a rotation window survive and cold keys age
out after two rotations. Correctness never depends on any of this — a
dropped entry is a miss, a surviving entry is still epoch-checked.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ..utils.lockorder import guard_attrs, make_lock

__all__ = ["VerdictCache"]


@guard_attrs
class VerdictCache:
    """Bounded (key → (epoch-sum, verdict)) map with lock-free probes.

    The cached "verdict" is opaque to this module — the plugin stores
    composed ``Status`` objects, the sharded front stores its own merged
    composition. Stats counters are plain ints: the probe side updates
    them without a lock (a torn ``+=`` can lose a rare increment, which
    is acceptable for monitoring and keeps the hit path at zero lock
    acquisitions); the insert side updates them under ``_lock``.
    """

    # _new/_old are REBOUND only under _lock (rotation/clear); probes read
    # them lock-free by design — attribute loads and dict.get are atomic
    # under the GIL, and a probe that races a rotation at worst consults
    # the just-demoted segment (a benign extra miss/hit of valid data).
    # Deliberately NOT in the GUARDED_BY table for that reason; likewise
    # hits/misses, which the lock-free probe side bumps by contract (a
    # torn increment loses a monitoring count, never a verdict). The
    # insert-side counters below ARE lock-owned: stats() reads them
    # racily at scrape (waived in baseline.txt / race_allow.txt).
    GUARDED_BY = {
        "insertions": "self._lock",
        "rotations": "self._lock",
        "invalidations": "self._lock",
    }

    def __init__(self, capacity: int = 65536) -> None:
        self.capacity = max(2, int(capacity))
        self._seg_cap = self.capacity // 2
        self._lock = make_lock("verdictcache.insert")
        self._new: dict = {}
        self._old: dict = {}
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.invalidations = 0  # explicit invalidate_all() calls
        self.rotations = 0

    # ------------------------------------------------------------- probe

    def get(self, key: tuple, esum: int) -> Optional[Any]:
        """The cached verdict for ``key`` at epoch-sum ``esum``, else None.

        An entry whose stored sum differs is a miss — never returned and
        left for rotation to recycle (epochs are monotonic, so it can
        never become valid again; overwriting is the insert's job)."""
        entry = self._new.get(key)
        if entry is None:
            entry = self._old.get(key)
            if entry is not None and entry[0] == esum:
                # promote across the rotation boundary so keys hot in the
                # previous window survive the next rotation; a lost race
                # with a concurrent rotation just skips the promotion
                self._new[key] = entry
        if entry is not None and entry[0] == esum:
            self.hits += 1
            return entry[1]
        self.misses += 1
        return None

    # ------------------------------------------------------------ insert

    def put(self, key: tuple, esum: int, verdict: Any) -> None:
        """Insert under the validate-after-compute protocol (see module
        docstring — the CALLER re-validated ``esum`` after computing)."""
        with self._lock:
            new = self._new
            new[key] = (esum, verdict)
            self.insertions += 1
            if len(new) >= self._seg_cap:
                self._old = new
                self._new = {}
                self.rotations += 1

    def invalidate_all(self) -> None:
        """Drop every entry (policy-spec swaps, replica rebootstrap).
        Belt-and-braces only — epoch bumps already fence every covered
        mutation; a probe racing this swap can serve one pre-swap verdict,
        exactly as if it had probed a moment earlier."""
        with self._lock:
            self._new = {}
            self._old = {}
            self.invalidations += 1

    # ------------------------------------------------------------- stats

    def __len__(self) -> int:
        return len(self._new) + len(self._old)

    def stats(self) -> Tuple[int, int, int, int, int]:
        """(hits, misses, entries, invalidations, insertions) — sampled
        racily, for metrics."""
        return (self.hits, self.misses, len(self), self.invalidations, self.insertions)
