"""Incremental [P,T] selector-mask maintenance.

The reference re-evaluates label selectors linearly on every pod event and
every reconcile (affectedThrottles — throttle_controller.go:248-269 — is an
O(#throttles) Python-equivalent scan). At the 100k-pod × 10k-throttle target
that is 10⁹ selector evaluations per full pass, so the new framework keeps
the match matrix *materialized* and maintains it incrementally:

- **fast tier**: every VALID selector — matchLabels conjunctions AND
  matchExpressions (In/NotIn/Exists/DoesNotExist) — compiles to interned
  (label-key → value-id) requirements over columnar int32 label arrays.
  A pod event recomputes one mask row via the native C++ engine's
  inverted-index candidate pruning; a throttle event recomputes one
  column with O(P) vectorized numpy ops.
- **general tier**: selectors that fail validation fall back to per-object
  oracle evaluation, confined to the affected row/column — the exact
  error-confinement semantics of the reference.

Namespacing: a Throttle only ever matches pods in its own namespace
(affectedThrottles lists the pod's namespace); ClusterThrottle terms AND a
namespaceSelector over the pod's namespace labels
(clusterthrottle_selector.go:71-87). Both are folded into the same row/column
updates.

Capacity management: arrays grow geometrically and rows/columns are
free-listed, so the mask object handed to the device keeps a stable shape
between growth events (no kernel recompilation on object churn).

Storage (PR 11): the match matrix is SPARSE — per-row sorted matched-col
arrays ``int32[pcap, kcap]`` (sentinel-padded) with per-row counts,
where ``kcap`` tracks the max per-pod match count. A dense ``[P, T]``
bool plane is 100 GB at the 1M-pod × 100k-throttle target; the sparse
rows are ~K×4 bytes/pod and double as the device's ``[P, K]`` cols
encoding directly. ``mask`` remains available as a property that
materializes the dense plane on demand (tests, the dense-kernel batch
route at small scale); hot consumers read the sparse accessors
(``row_cols`` / ``rows_of_col`` / ``row_cols_block``).

Object retention (PR 11): with a ``pod_resolver`` wired (the columnar
store's ``materialize_pod``), the index retains NO Pod objects — per row
it keeps only the key, namespace, and a reference to the store's shared
interned labels dict. The rare consumers that need a full object
(general-tier selector evaluation, ``matched_pods``) materialize through
the resolver at call time. Without a resolver (standalone use) the
index retains event objects exactly as before.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

import numpy as np

from ..utils.lockorder import assert_held, guard_attrs, make_rlock
from ..api.pod import Namespace, Pod
from ..api.types import (
    ClusterThrottle,
    SelectorError,
    Throttle,
)
from ..native import NativeRowEngine

AnyThrottle = Union[Throttle, ClusterThrottle]

_MISSING = -1  # pod lacks the label key
_ANY = -2  # term does not constrain this key
# sparse-row padding sentinel: sorts AFTER every valid column id, so a
# plain ascending sort keeps valid cols as a sorted prefix
_SENT = np.iinfo(np.int32).max


class _Interner:
    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}

    def id_of(self, value: str) -> int:
        idx = self._ids.get(value)
        if idx is None:
            idx = len(self._ids)
            self._ids[value] = idx
        return idx


def _simple_terms(thr: AnyThrottle) -> Optional[List[Tuple[Dict[str, str], Dict[str, str]]]]:
    """Return [(pod_pairs, ns_pairs)] if every term is matchLabels-only."""
    terms = []
    for term in thr.spec.selector.selector_terms:
        if term.pod_selector.match_expressions:
            return None
        ns_pairs: Dict[str, str] = {}
        if isinstance(thr, ClusterThrottle):
            if term.namespace_selector.match_expressions:
                return None
            ns_pairs = dict(term.namespace_selector.match_labels)
        terms.append((dict(term.pod_selector.match_labels), ns_pairs))
    return terms


@guard_attrs
class SelectorIndex:
    """One index instance per kind (mirroring the two controllers)."""

    # every row/column plane, interner, and cache below moves only under
    # the single per-index RLock; `*_locked` helpers run with it held
    # (callers outside this class take it explicitly — see
    # devicestate's `with ks.index._lock:` probe path)
    GUARDED_BY = {
        "_probe_cache": "self._lock",
        "_gen": "self._lock",
        "_pod_rows": "self._lock",
        "_row_pods": "self._lock",
        "_row_keys": "self._lock",
        "_row_ns_names": "self._lock",
        "_row_labels": "self._lock",
        "_row_prev": "self._lock",
        "_free_rows": "self._lock",
        "_pcap": "self._lock",
        "_pod_valid": "self._lock",
        "_pod_ns": "self._lock",
        "_pod_ns_exists": "self._lock",
        "_pod_label": "self._lock",
        "_ns_label": "self._lock",
        "_thr_cols": "self._lock",
        "_col_thrs": "self._lock",
        "_col_keys": "self._lock",
        "_free_cols": "self._lock",
        "_tcap": "self._lock",
        "_thr_valid": "self._lock",
        "_namespaces": "self._lock",
        "_ns_label_ids": "self._lock",
    }

    def __init__(
        self,
        kind: str,
        pod_capacity: int = 64,
        throttle_capacity: int = 16,
        use_native: bool = True,
        interner=None,
    ):
        assert kind in ("throttle", "clusterthrottle")
        self.kind = kind
        self._lock = make_rlock(f"index.{kind}")

        # label keys+values share one pool with the store's arena when the
        # owner wires it (``interner``) — one interning per string per
        # process instead of one per kind
        self._values = interner if interner is not None else _Interner()
        self._ns_ids = _Interner()
        self._key_ids = self._values if interner is not None else _Interner()
        # columnar-store materializer (Store.materialize_pod). When set,
        # the index retains NO pod objects (see module docstring).
        self.pod_resolver: Optional[callable] = None

        # probe-row cache for NOT-stored pods (the PreFilter common case):
        # a selector match depends only on (namespace, labels), and the
        # scheduler retries the same Pending pod across backoff cycles, so
        # repeats skip the O(T) compiled-column evaluation. Invalidated
        # wholesale by bumping _gen on any column/namespace change.
        self._probe_cache: "OrderedDict[tuple, Tuple[int, np.ndarray]]" = OrderedDict()
        self._gen = 0

        # native C++ row-match tier (kube_throttler_tpu/native/ktnative.cpp); None → pure Python
        self._native: Optional[NativeRowEngine] = None
        if use_native:
            try:
                self._native = NativeRowEngine(kind)
            except RuntimeError:
                pass

        # pods
        self._pod_rows: Dict[str, int] = {}
        # row → retained event object (LEGACY/standalone mode only: with a
        # pod_resolver the index retains no objects and the three light
        # row-meta dicts below carry what matching needs — the key, the
        # namespace name, and a reference to the store's SHARED interned
        # labels dict)
        self._row_pods: Dict[int, Pod] = {}
        self._row_keys: Dict[int, str] = {}
        self._row_ns_names: Dict[int, str] = {}
        self._row_labels: Dict[int, dict] = {}
        # single-slot previous (row, object, mask-row) cache: lets the
        # MODIFIED handler's old-side affected query reuse the row the index
        # JUST replaced instead of re-evaluating T columns. One slot is
        # enough — the consumer runs inside the SAME store dispatch (store
        # lock held), before the next pod event can overwrite it — and keeps
        # the cache O(tcap) bytes instead of growing per churned row. It is
        # dropped on any column/namespace change (it must never outlive the
        # compiled columns it was computed against).
        self._row_prev: Optional[Tuple[int, Pod, np.ndarray]] = None
        self._free_rows: List[int] = []
        self._pcap = pod_capacity
        self._pod_valid = np.zeros(self._pcap, dtype=bool)
        self._pod_ns = np.full(self._pcap, _MISSING, dtype=np.int32)
        self._pod_ns_exists = np.zeros(self._pcap, dtype=bool)
        # label columns: key -> int32[pcap] (pod labels / pod's-ns labels)
        self._pod_label: Dict[str, np.ndarray] = {}
        self._ns_label: Dict[str, np.ndarray] = {}

        # throttles
        self._thr_cols: Dict[str, int] = {}
        self._col_thrs: Dict[int, AnyThrottle] = {}
        # col -> key mirror of _col_thrs: affected-throttle lookups are the
        # per-event ingest hot path (20+ matched cols per pod at full
        # scale), and thr.key re-derives the "ns/name" string per call
        self._col_keys: Dict[int, str] = {}
        self._free_cols: List[int] = []
        self._tcap = throttle_capacity
        self._thr_valid = np.zeros(self._tcap, dtype=bool)

        # namespaces (labels, for clusterthrottle ns selectors)
        self._namespaces: Dict[str, Namespace] = {}
        # interned {key_id: value_id} per namespace, for the native row path
        self._ns_label_ids: Dict[str, Dict[int, int]] = {}

        # sparse match matrix: per-row SORTED matched cols (sentinel-padded)
        # + per-row counts; kcap tracks the max per-pod match count.
        # DELIBERATELY outside the GUARDED_BY table: mutations all run
        # under self._lock, but the public sparse accessors below read
        # lock-free under the owner's single-mutator coherence — exactly
        # the stance external consumers of the former dense ``mask``
        # plane took (devicestate reads these under ITS main lock, which
        # serializes against the mutating dispatch path).
        self._kcap = 8
        self._row_cols = np.full((self._pcap, self._kcap), _SENT, dtype=np.int32)
        self._row_n = np.zeros(self._pcap, dtype=np.int32)

    @property
    def mask(self) -> np.ndarray:
        """Dense ``bool[pcap, tcap]`` materialized from the sparse rows —
        compatibility/readout surface (tests, the dense-kernel device
        mask). O(P×T) memory: production hot paths use the sparse
        accessors instead (``row_cols`` / ``rows_of_col`` / ...)."""
        with self._lock:
            dense = np.zeros((self._pcap, self._tcap), dtype=bool)
            valid = self._row_cols != _SENT
            if valid.any():
                rows = np.nonzero(valid)[0]
                dense[rows, self._row_cols[valid]] = True
            return dense

    # ----------------------------------------------------- sparse row plane

    def _grow_k_locked(self) -> None:
        new_k = self._kcap * 2
        grown = np.full((self._row_cols.shape[0], new_k), _SENT, dtype=np.int32)
        grown[:, : self._kcap] = self._row_cols
        self._row_cols = grown
        self._kcap = new_k

    def _set_row_sparse_locked(self, row: int, cols: np.ndarray) -> None:
        """Replace one row's matched-col set (``cols`` sorted ascending,
        no sentinel)."""
        n = int(cols.size)
        while n > self._kcap:
            self._grow_k_locked()
        rc = self._row_cols[row]
        rc[:] = _SENT
        rc[:n] = cols
        self._row_n[row] = n

    def _rows_of_col_locked(self, col: int) -> np.ndarray:
        """Rows currently containing ``col`` — O(P×kcap) vectorized scan
        (column membership changes are the rare direction; rows are the
        hot one)."""
        return np.nonzero((self._row_cols == col).any(axis=1))[0]

    def _set_col_sparse_locked(self, col: int, match: np.ndarray) -> None:
        """Make column ``col``'s membership equal ``match`` (bool[pcap])
        by diffing against the rows that currently hold it."""
        new_rows = np.flatnonzero(match[: self._row_cols.shape[0]])
        old_rows = self._rows_of_col_locked(col)
        remove = np.setdiff1d(old_rows, new_rows, assume_unique=True)
        insert = np.setdiff1d(new_rows, old_rows, assume_unique=True)
        if remove.size:
            sub = self._row_cols[remove]
            sub[sub == col] = _SENT
            sub.sort(axis=1)
            self._row_cols[remove] = sub
            self._row_n[remove] -= 1
        if insert.size:
            while int(self._row_n[insert].max()) + 1 > self._kcap:
                self._grow_k_locked()
            sub = self._row_cols[insert]
            sub[np.arange(insert.size), self._row_n[insert]] = col
            sub.sort(axis=1)
            self._row_cols[insert] = sub
            self._row_n[insert] += 1

    # public sparse accessors (devicestate's hot-path reads; all return
    # COPIES unless noted — callers run outside this lock by the same
    # single-mutator coherence the dense mask relied on)

    def row_cols(self, row: int) -> np.ndarray:
        """Sorted matched cols of one row (copy)."""
        return self._row_cols[row, : self._row_n[row]].copy()

    def rows_of_col(self, col: int) -> np.ndarray:
        return np.nonzero((self._row_cols == col).any(axis=1))[0]

    def row_has_col(self, row: int, col: int) -> bool:
        n = int(self._row_n[row])
        rc = self._row_cols[row, :n]
        i = int(np.searchsorted(rc, col))
        return i < n and rc[i] == col

    def row_cols_block(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(cols block with sentinel padding, per-row counts) for a set of
        rows — the aggregate-rebase gather."""
        return self._row_cols[rows], self._row_n[rows]

    def sparse_snapshot(self) -> Tuple[np.ndarray, np.ndarray, int]:
        """(row_cols, row_n, kcap) LIVE references — the device cols
        rebuild reads them under the owner's coherence rules."""
        return self._row_cols, self._row_n, self._kcap

    def nnz_max(self) -> int:
        return int(self._row_n.max()) if self._row_n.size else 0

    def mask_rows(self, rows: np.ndarray) -> np.ndarray:
        """Dense bool[len(rows), tcap] of the given rows."""
        with self._lock:
            out = np.zeros((len(rows), self._tcap), dtype=bool)
            sub = self._row_cols[rows]
            valid = sub != _SENT
            if valid.any():
                rr = np.nonzero(valid)[0]
                out[rr, sub[valid]] = True
            return out

    # ------------------------------------------------------------------ pods

    def _pod_col_array_locked(self, store: Dict[str, np.ndarray], key: str) -> np.ndarray:
        arr = store.get(key)
        if arr is None:
            arr = np.full(self._pcap, _MISSING, dtype=np.int32)
            store[key] = arr
        return arr

    def _grow_pods_locked(self) -> None:
        assert_held(self._lock, "SelectorIndex._grow_pods_locked")
        new_cap = self._pcap * 2
        self._pod_valid = np.resize(self._pod_valid, new_cap)
        self._pod_valid[self._pcap :] = False
        grown_ns = np.full(new_cap, _MISSING, dtype=np.int32)
        grown_ns[: self._pcap] = self._pod_ns
        self._pod_ns = grown_ns
        grown_exists = np.zeros(new_cap, dtype=bool)
        grown_exists[: self._pcap] = self._pod_ns_exists
        self._pod_ns_exists = grown_exists
        for store in (self._pod_label, self._ns_label):
            for key, arr in store.items():
                grown = np.full(new_cap, _MISSING, dtype=np.int32)
                grown[: self._pcap] = arr
                store[key] = grown
        grown_rc = np.full((new_cap, self._kcap), _SENT, dtype=np.int32)
        grown_rc[: self._pcap] = self._row_cols
        self._row_cols = grown_rc
        grown_n = np.zeros(new_cap, dtype=np.int32)
        grown_n[: self._pcap] = self._row_n
        self._row_n = grown_n
        self._pcap = new_cap

    def _upsert_pod_locked(self, pod: Pod) -> Tuple[int, bool]:
        """Row assignment + label-column writes for one pod (no re-match).
        Returns ``(row, needs_recompute)`` — False when the update could
        not have moved the mask row (labels+namespace unchanged)."""
        assert_held(self._lock, "SelectorIndex._upsert_pod_locked")
        row = self._pod_rows.get(pod.key)
        fresh = row is None
        if fresh:
            if self._free_rows:
                row = self._free_rows.pop()
            else:
                row = len(self._pod_rows)
                while row >= self._pcap:
                    self._grow_pods_locked()
            self._pod_rows[pod.key] = row
        if self.pod_resolver is None:
            prev = self._row_pods.get(row)
            if prev is not None and prev is not pod:
                self._row_prev = (row, prev, self.row_cols(row))
            self._row_pods[row] = pod
            prev_labels = prev.labels if prev is not None else None
            prev_ns = prev.namespace if prev is not None else None
        else:
            # no-retention mode: remember only (key, ns, shared-labels-ref)
            prev_labels = None if fresh else self._row_labels.get(row)
            prev_ns = None if fresh else self._row_ns_names.get(row)
            self._row_keys[row] = pod.key
            self._row_ns_names[row] = pod.namespace
            self._row_labels[row] = pod.labels
        self._pod_valid[row] = True

        # Selector matching reads only (pod.labels, pod.namespace) — the
        # namespace-side inputs (existence, ns labels) are maintained by
        # upsert_namespace, which recomputes affected rows itself. So a
        # pod update that changes neither (the dominant churn shape:
        # requests/status-only updates) cannot flip this mask row, and
        # the O(T) column sweep is skipped entirely. With an arena-backed
        # store the labels compare is usually an identity hit (shared
        # interned dicts).
        if (
            prev_labels is not None
            and (prev_labels is pod.labels or prev_labels == pod.labels)
            and prev_ns == pod.namespace
        ):
            return row, False

        self._pod_ns[row] = self._ns_ids.id_of(pod.namespace)
        self._pod_ns_exists[row] = pod.namespace in self._namespaces

        seen: Set[str] = set()
        for key, value in pod.labels.items():
            self._pod_col_array_locked(self._pod_label, key)[row] = self._values.id_of(value)
            seen.add(key)
        for key, arr in self._pod_label.items():
            if key not in seen:
                arr[row] = _MISSING

        ns = self._namespaces.get(pod.namespace)
        ns_labels = ns.labels if ns else {}
        seen = set()
        for key, value in ns_labels.items():
            self._pod_col_array_locked(self._ns_label, key)[row] = self._values.id_of(value)
            seen.add(key)
        for key, arr in self._ns_label.items():
            if key not in seen:
                arr[row] = _MISSING
        return row, True

    def upsert_pod(self, pod: Pod) -> int:
        """Insert or update a pod; recomputes its mask row. Returns the row."""
        with self._lock:
            row, recompute = self._upsert_pod_locked(pod)
            if recompute:
                self._recompute_row_locked(row, pod=pod)
            return row

    def upsert_pods_batch(self, pods: Sequence[Pod]) -> List[int]:
        """Batch upsert under ONE lock hold: every pod's label columns are
        written FIRST, then one re-match pass recomputes exactly the rows
        whose matching inputs moved. Correctness rests on row independence
        — a row's re-match reads only its own label entries and the
        compiled columns, so deferring it past the other pods' column
        writes cannot change its result (the per-event path interleaves
        them; both orders are property-tested equal). Returns the rows in
        input order."""
        with self._lock:
            rows: List[int] = []
            pending: List[Tuple[int, Pod]] = []
            for pod in pods:
                row, recompute = self._upsert_pod_locked(pod)
                rows.append(row)
                if recompute:
                    pending.append((row, pod))
            for row, pod in pending:
                self._recompute_row_locked(row, pod=pod)
            return rows

    def remove_pod(self, pod_key: str) -> None:
        with self._lock:
            row = self._pod_rows.pop(pod_key, None)
            if row is None:
                return
            self._row_pods.pop(row, None)
            self._row_keys.pop(row, None)
            self._row_ns_names.pop(row, None)
            self._row_labels.pop(row, None)
            if self._row_prev is not None and self._row_prev[0] == row:
                self._row_prev = None
            self._pod_valid[row] = False
            self._row_cols[row, :] = _SENT
            self._row_n[row] = 0
            self._free_rows.append(row)

    # ------------------------------------------------------------- throttles

    def upsert_throttle(self, thr: AnyThrottle) -> int:
        with self._lock:
            self._gen += 1  # compiled columns change → probe cache stale
            key = thr.key
            col = self._thr_cols.get(key)
            if col is None:
                if self._free_cols:
                    col = self._free_cols.pop()
                else:
                    col = len(self._thr_cols)
                    while col >= self._tcap:
                        self._grow_throttles_locked()
                self._thr_cols[key] = col
            self._col_thrs[col] = thr
            self._col_keys[col] = key
            self._thr_valid[col] = True
            self._row_prev = None  # compiled columns changed
            if self._native is not None:
                self._native_sync_col_locked(col, thr)
            self._recompute_col_locked(col)
            return col

    def refresh_throttle_object(self, thr: AnyThrottle) -> Optional[int]:
        """Swap the stored object for an update that did NOT change the
        selector (e.g. a status write-back): no column recompute, no mask
        change — a [P]-wide re-match per status echo would make every
        reconcile O(pods). Returns the column, or None if not indexed."""
        with self._lock:
            col = self._thr_cols.get(thr.key)
            if col is None:
                return None
            self._col_thrs[col] = thr
            return col

    def _grow_throttles_locked(self) -> None:
        new_cap = self._tcap * 2
        grown_valid = np.zeros(new_cap, dtype=bool)
        grown_valid[: self._tcap] = self._thr_valid
        self._thr_valid = grown_valid
        # sparse rows carry column IDS — tcap growth changes nothing there
        self._tcap = new_cap
        if self._native is not None:
            self._native.reserve(new_cap)

    def remove_throttle(self, throttle_key: str) -> None:
        with self._lock:
            self._gen += 1
            col = self._thr_cols.pop(throttle_key, None)
            if col is None:
                return
            self._col_thrs.pop(col, None)
            self._col_keys.pop(col, None)
            self._thr_valid[col] = False
            self._row_prev = None  # compiled columns changed
            self._set_col_sparse_locked(col, np.zeros(self._pcap, dtype=bool))
            self._free_cols.append(col)
            if self._native is not None:
                self._native.clear_col(col)

    # ------------------------------------------------------------ namespaces

    def upsert_namespace(self, ns: Namespace) -> None:
        """Namespace (re)definition: refresh ns-label columns of its pods and
        recompute their rows (cluster selectors may flip)."""
        with self._lock:
            if self.kind == "clusterthrottle":
                # ns existence/labels feed clusterthrottle probe matches;
                # throttle matching reads only thr.namespace == pod.namespace
                # (in the cache key), so that kind's cache survives ns churn
                self._gen += 1
            self._namespaces[ns.name] = ns
            self._ns_label_ids.pop(ns.name, None)
            self._row_prev = None  # ns labels feed clusterthrottle matches
            if self.kind != "clusterthrottle":
                return
            ns_id = self._ns_ids.id_of(ns.name)
            rows = np.nonzero(self._pod_valid & (self._pod_ns == ns_id))[0]
            self._pod_ns_exists[rows] = True
            for row in rows:
                seen: Set[str] = set()
                for key, value in ns.labels.items():
                    self._pod_col_array_locked(self._ns_label, key)[row] = self._values.id_of(value)
                    seen.add(key)
                for key, arr in self._ns_label.items():
                    if key not in seen:
                        arr[row] = _MISSING
                self._recompute_row_locked(int(row))

    def remove_namespace(self, name: str) -> None:
        """Namespace deletion: its pods can no longer match any
        ClusterThrottle (the oracle requires the Namespace object —
        clusterthrottle_controller.go:273-276 answers ERROR for pods of an
        unknown namespace, and an unknown namespace matches no selector).
        Throttle-kind matching ignores Namespace objects entirely, so that
        kind only drops its bookkeeping."""
        with self._lock:
            self._namespaces.pop(name, None)
            self._ns_label_ids.pop(name, None)
            self._row_prev = None
            if self.kind != "clusterthrottle":
                return
            self._gen += 1  # existence feeds clusterthrottle probe matches
            ns_id = self._ns_ids.id_of(name)
            rows = np.nonzero(self._pod_valid & (self._pod_ns == ns_id))[0]
            self._pod_ns_exists[rows] = False
            # every match path returns False for an absent Namespace (native
            # gate ktnative.cpp ns_exists; _match_one_locked/_eval_general_locked ns None),
            # so the rows' recompute result is provably all-False — clear
            # vectorized instead of O(rows × T) selector evaluations
            self._row_cols[rows, :] = _SENT
            self._row_n[rows] = 0

    # ------------------------------------------------------------- recompute

    def _term_col_match_locked(self, pairs: Dict[str, str], store: Dict[str, np.ndarray]) -> np.ndarray:
        """Vectorized: which pods satisfy all (key,value) pairs."""
        out = self._pod_valid.copy()
        for key, value in pairs.items():
            arr = store.get(key)
            if arr is None:
                out[:] = False
                return out
            out &= arr == self._values.id_of(value)
        return out

    def _selector_col_match_locked(self, selector, store: Dict[str, np.ndarray]) -> np.ndarray:
        """Vectorized column evaluation of one LabelSelector over interned
        label arrays — matchLabels AND matchExpressions, mirroring
        LabelSelector.matches (api/types.py:303-322). The caller validates
        the selector first (invalid → general tier)."""
        out = self._term_col_match_locked(selector.match_labels, store)
        for req in selector.match_expressions:
            arr = store.get(req.key)
            present = (
                (arr != _MISSING) if arr is not None
                else np.zeros(self._pcap, dtype=bool)
            )
            if req.operator == "In":
                if arr is None:
                    out[:] = False
                    return out
                ids = [self._values.id_of(v) for v in req.values]
                out &= present & np.isin(arr, ids)
            elif req.operator == "NotIn":
                if arr is not None:
                    ids = [self._values.id_of(v) for v in req.values]
                    out &= ~(present & np.isin(arr, ids))
            elif req.operator == "Exists":
                out &= present
            else:  # DoesNotExist
                out &= ~present
        return out

    def _recompute_col_locked(self, col: int) -> None:
        thr = self._col_thrs[col]
        try:
            # vectorized tier covers the full valid selector surface
            # (matchLabels + matchExpressions); validation errors fall to
            # the per-pod general tier for exact error confinement
            for term in thr.spec.selector.selector_terms:
                term.pod_selector.validate()
                if self.kind == "clusterthrottle":
                    term.namespace_selector.validate()
            match = np.zeros(self._pcap, dtype=bool)
            for term in thr.spec.selector.selector_terms:
                m = self._selector_col_match_locked(term.pod_selector, self._pod_label)
                if self.kind == "clusterthrottle":
                    m &= self._pod_ns_exists  # unknown namespace → no match
                    m &= self._selector_col_match_locked(
                        term.namespace_selector, self._ns_label
                    )
                match |= m
        except SelectorError:
            match = np.zeros(self._pcap, dtype=bool)
            for key, row in self._pod_rows.items():
                pod = self._resolve_row_pod_locked(row)
                if pod is not None:
                    match[row] = self._eval_general_locked(thr, pod)
        if isinstance(thr, Throttle):
            match &= self._pod_ns == self._ns_ids.id_of(thr.namespace)
        self._set_col_sparse_locked(col, match)

    def _resolve_row_pod_locked(self, row: int) -> Optional[Pod]:
        """The row's full Pod object: the retained one (legacy mode) or a
        lazy materialization through the store resolver (rare paths —
        general-tier evaluation, matched_pods)."""
        pod = self._row_pods.get(row)
        if pod is not None or self.pod_resolver is None:
            return pod
        key = self._row_keys.get(row)
        return self.pod_resolver(key) if key is not None else None

    _NATIVE_OPS = {
        "In": NativeRowEngine.OP_IN,
        "NotIn": NativeRowEngine.OP_NOT_IN,
        "Exists": NativeRowEngine.OP_EXISTS,
        "DoesNotExist": NativeRowEngine.OP_DOES_NOT_EXIST,
    }

    def _native_reqs_locked(self, selector) -> List[Tuple[int, int, Tuple[int, ...]]]:
        """Compile one LabelSelector to native requirements; raises
        SelectorError for invalid selectors (the caller routes those to the
        general tier, which preserves the exact error-confinement
        semantics of _eval_general_locked)."""
        selector.validate()
        reqs = [
            (
                self._key_ids.id_of(k),
                NativeRowEngine.OP_EQ,
                (self._values.id_of(v),),
            )
            for k, v in selector.match_labels.items()
        ]
        for expr in selector.match_expressions:
            reqs.append(
                (
                    self._key_ids.id_of(expr.key),
                    self._NATIVE_OPS[expr.operator],
                    tuple(self._values.id_of(v) for v in expr.values),
                )
            )
        return reqs

    def _native_sync_col_locked(self, col: int, thr: AnyThrottle) -> None:
        """Compile a throttle's selector into the native engine's column —
        matchLabels AND matchExpressions (In/NotIn/Exists/DoesNotExist);
        only selectors that fail validation stay on the Python general
        tier."""
        assert self._native is not None
        thr_ns = self._ns_ids.id_of(thr.namespace) if isinstance(thr, Throttle) else -1
        try:
            terms = []
            for term in thr.spec.selector.selector_terms:
                pr = self._native_reqs_locked(term.pod_selector)
                nr = (
                    self._native_reqs_locked(term.namespace_selector)
                    if isinstance(thr, ClusterThrottle)
                    else []
                )
                terms.append((pr, nr))
        except SelectorError:
            self._native.set_col_general(col, thr_ns)
            return
        self._native.set_col(col, thr_ns, terms)

    def _match_row_arbitrary_locked(self, pod: Pod) -> np.ndarray:
        """Evaluate a pod (not necessarily stored) against every compiled
        column → bool[tcap]. Native C++ tier when available."""
        return self._match_parts_locked(pod.namespace, pod.labels, lambda: pod)

    def _match_parts_locked(self, ns_name: str, labels: dict, pod_supplier) -> np.ndarray:
        """Row evaluation from its matching INPUTS — (namespace, labels) —
        so the no-retention index can recompute a stored row without
        materializing its pod. ``pod_supplier`` produces the full object
        only when a column needs the general tier (invalid selectors) or
        the pure-Python fallback; it may return None (row reads
        no-match)."""
        if self._native is not None:
            ns = self._namespaces.get(ns_name)
            pod_labels = {
                self._key_ids.id_of(k): self._values.id_of(v) for k, v in labels.items()
            }
            ns_labels = self._ns_label_ids.get(ns_name)
            if ns_labels is None:
                ns_labels = {
                    self._key_ids.id_of(k): self._values.id_of(v)
                    for k, v in (ns.labels if ns else {}).items()
                }
                self._ns_label_ids[ns_name] = ns_labels
            match, general = self._native.match_row(
                self._ns_ids.id_of(ns_name), ns is not None, pod_labels, ns_labels
            )
            out = np.zeros(self._tcap, dtype=bool)
            out[: len(match)] = match.astype(bool)
            gen_cols = np.nonzero(general)[0]
            if gen_cols.size:
                pod = pod_supplier()
                for col in gen_cols:
                    out[col] = (
                        self._eval_general_locked(self._col_thrs[int(col)], pod)
                        if pod is not None
                        else False
                    )
            return out
        out = np.zeros(self._tcap, dtype=bool)
        pod = pod_supplier()
        if pod is None:
            return out
        for key, col in self._thr_cols.items():
            out[col] = self._match_one_locked(self._col_thrs[col], pod)
        return out

    _PROBE_CACHE_MAX = 4096

    def match_row_cached_locked(self, pod: Pod) -> np.ndarray:
        """``_match_row_arbitrary_locked`` behind a (namespace, labels)-keyed LRU.

        Caller must hold ``_lock``. The returned array is SHARED with the
        cache — treat it as read-only. Correctness: a selector match reads
        nothing of the pod beyond namespace + labels (``_match_one_locked``), and
        ``_gen`` is bumped by every column or namespace mutation, so a hit
        can never serve a stale compiled-column evaluation."""
        assert_held(self._lock, "SelectorIndex.match_row_cached_locked")
        key = (pod.namespace, frozenset(pod.labels.items()))
        hit = self._probe_cache.get(key)
        if hit is not None and hit[0] == self._gen:
            self._probe_cache.move_to_end(key)
            return hit[1]
        row = self._match_row_arbitrary_locked(pod)
        self._probe_cache[key] = (self._gen, row)
        # assignment to an existing (gen-stale) key keeps its old LRU slot;
        # a just-refreshed hot entry must not be the next eviction victim
        self._probe_cache.move_to_end(key)
        if len(self._probe_cache) > self._PROBE_CACHE_MAX:
            self._probe_cache.popitem(last=False)
        return row

    def _recompute_row_locked(self, row: int, pod: Optional[Pod] = None) -> None:
        """Re-match one stored row. ``pod`` (the upsert paths always have
        it in hand) short-circuits any materialization; without it the
        matching inputs come from the row meta and the full object is
        resolved lazily only if a general-tier column demands it."""
        if pod is not None:
            match = self._match_row_arbitrary_locked(pod)
        elif self.pod_resolver is None:
            match = self._match_row_arbitrary_locked(self._row_pods[row])
        else:
            match = self._match_parts_locked(
                self._row_ns_names.get(row, ""),
                self._row_labels.get(row, {}),
                lambda: self._resolve_row_pod_locked(row),
            )
        self._set_row_sparse_locked(row, np.flatnonzero(match).astype(np.int32))

    def _match_one_locked(self, thr: AnyThrottle, pod: Pod) -> bool:
        """Single-pair oracle used by row recompute AND external callers
        (e.g. the not-yet-indexed-pod fallback) — it must apply the FULL
        affected-throttle predicate, including Throttle namespace equality
        and ClusterThrottle namespace existence."""
        if isinstance(thr, Throttle) and thr.namespace != pod.namespace:
            return False
        simple = _simple_terms(thr)
        if simple is not None:
            if self.kind == "clusterthrottle":
                ns = self._namespaces.get(pod.namespace)
                if ns is None:
                    # a pod whose Namespace object is unknown can never match
                    # a ClusterThrottle (the oracle path errors; the mask
                    # reads no-match — clusterthrottle_controller.go:273-276)
                    return False
                ns_labels = ns.labels
            else:
                ns_labels = {}
            for pod_pairs, ns_pairs in simple:
                if all(pod.labels.get(k) == v for k, v in pod_pairs.items()):
                    if self.kind == "clusterthrottle":
                        if all(ns_labels.get(k) == v for k, v in ns_pairs.items()):
                            return True
                    else:
                        return True
            return False
        return self._eval_general_locked(thr, pod)

    def _eval_general_locked(self, thr: AnyThrottle, pod: Pod) -> bool:
        try:
            if isinstance(thr, Throttle):
                return thr.spec.selector.matches_to_pod(pod)
            ns = self._namespaces.get(pod.namespace)
            if ns is None:
                return False
            return thr.spec.selector.matches_to_pod(pod, ns)
        except SelectorError:
            # an invalid selector term fails that term; the reference
            # propagates the error per-call — confining it to no-match keeps
            # the index total (callers re-raise on direct evaluation paths)
            return False

    # --------------------------------------------------------------- queries

    def affected_throttle_keys(self, pod_key: str) -> List[str]:
        """Keys of throttles matching the pod (affectedThrottles batched).
        O(K) via the col→object map — an inverted {col: key} dict built
        per call would be O(T) and dominated full-scale event ingest."""
        with self._lock:
            if not self._col_thrs:
                return []
            row = self._pod_rows.get(pod_key)
            if row is None:
                return []
            cols = self._row_cols[row, : self._row_n[row]]
            ck = self._col_keys
            return [ck[c] for c in cols.tolist() if c in ck]

    def affected_throttle_keys_for(self, pod: Pod) -> List[str]:
        """affectedThrottles for an ARBITRARY pod object.

        When the queried object is exactly the indexed one, this is an O(K)
        mask-row read. Otherwise (a pod version the index has moved past —
        e.g. the old side of a MODIFIED event — or a pod not yet stored) the
        row is evaluated fresh against every compiled column, without
        mutating the index."""
        with self._lock:
            if not self._col_thrs:
                return []
            row = self._pod_rows.get(pod.key)
            if row is not None and (
                self._row_pods.get(row) is pod
                # no-retention identity: the arena canonicalizes labels to
                # shared dicts, so the current row version is recognizable
                # by (labels identity, namespace) without keeping the object
                or (
                    self.pod_resolver is not None
                    and self._row_labels.get(row) is pod.labels
                    and self._row_ns_names.get(row) == pod.namespace
                )
            ):
                cols = self._row_cols[row, : self._row_n[row]]
            else:
                prev = self._row_prev
                if prev is not None and prev[0] == row and prev[1] is pod:
                    # the old side of the MODIFIED event the index just
                    # processed: its row was saved before the overwrite
                    pc = prev[2]
                    cols = pc[self._thr_valid[pc]]
                else:
                    cols = np.nonzero(self.match_row_cached_locked(pod) & self._thr_valid)[0]
            ck = self._col_keys
            return [ck[c] for c in cols.tolist() if c in ck]

    def matched_pod_keys(self, throttle_key: str) -> List[str]:
        """Pod keys matching a throttle (affectedPods' selector part)."""
        with self._lock:
            col = self._thr_cols.get(throttle_key)
            if col is None:
                return []
            rows = self._rows_of_col_locked(col)
            if self.pod_resolver is not None:
                rk = self._row_keys
                return [rk[int(r)] for r in rows if int(r) in rk]
            row_to_key = {row: key for key, row in self._pod_rows.items()}
            return [row_to_key[r] for r in rows if r in row_to_key]

    def matched_pods(self, throttle_key: str) -> List[Pod]:
        """The indexed Pod objects matching a throttle (latest store
        state). In no-retention mode the objects are materialized through
        the resolver OUTSIDE the index lock (lock order: the resolver
        takes the store lock, which must never nest inside this one)."""
        keys: Optional[List[str]] = None
        with self._lock:
            col = self._thr_cols.get(throttle_key)
            if col is None:
                return []
            rows = self._rows_of_col_locked(col)
            if self.pod_resolver is None:
                return [self._row_pods[int(r)] for r in rows if int(r) in self._row_pods]
            rk = self._row_keys
            keys = [rk[int(r)] for r in rows if int(r) in rk]
        out = []
        for key in keys:
            pod = self.pod_resolver(key)
            if pod is not None:
                out.append(pod)
        return out

    def indexed_pod(self, pod_key: str) -> Optional[Pod]:
        with self._lock:
            row = self._pod_rows.get(pod_key)
            if row is None:
                return None
            pod = self._row_pods.get(row)
            if pod is not None or self.pod_resolver is None:
                return pod
        return self.pod_resolver(pod_key)

    def mask_cell(self, pod_key: str, throttle_key: str) -> bool:
        """Does the indexed pod currently match the throttle?"""
        with self._lock:
            row = self._pod_rows.get(pod_key)
            col = self._thr_cols.get(throttle_key)
            if row is None or col is None:
                return False
            return self.row_has_col(row, col)

    def pod_row(self, pod_key: str) -> Optional[int]:
        with self._lock:
            return self._pod_rows.get(pod_key)

    def throttle_col(self, throttle_key: str) -> Optional[int]:
        with self._lock:
            return self._thr_cols.get(throttle_key)

    def throttle_cols_snapshot(self) -> Dict[str, int]:
        """One-lock-hold copy of the live throttle-key → column map (the
        snapshot/recovery plane walk iterates it outside the lock)."""
        with self._lock:
            return dict(self._thr_cols)

    def generation(self) -> int:
        """Monotonic matching generation: bumped by every column or
        namespace mutation (exactly the probe-cache invalidation signal).
        The verdict cache's per-pod fingerprint memo revalidates against
        this — a stale memo would key verdicts on an outdated matched-cols
        set, which is a correctness bug, not just a perf one."""
        with self._lock:
            return self._gen

    def has_namespace(self, name: str) -> bool:
        """Is the Namespace object known to this index? The clusterthrottle
        oracle answers ERROR for pods of an unknown namespace
        (clusterthrottle_controller.go:273-276) — the verdict cache must
        refuse to fingerprint those pods, or they would collide with
        known-ns pods sharing the same (shape, accel, cols) key."""
        with self._lock:
            return name in self._namespaces

    @property
    def capacities(self) -> Tuple[int, int]:
        with self._lock:
            return self._pcap, self._tcap
