"""Atomic, checksummed, versioned snapshots of the standalone daemon's
full recoverable state.

The journal (engine/journal.py) already makes the Store durable, but a
journal-only restart replays from genesis (or the last compaction) and
recovers NOTHING that never flows through store events: standing
scheduler-cycle reservations and their TTL deadlines, and the published
``st_*`` device planes a recovering process must agree with. A snapshot
captures all of it at one consistent instant:

- every stored object (namespaces first, then throttles, then pods) as
  round-trippable manifest dicts (api/serialization.py);
- the store's resourceVersion high-water mark (restored so post-recovery
  writes never reuse a version an old client observed);
- per-kind reservation ledgers with TTLs serialized as REMAINING seconds —
  restore rebases them against the restored clock, so a deadline can never
  resurrect just because wall time moved while the process was dead;
- the published ``st_*`` devicestate planes per throttle key (recovery's
  divergence oracle: rebuilt planes must match restored statuses);
- the journal's ``(byte offset, sha256)`` at cut time — the tail-replay
  anchor (see engine/recovery.py).

File format: one JSON header line ``{"format", "version", "sha256",
"length"}`` followed by the JSON payload. The payload checksum makes a
torn or bit-rotted snapshot DETECTABLE, and the writer makes torn ones
IMPOSSIBLE to observe under its own name: payload is written to a temp
file in the same directory, fsynced, then atomically renamed to
``snapshot-<seq>.ktsnap`` (the directory is fsynced after the rename so
the new name itself survives a power cut). Recovery walks snapshots
newest-first and falls back to older ones on checksum failure.

Snapshots are cut on a journal-size trigger (``StoreJournal.set_snapshotter``)
and at graceful shutdown (cli.py). Consistency: the payload is gathered
under the store lock (reentrant when the trigger fires inside dispatch),
so objects, reservations, planes, and the journal position all describe
the same instant in the event stream.

Crash points (``crash.snapshot.*``, faults/plan.py) SIGKILL the writer at
every interesting instant — before the write, mid-tmp-file, before and
after the rename, and mid-prune — and the crash harness
(tools/crashtest.py) proves recovery survives each artifact.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import tempfile
from typing import Dict, List, Optional, Tuple

from ..faults.plan import maybe_crash
from ..utils.clock import Clock, RealClock
from ..utils.lockorder import guard_attrs, make_lock
from .store import Store

logger = logging.getLogger(__name__)

SNAPSHOT_FORMAT = "kube-throttler-snapshot"
# v1: every object (pods included) as manifest dicts in "objects".
# v2 (PR 11): pods move to a COLUMNAR block ("podColumns" — local string
# table + interned shape tables + per-pod id rows, engine/columnar.py) —
# ~30 bytes/pod instead of ~1 KB and no per-pod materialization on the
# write path. Readers accept both; writers emit v2 (with pods staying in
# "objects" only when the store runs the frozen-dict reference mode).
SNAPSHOT_VERSION = 2
# every entry here needs a ``snapshot:<v>`` row in version.FORMAT_REGISTRY
# (machine-checked by analysis/protocol.py): a version bump cannot land
# without declaring the minimum reader that replays it.
SUPPORTED_SNAPSHOT_VERSIONS = (1, 2)

_NAME_RE = re.compile(r"^snapshot-(\d{12})\.ktsnap$")


class SnapshotError(Exception):
    """A snapshot file that must not be trusted: bad header, unsupported
    version, truncated payload, or checksum mismatch."""


def snapshot_name(seq: int) -> str:
    return f"snapshot-{seq:012d}.ktsnap"


def find_snapshots(directory: str) -> List[Tuple[int, str]]:
    """``[(seq, path)]`` of well-NAMED snapshots, newest (highest seq)
    first. Validity is the loader's job — a corrupt file still lists, so
    recovery can count it as rejected and fall back."""
    out: List[Tuple[int, str]] = []
    try:
        entries = os.listdir(directory)
    except OSError:
        return out
    for name in entries:
        m = _NAME_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def parse_snapshot_bytes(blob: bytes, origin: str = "<bytes>") -> dict:
    """Parse + verify a snapshot from raw bytes (header line + payload) —
    the form a replication bootstrap receives over the wire. Raises
    :class:`SnapshotError` on any integrity failure."""
    header_line, _, body = blob.partition(b"\n")
    try:
        header = json.loads(header_line.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise SnapshotError(f"bad snapshot header in {origin}: {e}") from e
    if header.get("format") != SNAPSHOT_FORMAT:
        raise SnapshotError(f"{origin}: not a {SNAPSHOT_FORMAT} file")
    if header.get("version") not in SUPPORTED_SNAPSHOT_VERSIONS:
        raise SnapshotError(
            f"{origin}: unsupported snapshot version {header.get('version')!r} "
            f"(this reader supports {SUPPORTED_SNAPSHOT_VERSIONS}; upgrade "
            f"the reader, the writer was newer)"
        )
    length = int(header.get("length", -1))
    payload = body.rstrip(b"\n")
    if length != len(payload):
        raise SnapshotError(
            f"{origin}: truncated payload ({len(payload)} bytes, header says {length})"
        )
    digest = hashlib.sha256(payload).hexdigest()
    if digest != header.get("sha256"):
        raise SnapshotError(f"{origin}: payload checksum mismatch")
    try:
        return json.loads(payload.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:  # pragma: no cover — sha256 gate
        raise SnapshotError(f"{origin}: undecodable payload: {e}") from e


def load_snapshot(path: str) -> dict:
    """Parse + verify one snapshot file; returns the payload dict. Raises
    :class:`SnapshotError` on any integrity failure (the caller falls back
    to an older snapshot or to pure journal replay)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise SnapshotError(f"unreadable snapshot {path}: {e}") from e
    return parse_snapshot_bytes(blob, origin=path)


@guard_attrs
class SnapshotManager:
    """Cuts snapshots of a Store (+ reservations, + published planes) into
    a directory; prunes superseded ones; serves health/metrics probes.

    ``reservations`` ({kind: ReservedResourceAmounts}) and
    ``device_manager`` are late-bound attributes — the CLI wires them after
    the plugin exists. ``bind_journal`` arms the journal-size trigger and
    makes every snapshot record the journal tail anchor."""

    # _seq moves under the lock; the stats below are single-writer values
    # read by health/metrics probes — unguarded on purpose (same stance as
    # the journal's robustness counters)
    GUARDED_BY = {"_seq": "self._lock"}

    def __init__(
        self,
        directory: str,
        store: Store,
        reservations: Optional[Dict[str, object]] = None,
        device_manager=None,
        clock: Optional[Clock] = None,
        keep: int = 3,
        faults=None,
        gang_ledger=None,
    ):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.store = store
        self.reservations = reservations or {}
        # gang ledger (engine/gang.py): its lock is held around the
        # reservation + gang capture below, which is what makes every
        # snapshot gang-ATOMIC — a half-formed group reserve can never be
        # observed by a snapshot, so recovery is always fully-reserved or
        # fully-rolled-back
        self.gang_ledger = gang_ledger
        self.device_manager = device_manager
        self.clock = clock or RealClock()
        self.keep = max(1, int(keep))
        self.faults = faults
        self.journal = None
        # HA fencing (engine/replication.py): when bound and stale, write()
        # refuses — a deposed leader must not publish snapshots a standby
        # could later bootstrap from
        self.fencing = None
        self._lock = make_lock("snapshot")
        existing = find_snapshots(directory)
        self._seq = existing[0][0] if existing else 0
        # single-writer stats (health/metrics probes read these)
        self.snapshots_written = 0
        self.snapshot_failures = 0
        self.stale_epoch_rejected = 0
        self.last_snapshot_time = None  # datetime (self.clock domain)
        self.last_snapshot_seq: Optional[int] = None
        self.last_snapshot_path: Optional[str] = None
        self.last_snapshot_reason: Optional[str] = None

    def bind_journal(self, journal, every_lines: int) -> None:
        """Record journal positions in snapshots and cut one every
        ``every_lines`` appended journal lines."""
        self.journal = journal
        journal.set_snapshotter(self, every_lines)

    # -- write --------------------------------------------------------------

    def snapshot_on_journal_trigger(self) -> None:
        """Journal-size trigger entry point — called from inside the
        store's dispatch (store lock held, journal lock released). Never
        raises into the dispatch path."""
        try:
            self.write(reason="journal-size")
        except Exception:  # noqa: BLE001 — dispatch must survive any writer bug
            logger.exception("snapshot trigger failed; ingest continues")

    def _gather(self, reason: str, seq: int) -> dict:
        """Materialize the payload under ONE store-lock hold (reentrant
        when triggered from dispatch), so objects, reservations, planes,
        and the journal anchor describe the same instant."""
        import contextlib

        from ..api.serialization import object_to_dict

        with self.store._lock:  # noqa: SLF001 — same-package access
            now = self.clock.now()
            objs = []
            for ns in self.store.list_namespaces():
                objs.append(object_to_dict(ns))
            for thr in self.store.list_throttles():
                objs.append(object_to_dict(thr))
            for thr in self.store.list_cluster_throttles():
                objs.append(object_to_dict(thr))
            arena = self.store.pod_arena
            pod_columns = None
            if arena is not None:
                # v2 columnar pod block: exported straight from the arena
                # (no per-pod materialization on the snapshot path)
                pod_columns = arena.export_columns(list(arena.keys()))
            else:
                for pod in self.store.list_pods():
                    objs.append(object_to_dict(pod))
            epoch = 0
            if self.fencing is not None:
                epoch = self.fencing.current()
            elif self.journal is not None:
                epoch = self.journal.last_epoch
            # the gang lock spans the reservation AND gang captures: a
            # reserve_group in flight holds it for its whole member loop,
            # so this gather waits it out and never sees a partial group
            # (lock order store → gang → reservation locks)
            gang_guard = (
                self.gang_ledger.lock
                if self.gang_ledger is not None
                else contextlib.nullcontext()
            )
            with gang_guard:
                payload = {
                    "seq": seq,
                    "reason": reason,
                    "epoch": epoch,
                    "takenAt": now.isoformat(),
                    "rv": self.store.latest_resource_version,
                    "objects": objs,
                    **({"podColumns": pod_columns} if pod_columns is not None else {}),
                    "reservations": {
                        kind: cache.snapshot_state(now)
                        for kind, cache in self.reservations.items()
                    },
                    "gangs": (
                        self.gang_ledger.snapshot_state(now)
                        if self.gang_ledger is not None
                        else None
                    ),
                    # open (begin-without-commit) preemptions at cut time:
                    # a tail-mode recovery whose anchor sits past the
                    # PREEMPT begin line still learns which eviction to
                    # roll back (engine/journal.py open_preempts; store →
                    # journal lock order, the dispatch path's own)
                    "preempts": (
                        self.journal.open_preempts()
                        if self.journal is not None
                        else None
                    ),
                    "published": (
                        self.device_manager.published_flags()
                        if self.device_manager is not None
                        else None
                    ),
                    "journal": (
                        dict(zip(("offset", "sha256"), self.journal.position()))
                        if self.journal is not None
                        else None
                    ),
                }
        return payload

    def write(self, reason: str = "manual") -> Optional[str]:
        """Cut one snapshot; returns its path, or None on an I/O failure
        (counted; the journal is still intact, so a failed snapshot only
        costs recovery speed, never correctness) — or None, counted
        separately, when this replica's fencing epoch has gone stale (a
        deposed leader must stop publishing snapshots)."""
        if self.fencing is not None and self.fencing.is_stale():
            self.stale_epoch_rejected += 1
            logger.warning(
                "snapshot (%s) refused: fencing epoch %d is stale",
                reason, self.fencing.current(),
            )
            return None
        maybe_crash(self.faults, "crash.snapshot.begin")
        with self._lock:
            self._seq += 1
            seq = self._seq
        try:
            payload = self._gather(reason, seq)
            path = self._write_atomic(payload, seq)
        except OSError:
            self.snapshot_failures += 1
            logger.warning(
                "snapshot %d (%s) failed; journal remains the recovery "
                "source", seq, reason, exc_info=True,
            )
            return None
        self.snapshots_written += 1
        self.last_snapshot_time = self.clock.now()
        self.last_snapshot_seq = seq
        self.last_snapshot_path = path
        self.last_snapshot_reason = reason
        self._prune()
        logger.info(
            "snapshot %s written (%s, %d objects)",
            path, reason, len(payload["objects"]),
        )
        return path

    def _write_atomic(self, payload: dict, seq: int) -> str:
        data = json.dumps(payload).encode("utf-8")
        header = json.dumps(
            {
                "format": SNAPSHOT_FORMAT,
                "version": SNAPSHOT_VERSION,
                "sha256": hashlib.sha256(data).hexdigest(),
                "length": len(data),
                # fencing epoch in the HEADER too: replication can answer
                # "whose term is this snapshot from" without parsing the
                # payload (loaders ignore unknown header keys)
                "epoch": payload.get("epoch", 0),
            }
        ).encode("utf-8")
        blob = header + b"\n" + data + b"\n"
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob[: len(blob) // 2])
                f.flush()
                # half the tmp file flushed, nothing renamed: the artifact
                # recovery must IGNORE (and clean up) without rejecting the
                # older complete snapshots next to it
                maybe_crash(self.faults, "crash.snapshot.tmp_partial")
                f.write(blob[len(blob) // 2 :])
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        final = os.path.join(self.directory, snapshot_name(seq))
        # tmp is complete + fsynced but unnamed: recovery sees only the
        # previous snapshots
        maybe_crash(self.faults, "crash.snapshot.pre_rename")
        # HA kill site: the leader dies mid-snapshot during a failover run
        # (tmp complete, rename pending) — the standby must promote from
        # the replicated journal, ignoring the orphan tmp
        maybe_crash(self.faults, "ha.snapshot.write")
        os.replace(tmp, final)
        self._fsync_dir()
        # renamed but superseded snapshots not yet pruned: recovery must
        # pick THIS one (highest seq) and ignore the stragglers
        maybe_crash(self.faults, "crash.snapshot.post_rename")
        return final

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(self.directory, os.O_RDONLY)
        except OSError:  # pragma: no cover — platform without dir-open
            return
        try:
            os.fsync(dfd)
        except OSError:  # pragma: no cover
            pass
        finally:
            os.close(dfd)

    def _prune(self) -> None:
        """Unlink snapshots beyond the newest ``keep`` (best effort — a
        crash mid-prune just leaves extra old snapshots for next time)."""
        for _seq, path in find_snapshots(self.directory)[self.keep :]:
            try:
                os.unlink(path)
            except OSError:  # pragma: no cover — racing an external cleaner
                continue
            maybe_crash(self.faults, "crash.snapshot.prune")

    # -- probes -------------------------------------------------------------

    def snapshot_age_seconds(self) -> Optional[float]:
        """Seconds since the last snapshot THIS process wrote (None before
        the first one — recovery's restored-snapshot age is reported by the
        recovery component instead)."""
        if self.last_snapshot_time is None:
            return None
        return max(0.0, (self.clock.now() - self.last_snapshot_time).total_seconds())

    def health_state(self) -> Tuple[str, dict]:
        """Health component (health.py): degraded while snapshot writes are
        failing — the journal still recovers everything except reservation
        TTL continuity, but an operator should know the snapshot cadence
        stopped."""
        age = self.snapshot_age_seconds()
        detail = {
            "written": self.snapshots_written,
            "failures": self.snapshot_failures,
            "lastSeq": self.last_snapshot_seq,
            "ageSeconds": round(age, 3) if age is not None else None,
            "staleEpochRejected": self.stale_epoch_rejected,
        }
        if self.stale_epoch_rejected:
            return "down", detail  # fenced replica: must not serve
        return ("degraded" if self.snapshot_failures else "ok"), detail
