"""Host→device state mirror: keeps the check kernel's inputs current.

The reference's PreFilter reads informer caches synchronously per pod
attempt (plugin.go:148-215). Here the equivalent read path is a device
kernel over mirrored tensors, so this manager maintains, per kind:

- a ``SelectorIndex`` (the [P,T] mask),
- pod staging rows (effective requests, int64 milli),
- throttle staging rows (effective threshold, status.used, status.throttled
  flags — i.e. exactly the fields ``check_throttled_for`` reads from the
  CRD object) plus the reservation mirror,

all as numpy staging arrays with dirty tracking; ``_sync`` uploads to device
only what changed. Stable padded capacities mean the jitted kernels never
recompile on object churn (they recompile only on capacity growth, which is
geometric and rare).

Writes arrive synchronously from store watch events (cheap row updates —
same contract as informer handlers); reads (``check_pod``,
``check_batch``) are served from device.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..utils.tracing import NoopTracer
from ..utils.lockorder import make_lock, make_rlock
from ..utils import epochassert as _epochassert
from ..utils.retrace import on_tick as _retrace_on_tick
from ..api.pod import Pod
from ..api.types import ClusterThrottle, ResourceAmount, Throttle
from ..quantity import to_milli
from ..resourcelist import pod_request_resource_list
from .index import SelectorIndex
from .reservations import ReservedResourceAmounts
from .store import Event, EventType, Store
from ..ops.check import (
    CHECK_ACTIVE,
    CHECK_INSUFFICIENT,
    CHECK_NOT_AFFECTED,
    CHECK_NOT_THROTTLED,
    CHECK_POD_EXCEEDS,
    STATUS_NAMES,
    check_pods,
    check_pods_compact,
    check_pods_gather,
)
from ..ops.schema import DimRegistry, PodBatch, ThrottleState

logger = logging.getLogger(__name__)

# cached once at import: _note_thr_col is on the reconcile hot path, and
# the assassin only needs mutation provenance when the suite arms it
_EPOCH_ASSERT = _epochassert.enabled()

AnyThrottle = Union[Throttle, ClusterThrottle]


def _next_rung(k: int) -> int:
    """One step up the shape ladder: ×4 below 128, ×2 above. The single
    definition both _next_pow2 and _bucket_ladder derive from — if the
    live bucketing and the prewarm walk ever disagreed, serving would hit
    mid-burst compiles on rungs prewarm never visited."""
    return k * (4 if k < 128 else 2)


def _next_pow2(n: int, lo: int = 8) -> int:
    """Smallest ladder rung ≥ n — THE shape-bucketing policy: every
    dynamically-sized device index/batch pads to one of these so the set
    of compiled XLA shapes stays logarithmic, not one per count. The
    ladder steps ×4 below 128 and ×2 above (8, 32, 128, 256, 512, …):
    small-burst sizes vary the most, so coarse rungs there cut the
    distinct-shape count (every extra shape is a full XLA compile —
    ~10-100ms CPU, seconds through a cold TPU tunnel, and prewarm() has
    to walk the whole ladder), while capping padding waste at 2× for the
    large shapes whose execution cost is real. (Name kept from the
    original pure-pow2 policy; rungs are now the sparse ladder above.)"""
    k = lo
    while k < n:
        k = _next_rung(k)
    return k


def _bucket_ladder(ladder_max: int, lo: int = 8) -> List[int]:
    """The rungs _next_pow2 can produce, ≤ ladder_max (prewarm walks these)."""
    out = []
    k = lo
    while k <= ladder_max:
        out.append(k)
        k = _next_rung(k)
    return out


# top rung of the prewarm bucket ladder for the CHECK kernels (the whole
# aggregate data plane is host numpy — steal/apply_agg_work — so no
# aggregate shapes exist to cap or warm)
CHECK_LADDER_MAX = 512


def _host_classify_rows(rows, pod_req, pod_present, on_equal, step3_on_equal):
    """Numpy port of ops.check._classify_core over [K] gathered rows — the
    single-pod HOST fast path. A one-pod check is a [K,R] computation over
    rows that already live in host staging; any device dispatch (let alone
    a remote-TPU-tunnel round trip) costs more than the arithmetic. The
    4-step semantics are kept line-for-line with the kernel and pinned by
    the device-vs-host parity test (test_check_kernel's
    test_host_single_check_matches_device_kernel, which forces both
    routes); invalid columns report CHECK_NOT_AFFECTED like the kernels'
    slot masking."""
    (
        valid,
        thr_cnt, thr_cnt_p, thr_req, thr_req_p,
        st_cnt, st_req_fp, st_req_t,
        au_cnt, au_cnt_p, au_req, au_req_p,
    ) = rows
    pod_nonzero = pod_present & (pod_req != 0)

    def cmp(u, t, oe):
        return u >= t if oe else u > t

    # step 1: pod alone vs threshold (pod count is 1 and always present)
    exceeds = (thr_cnt_p & (1 > thr_cnt)) | np.any(
        thr_req_p & pod_present & (pod_req > thr_req) & (pod_req != 0), axis=-1
    )
    # step 2: persisted throttled flags
    st_active = st_cnt | np.any(st_req_fp & st_req_t & pod_nonzero, axis=-1)
    # step 3: used + reserved saturation
    saturated = (
        thr_cnt_p & au_cnt_p & cmp(au_cnt, thr_cnt, step3_on_equal)
    ) | np.any(
        thr_req_p & au_req_p & cmp(au_req, thr_req, step3_on_equal) & pod_nonzero,
        axis=-1,
    )
    # step 4: used + reserved + pod overflow
    insufficient = (
        thr_cnt_p & cmp(au_cnt + 1, thr_cnt, on_equal)
    ) | np.any(
        thr_req_p
        & (au_req_p | pod_present)
        & cmp(au_req + pod_req, thr_req, on_equal)
        & pod_nonzero,
        axis=-1,
    )
    out = np.where(
        exceeds,
        np.int8(CHECK_POD_EXCEEDS),
        np.where(
            st_active | saturated,
            np.int8(CHECK_ACTIVE),
            np.where(
                insufficient,
                np.int8(CHECK_INSUFFICIENT),
                np.int8(CHECK_NOT_THROTTLED),
            ),
        ),
    )
    return np.where(valid, out, np.int8(CHECK_NOT_AFFECTED))


_AGG_DEVICE_DELTAS: Optional[bool] = None


def _agg_device_deltas() -> bool:
    """True routes pending-delta bursts through the real
    ``apply_pod_deltas_batched`` device kernel instead of its host mirror
    (KT_AGG_DEVICE_DELTAS=1 — see _KindState.apply_pending_batched).
    Resolved once; the parity test toggles the cache directly."""
    global _AGG_DEVICE_DELTAS
    if _AGG_DEVICE_DELTAS is None:
        _AGG_DEVICE_DELTAS = os.environ.get("KT_AGG_DEVICE_DELTAS") == "1"
    return _AGG_DEVICE_DELTAS


_cls_lib = None
_cls_lib_tried = False


def _native_cls_lib():
    """The native classifier tier (ktn_cls_* in native/ktnative.cpp), or
    None (no toolchain / KT_TPU_NO_NATIVE=1 → numpy tier). Cached to keep
    the per-decision cost to one global read."""
    global _cls_lib, _cls_lib_tried
    if not _cls_lib_tried:
        from ..native import load

        _cls_lib = load()
        _cls_lib_tried = True
    return _cls_lib


def _native_classify_cols(lib, ks, cols, pod_req_row, pod_present_row, on_equal, step3):
    """ktn_cls_run over the kind's LIVE staging planes — caller holds the
    main lock, so the C++ K×R pass (sub-µs) reads a coherent snapshot with
    zero [K,R] gather copies and zero per-call numpy allocation. Plane
    pointers are registered into a C-side handle once per staging
    allocation; the identity check re-registers after capacity growth
    (ensure_capacity reallocates, logarithmically under the ladder).
    Semantics are pinned to _host_classify_rows (numpy tier) AND the
    device kernel by test_host_single_check_matches_device_kernel, whose
    final section forces the numpy tier through the module lib cache."""
    planes = (
        ks.thr_valid,
        ks.thr_cnt, ks.thr_cnt_present, ks.thr_req, ks.thr_req_present,
        ks.st_cnt_throttled, ks.st_req_flag_present, ks.st_req_throttled,
        ks.used_cnt, ks.used_cnt_present, ks.used_req, ks.used_req_present,
        ks.res_cnt, ks.res_cnt_present, ks.res_req, ks.res_req_present,
    )
    cached = ks._cls_cache
    if (
        cached is None
        or cached[0] != ks.R
        or any(a is not b for a, b in zip(cached[1], planes))
    ):
        if cached is not None:
            cached[3]()  # single-shot destroy (finalizer marks itself dead)
        handle = lib.ktn_cls_create(ks.R, *(a.ctypes.data for a in planes))
        # the tuple keeps the registered arrays alive for the handle's raw
        # pointers; replaced wholesale on the next growth. The finalizer
        # frees the C-side handle when the kind state is GC'd (tests build
        # many managers); calling it early (re-registration) destroys
        # exactly once — weakref.finalize guarantees at-most-once.
        fin = weakref.finalize(ks, lib.ktn_cls_destroy, handle)
        ks._cls_cache = (ks.R, planes, handle, fin)
    else:
        handle = cached[2]
    K = cols.shape[0]
    sc = ks._cls_scratch
    if sc is None or sc[0].shape[0] < K:
        cap = max(64, 1 << (int(K) - 1).bit_length())
        sc = (np.empty(cap, dtype=np.int32), np.empty(cap, dtype=np.int8))
        ks._cls_scratch = sc
    cbuf, obuf = sc
    cbuf[:K] = cols
    lib.ktn_cls_run(
        handle, K, cbuf.ctypes.data,
        pod_req_row.ctypes.data, pod_present_row.ctypes.data,
        int(on_equal), int(step3), obuf.ctypes.data,
    )
    # copy: the scratch is reused by the next decision once the lock drops
    return obuf[:K].copy()


def _pad_pow2(idx: np.ndarray, lo: int = 8) -> np.ndarray:
    """Pad a 1-D index array to the next ladder rung by repeating its
    first element (a duplicate scatter index writing the same value is a
    no-op; a duplicate gather index is simply read twice)."""
    k = _next_pow2(idx.size, lo)
    if k == idx.size:
        return idx
    out = np.full(k, idx[0] if idx.size else 0, dtype=idx.dtype)
    out[: idx.size] = idx
    return out


class _KindState:
    """Staging arrays + index for one kind."""

    def __init__(self, kind: str, dims: DimRegistry, interner=None):
        self.kind = kind
        self.dims = dims
        self.index = SelectorIndex(kind, interner=interner)
        # columnar store arena (engine/columnar.py), wired by the manager
        # when the store carries one: pod request encodes come from the
        # interned request-shape cache instead of per-pod Fraction math
        self.arena = None
        self.R = dims.capacity
        pcap, tcap = self.index.capacities
        self._alloc_pods(pcap)
        self._alloc_throttles(tcap)
        self.dirty_pods = True
        self.dirty_throttles = True
        # post-update matched cols of the most recent pod delta capture
        # (capture_pod_delta_end) — feeds the manager's per-event
        # affected-keys cache
        self.last_event_cols: Optional[np.ndarray] = None
        # native single-pod classifier: (R, planes tuple, C handle int,
        # finalizer) — re-registered when any staging plane is reallocated
        # (identity check in _native_classify_cols); the weakref finalizer
        # frees the C handle on GC or early at re-registration (at-most-
        # once either way); scratch = (cols i32, out i8)
        self._cls_cache = None
        self._cls_scratch = None
        self._device_state: Optional[ThrottleState] = None
        self._device_packed = None  # CheckPrecompPacked cache for check_pod
        self._device_pods: Optional[PodBatch] = None
        self._device_mask = None
        # sparse companion of the mask for batch checks: int32[pcap, K]
        # matched throttle cols per pod row (-1 pads), K a ladder rung of
        # the max per-row match count. None when the dense kernel is the
        # better batch shape (K within ~tcap/4) or not yet built.
        self._cols_host: Optional[np.ndarray] = None
        self._device_cols = None
        self._cols_K = 0
        # column/namespace invalidation pending a cols rebuild (the device
        # mask itself rebuilds lazily; see device_pods)
        self._cols_stale = False
        # pod rows whose device-mask rows lag the host mask (applied when a
        # mask consumer next asks for it)
        self._mask_dirty_rows: set = set()
        # rows/cols touched by single-object events since the last device
        # sync — applied as device-side scatters instead of a full re-upload
        self._dirty_pod_rows: set = set()
        self._dirty_thr_cols: set = set()
        # beyond this many pending rows a full upload is cheaper
        self.row_scatter_max = 256

        # --- live used-aggregation state (reconcile data plane) ----------
        # HOST-resident exact-int64 running aggregates of status.used per
        # throttle column: streaming pod-event deltas apply as plain numpy
        # adds (zero arithmetic intensity — a device dispatch per drain
        # costs more than the math); per-column rebases on selector/
        # threshold edits and the full rebase on namespace/capacity changes
        # are sparse host scatters over the live mask (_host_rebase_full/
        # _cols — O(nnz), no [P,T] device upload). Replaces the reference's
        # per-reconcile O(P_ns) pod scan (throttle_controller.go:103-119).
        self.agg_cnt = None  # int64[T] host
        self.agg_req = None  # int64[T,R] host
        self.agg_contrib = None  # int32[T,R] host
        self._agg_full_rebase = True
        self._agg_rebase_cols: set = set()
        # pending (cols int32[k], sign ±1, req int64[R'], present bool[R'])
        self._agg_pending: list = []
        self._agg_pending_max = 131072
        self._delta_old = None  # snapshot between capture begin/end
        self._counted_device = None
        self._counted_dirty = True
        # {id(ResourceAmount): (weakref, cnt, req int64[R'], present bool[R'])}
        # — raw integer rows stashed when aggregate_used_for DECODES a used
        # amount, so the status-write echo can write the staging row
        # directly instead of round-tripping Fraction→milli again
        # (~24µs of the echo's ~43µs); weakref finalizers evict
        self._used_raw: dict = {}
        # col → the throttle's accelClassThresholds tuple (heterogeneity):
        # sparse — only columns whose spec declares entries appear. Feeds
        # encode_class_thresholds for the gang kernel and gates the
        # accel-aware host routing (manager.has_accel_thresholds).
        self.accel_cols: Dict[int, tuple] = {}

    def _alloc_pods(self, pcap: int) -> None:
        self.pod_req = np.zeros((pcap, self.R), dtype=np.int64)
        self.pod_present = np.zeros((pcap, self.R), dtype=bool)
        self.pod_valid = np.zeros(pcap, dtype=bool)
        # shouldCountIn ∧ is_not_finished per row — membership of status.used
        self.counted = np.zeros(pcap, dtype=bool)
        # shouldCountIn alone (phase-independent) — membership of the
        # reconcile unreserve walk, which includes terminated pods
        # (throttle_controller.go:135-155)
        self.count_in = np.zeros(pcap, dtype=bool)
        self.pcap = pcap

    def _alloc_throttles(self, tcap: int) -> None:
        z64 = lambda *s: np.zeros(s, dtype=np.int64)
        zb = lambda *s: np.zeros(s, dtype=bool)
        R = self.R
        self.thr_cnt, self.thr_cnt_present = z64(tcap), zb(tcap)
        self.thr_req, self.thr_req_present = z64(tcap, R), zb(tcap, R)
        self.used_cnt, self.used_cnt_present = z64(tcap), zb(tcap)
        self.used_req, self.used_req_present = z64(tcap, R), zb(tcap, R)
        self.res_cnt, self.res_cnt_present = z64(tcap), zb(tcap)
        self.res_req, self.res_req_present = z64(tcap, R), zb(tcap, R)
        self.st_cnt_throttled = zb(tcap)
        self.st_req_throttled = zb(tcap, R)
        self.st_req_flag_present = zb(tcap, R)
        self.thr_valid = zb(tcap)
        # verdict-epoch plane (engine/verdictcache.py): col_epoch[c] is
        # bumped by every mutation that can change a verdict over col c
        # (row encodes, removals, reservation writes); global_epoch covers
        # mutations with no single-col footprint (namespace events re-route
        # clusterthrottle matching wholesale). Monotonic, never reset —
        # a cache key's epoch-sum can therefore only grow, so equality
        # proves no covered mutation happened since the entry was computed.
        self.col_epoch = z64(tcap)
        self.global_epoch = 0
        self.tcap = tcap

    # -- growth -----------------------------------------------------------

    def _pad_cols(self, arr: np.ndarray, new_r: int) -> np.ndarray:
        out = np.zeros(arr.shape[:-1] + (new_r,), dtype=arr.dtype)
        out[..., : arr.shape[-1]] = arr
        return out

    def ensure_capacity(self) -> None:
        """Grow staging to match index capacities / dim registry."""
        if self.dims.capacity != self.R:
            new_r = self.dims.capacity
            for name in (
                "pod_req", "pod_present", "thr_req", "thr_req_present",
                "used_req", "used_req_present", "res_req", "res_req_present",
                "st_req_throttled", "st_req_flag_present",
            ):
                setattr(self, name, self._pad_cols(getattr(self, name), new_r))
            self.R = new_r
            self.dirty_pods = self.dirty_throttles = True
        pcap, tcap = self.index.capacities
        if pcap != self.pcap:
            for name in ("pod_req", "pod_present"):
                arr = getattr(self, name)
                grown = np.zeros((pcap,) + arr.shape[1:], dtype=arr.dtype)
                grown[: arr.shape[0]] = arr
                setattr(self, name, grown)
            for name in ("pod_valid", "counted", "count_in"):
                arr = getattr(self, name)
                grown = np.zeros(pcap, dtype=bool)
                grown[: arr.shape[0]] = arr
                setattr(self, name, grown)
            self.pcap = pcap
            self.dirty_pods = True
            self._counted_dirty = True
        if tcap != self.tcap:
            old = self.tcap
            for name in (
                "thr_cnt", "thr_cnt_present", "used_cnt", "used_cnt_present",
                "res_cnt", "res_cnt_present", "st_cnt_throttled", "thr_valid",
                "col_epoch",
            ):
                arr = getattr(self, name)
                grown = np.zeros(tcap, dtype=arr.dtype)
                grown[:old] = arr
                setattr(self, name, grown)
            for name in (
                "thr_req", "thr_req_present", "used_req", "used_req_present",
                "res_req", "res_req_present", "st_req_throttled", "st_req_flag_present",
            ):
                arr = getattr(self, name)
                grown = np.zeros((tcap, self.R), dtype=arr.dtype)
                grown[:old] = arr
                setattr(self, name, grown)
            self.tcap = tcap
            self.dirty_throttles = True

    # -- row updates ------------------------------------------------------

    def _amount_into_row(
        self,
        amount: Optional[ResourceAmount],
        cnt_name: str,
        cnt_present_name: str,
        req_name: str,
        req_present_name: str,
        i: int,
    ) -> None:
        if amount is None:
            amount = ResourceAmount()
        # resolve every dim index FIRST and grow once: ensure_capacity()
        # REPLACES the staging arrays, so references must only be taken
        # after any growth has happened
        entries = [
            (self.dims.index_of(name), to_milli(q))
            for name, q in (amount.resource_requests or {}).items()
        ]
        if any(j >= self.R for j, _ in entries):
            self.ensure_capacity()
        cnt = getattr(self, cnt_name)
        cnt_present = getattr(self, cnt_present_name)
        req = getattr(self, req_name)
        req_present = getattr(self, req_present_name)
        if amount.resource_counts is not None:
            cnt[i] = amount.resource_counts
            cnt_present[i] = True
        else:
            cnt[i] = 0
            cnt_present[i] = False
        req[i, :] = 0
        req_present[i, :] = False
        for j, milli in entries:
            req[i, j] = milli
            req_present[i, j] = True

    def _note_thr_col(self, col: int, before: Tuple[int, int]) -> None:
        """Record a single-throttle change for the scatter path, or escalate
        to a full re-upload if capacity moved under us."""
        if _EPOCH_ASSERT:
            # depth=2: skip this helper so the recorded site is the mutator
            # (set_throttle_row / remove_throttle_row / set_reserved_row)
            _epochassert.note_mutation(depth=2)
        if (self.tcap, self.R) == before and not self.dirty_throttles:
            self._dirty_thr_cols.add(col)
        else:
            self.dirty_throttles = True

    def _note_pod_row(self, row: int, before: Tuple[int, int]) -> None:
        if (self.pcap, self.R) == before and not self.dirty_pods:
            self._dirty_pod_rows.add(row)
        else:
            self.dirty_pods = True

    def set_throttle_row(
        self,
        thr: AnyThrottle,
        selector_changed: bool = True,
        old: Optional[AnyThrottle] = None,
    ) -> int:
        """Encode a throttle's device row. ``old`` (the MODIFIED event's
        previous object) lets the dominant caller — the status-write echo
        of our own reconcile, ~every status write under churn — skip the
        encode of sub-objects that did not change: usually only ``used``
        moved, so the effective-threshold and flag encodes (≈half the
        echo's cost) are replaced by three cheap dataclass compares."""
        from ..api.types import effective_threshold

        if selector_changed:
            col = self.index.upsert_throttle(thr)
        else:
            # status/threshold-only update: the mask column is untouched, so
            # skip the O(P) column re-match and just refresh the object
            col = self.index.refresh_throttle_object(thr)
            if col is None:  # not indexed yet (shouldn't happen) — full path
                col = self.index.upsert_throttle(thr)
        before = (self.tcap, self.R)
        self.ensure_capacity()
        grown = before != (self.tcap, self.R)
        # diffing is only sound when the row is already encoded (the object
        # was indexed, not a fresh column) and no capacity growth re-zeroed
        # the staging arrays
        diff = old is not None and not selector_changed and not grown
        if not (
            diff
            and old.spec.threshold == thr.spec.threshold
            and old.status.calculated_threshold.threshold
            == thr.status.calculated_threshold.threshold
            # effective_threshold switches source (spec vs calculated) on
            # whether calculatedAt is stamped — a None↔set flip changes the
            # effective value even with both .threshold fields unchanged
            and (old.status.calculated_threshold.calculated_at is None)
            == (thr.status.calculated_threshold.calculated_at is None)
        ):
            eff = effective_threshold(thr.spec.threshold, thr.status)
            self._amount_into_row(
                eff, "thr_cnt", "thr_cnt_present", "thr_req", "thr_req_present", col
            )
        if not (diff and old.status.used == thr.status.used):
            used = thr.status.used
            raw = self._used_raw.get(id(used))
            if raw is not None and raw[0]() is used:
                # the echo of our own reconcile: the decode that built this
                # ResourceAmount stashed its exact int64 row — write it
                # directly, skipping the Fraction→milli re-encode
                _, cnt_v, req_row, pres_row = raw
                self.used_cnt[col] = cnt_v
                self.used_cnt_present[col] = used.resource_counts is not None
                self.used_req[col, :] = 0
                self.used_req_present[col, :] = False
                n = req_row.shape[0]
                self.used_req[col, :n] = req_row
                self.used_req_present[col, :n] = pres_row
            else:
                self._amount_into_row(
                    used,
                    "used_cnt", "used_cnt_present", "used_req", "used_req_present", col,
                )
        accel = thr.spec.accel_class_thresholds
        if accel:
            self.accel_cols[col] = accel
        else:
            self.accel_cols.pop(col, None)
        st = thr.status.throttled
        if not (diff and old.status.throttled == st):
            self.st_cnt_throttled[col] = st.resource_counts_pod
            self.st_req_throttled[col, :] = False
            self.st_req_flag_present[col, :] = False
            for name, flag in (st.resource_requests or {}).items():
                j = self.dims.index_of(name)
                if j >= self.R:
                    self.ensure_capacity()
                self.st_req_flag_present[col, j] = True
                self.st_req_throttled[col, j] = flag
        self.thr_valid[col] = True
        self.col_epoch[col] += 1
        self._note_thr_col(col, before)
        return col

    def remove_throttle_row(self, key: str) -> Optional[int]:
        col = self.index.throttle_col(key)
        self.index.remove_throttle(key)
        if col is not None:
            self.accel_cols.pop(col, None)
            self.thr_valid[col] = False
            self.res_cnt[col] = 0
            self.res_cnt_present[col] = False
            self.res_req[col, :] = 0
            self.res_req_present[col, :] = False
            self.col_epoch[col] += 1
            self._note_thr_col(col, (self.tcap, self.R))
        return col

    def set_reserved_row(self, key: str, amount: ResourceAmount) -> None:
        col = self.index.throttle_col(key)
        if col is None:
            return
        before = (self.tcap, self.R)
        self._amount_into_row(amount, "res_cnt", "res_cnt_present", "res_req", "res_req_present", col)
        self.col_epoch[col] += 1
        self._note_thr_col(col, before)

    def pod_request_entries(self, pod: Pod) -> List[Tuple[int, int]]:
        """(dim index, milli value) pairs for a pod's effective requests —
        the registry-dependent half of the row encode. Valid for any
        consumer sharing this instance's ``dims``. Arena-absorbed pods
        carry their interned request-shape id, so the entries come from
        the per-shape cache — zero per-pod dict hydration or Fraction
        arithmetic on the hot path."""
        arena = self.arena
        if arena is not None and getattr(pod, "_kt_arena", None) is arena.token:
            return arena.entries_for(pod.__dict__["_kt_req_sid"], self.dims)
        return [
            (self.dims.index_of(name), to_milli(q))
            for name, q in pod_request_resource_list(pod).items()
        ]

    def encode_pod_requests_into(
        self, req: np.ndarray, present: np.ndarray, i: int, pod: Pod,
        entries: Optional[List[Tuple[int, int]]] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Canonical pod-request row encoding (shared by the mirror rows and
        ad-hoc single-pod batches). Returns possibly-regrown arrays."""
        req[i, :] = 0
        present[i, :] = False
        if entries is None:
            entries = self.pod_request_entries(pod)
        for j, milli in entries:
            if j >= req.shape[1]:
                self.ensure_capacity()
                req = np.pad(req, ((0, 0), (0, self.R - req.shape[1])))
                present = np.pad(present, ((0, 0), (0, self.R - present.shape[1])))
            req[i, j] = milli
            present[i, j] = True
        return req, present

    def set_pod_row(
        self,
        pod: Pod,
        counted: bool = False,
        count_in: bool = False,
        entries: Optional[List[Tuple[int, int]]] = None,
    ) -> None:
        row = self.index.upsert_pod(pod)
        before = (self.pcap, self.R)
        self.ensure_capacity()
        self.pod_req, self.pod_present = self.encode_pod_requests_into(
            self.pod_req, self.pod_present, row, pod, entries=entries
        )
        self.pod_valid[row] = True
        self.count_in[row] = count_in
        if self.counted[row] != counted:
            self.counted[row] = counted
            self._counted_dirty = True
        self._note_pod_row(row, before)

    def set_pod_rows(self, plans) -> None:
        """Batched :meth:`set_pod_row`: ``plans`` is
        ``[(key, event, counted, count_in, entries)]`` for the upserted
        pods of one ingest run. The index side goes through
        ``upsert_pods_batch`` (one index-lock hold; label columns for the
        whole run land before one re-match pass); the staging rows then
        encode per pod exactly like the single path."""
        if not plans:
            return
        rows = self.index.upsert_pods_batch([ev.obj for _, ev, _, _, _ in plans])
        before = (self.pcap, self.R)
        self.ensure_capacity()
        for (key, ev, counted, count_in, entries), row in zip(plans, rows):
            pod = ev.obj
            self.pod_req, self.pod_present = self.encode_pod_requests_into(
                self.pod_req, self.pod_present, row, pod, entries=entries
            )
            self.pod_valid[row] = True
            self.count_in[row] = count_in
            if self.counted[row] != counted:
                self.counted[row] = counted
                self._counted_dirty = True
            self._note_pod_row(row, before)

    def remove_pod_row(self, key: str) -> None:
        row = self.index.pod_row(key)
        self.index.remove_pod(key)
        if row is not None:
            self.pod_valid[row] = False
            self.count_in[row] = False
            if self.counted[row]:
                self.counted[row] = False
                self._counted_dirty = True
            self._note_pod_row(row, (self.pcap, self.R))

    # -- device sync ------------------------------------------------------

    # (ThrottleState field, staging attribute) in constructor order
    _THR_FIELDS = (
        ("valid", "thr_valid"),
        ("thr_cnt", "thr_cnt"), ("thr_cnt_present", "thr_cnt_present"),
        ("thr_req", "thr_req"), ("thr_req_present", "thr_req_present"),
        ("used_cnt", "used_cnt"), ("used_cnt_present", "used_cnt_present"),
        ("used_req", "used_req"), ("used_req_present", "used_req_present"),
        ("res_cnt", "res_cnt"), ("res_cnt_present", "res_cnt_present"),
        ("res_req", "res_req"), ("res_req_present", "res_req_present"),
        ("st_cnt_throttled", "st_cnt_throttled"),
        ("st_req_throttled", "st_req_throttled"),
        ("st_req_flag_present", "st_req_flag_present"),
    )

    def device_state(self) -> ThrottleState:
        self.ensure_capacity()
        if (
            not self.dirty_throttles
            and self._device_state is not None
            and self._dirty_thr_cols
            and len(self._dirty_thr_cols) <= self.row_scatter_max
        ):
            # single-throttle events: scatter only the touched rows of the
            # 16 [T]/[T,R] tensors instead of re-uploading them all.
            # Power-of-two padded (duplicating the first index — writing the
            # same value twice is a no-op): an unbucketed shape would make
            # every distinct dirty-count a fresh XLA compile.
            cols = _pad_pow2(np.fromiter(self._dirty_thr_cols, dtype=np.int64))
            s = self._device_state
            self._device_state = ThrottleState(
                **{
                    field: getattr(s, field).at[cols].set(getattr(self, attr)[cols])
                    for field, attr in self._THR_FIELDS
                }
            )
            self._dirty_thr_cols.clear()
            self._device_packed = None  # derived cache follows the state
            return self._device_state
        if self.dirty_throttles or self._device_state is None or self._dirty_thr_cols:
            self._device_state = ThrottleState(
                **{
                    field: jnp.asarray(getattr(self, attr))
                    for field, attr in self._THR_FIELDS
                }
            )
            self.dirty_throttles = False
            self._dirty_thr_cols.clear()
            self._device_packed = None  # derived cache follows the state
        return self._device_state

    def device_packed(self):
        """Packed residual-form precomp for the indexed single-pod check,
        rebuilt lazily on throttle-state change."""
        from ..ops.fastcheck import pack_check_state, precompute_check_state

        state = self.device_state()  # refreshes + clears dirty_throttles
        if self._device_packed is None:
            self._device_packed = pack_check_state(precompute_check_state(state))
        return self._device_packed

    def device_pods(self, need_mask: bool = True) -> Tuple[PodBatch, Optional[jnp.ndarray]]:
        """Device pod arrays + (optionally) the [P,T] device mask.

        The mask is maintained LAZILY with its own dirty-row set: the
        sparse-gather batch path never reads it, so a triage call must not
        pay the full [P,T] re-upload a throttle/namespace invalidation
        queued up (2.1 GB at 100k×10k — per batch call, through a TPU
        tunnel, for a tensor the kernel ignores). Pass ``need_mask=False``
        to skip it; consumers that DO read it (aggregate rebases, the
        dense fallback, the sharded tick, prewarm) get it refreshed on
        demand. Returns mask ``None`` when skipped."""
        self.ensure_capacity()
        if (
            self.dirty_pods
            or self._device_pods is None
            or len(self._dirty_pod_rows) > self.row_scatter_max
        ):
            self._device_pods = PodBatch(
                valid=jnp.asarray(self.pod_valid),
                req=jnp.asarray(self.pod_req),
                req_present=jnp.asarray(self.pod_present),
            )
            self._rebuild_cols()
            self._cols_stale = False
            self.dirty_pods = False
            self._dirty_pod_rows.clear()
            self._device_mask = None  # rebuilt from the live numpy on demand
            self._mask_dirty_rows.clear()
        else:
            cols_rebuilt = False
            if self._cols_stale:
                # throttle/namespace event invalidated columns: the [P,K]
                # cols derive from the HOST mask, so rebuild them now (the
                # device mask itself can wait for a consumer)
                self._rebuild_cols()
                self._cols_stale = False
                cols_rebuilt = True  # already includes any dirty rows
            if self._dirty_pod_rows:
                # single-pod events: ship only the touched rows (device-side
                # scatter instead of a full [P,R] host→device transfer);
                # pow2-padded like the throttle-col scatter (compile
                # stability). The mask rows are deferred into
                # _mask_dirty_rows until a mask consumer shows up.
                rows = _pad_pow2(np.fromiter(self._dirty_pod_rows, dtype=np.int64))
                self._device_pods = PodBatch(
                    valid=self._device_pods.valid.at[rows].set(self.pod_valid[rows]),
                    req=self._device_pods.req.at[rows].set(self.pod_req[rows]),
                    req_present=self._device_pods.req_present.at[rows].set(
                        self.pod_present[rows]
                    ),
                )
                if not cols_rebuilt:  # the full rebuild read the live mask
                    self._update_cols_rows(rows)
                self._mask_dirty_rows.update(self._dirty_pod_rows)
                self._dirty_pod_rows.clear()
        if not need_mask:
            return self._device_pods, None
        if (
            self._device_mask is None
            or self._device_mask.shape != self.index.capacities
            or len(self._mask_dirty_rows) > self.row_scatter_max
        ):
            # materialized dense from the sparse rows (the dense device
            # route only activates at small K/T ratios — see _rebuild_cols)
            self._device_mask = jnp.asarray(self.index.mask)
            self._mask_dirty_rows.clear()
        elif self._mask_dirty_rows:
            rows = _pad_pow2(np.fromiter(self._mask_dirty_rows, dtype=np.int64))
            self._device_mask = self._device_mask.at[rows].set(
                self.index.mask_rows(rows)
            )
            self._mask_dirty_rows.clear()
        return self._device_pods, self._device_mask

    def device_cols(self):
        """Sparse cols int32[pcap,K] for ``check_pods_gather``, or None when
        the dense mask is the better batch shape. Valid only immediately
        after ``device_pods()`` under the same lock hold (shares its
        invalidation bookkeeping)."""
        return self._device_cols

    @staticmethod
    def _strip_sentinel(block: np.ndarray, counts: np.ndarray, K: int) -> np.ndarray:
        """Sparse-row block (sentinel-padded, kcap wide) → the device's
        int32[*, K] cols encoding (-1 padded)."""
        n = block.shape[0]
        out = np.full((n, K), -1, dtype=np.int32)
        w = min(K, block.shape[1])
        sub = block[:, :w]
        keep = np.arange(w, dtype=np.int32)[None, :] < counts[:, None]
        out[:, :w] = np.where(keep, sub, -1)
        return out

    def _rebuild_cols(self) -> None:
        """Full sparse-cols rebuild from the index's sparse rows (which
        ARE the [P,K] encoding — one sentinel→-1 strip away). Chooses the
        ladder-padded K from the max per-row match count; opts OUT of the
        sparse path (sets None) when K stops being ≪ T — a near-dense mask
        gathers most of the state anyway, at worse locality than the
        broadcast kernel."""
        nnz_max = self.index.nnz_max()
        # TRUE pow2 here, not the ×4 shape ladder: K is a property of the
        # CLUSTER STATE (max matches per pod), not of a per-call burst — it
        # changes only on rung escalation, so compile count stays tiny
        # while padding waste caps at 2× (the ladder padded 20 matches to
        # 64, tripling every [P,K] batch kernel's work at 100k×10k)
        K = 4
        while K < max(nnz_max, 1):
            K *= 2
        if K * 4 >= max(self.tcap, 16):
            self._cols_host = None
            self._device_cols = None
            self._cols_K = 0
            return
        row_cols, row_n, _kcap = self.index.sparse_snapshot()
        self._cols_host = self._strip_sentinel(row_cols, row_n, K)
        self._device_cols = jnp.asarray(self._cols_host)
        self._cols_K = K

    def _update_cols_rows(self, rows: np.ndarray) -> None:
        """Scatter-update the sparse cols for the given (pow2-padded) dirty
        rows; escalates to a full rebuild if a row outgrew K."""
        if self._cols_host is None:
            return
        block, counts = self.index.row_cols_block(rows)
        if counts.size and int(counts.max()) > self._cols_K:
            self._rebuild_cols()  # K ladder rung grew
            return
        self._cols_host[rows] = self._strip_sentinel(block, counts, self._cols_K)
        self._device_cols = self._device_cols.at[rows].set(self._cols_host[rows])

    def refresh_mask(self) -> None:
        self._device_mask = None
        self._mask_dirty_rows.clear()  # subsumed by the full rebuild
        self._cols_stale = True  # [P,K] cols derive from the (host) mask

    # -- live used-aggregation (the reconcile data plane) ------------------

    def _pod_contribution(self, pod_key: str, cols: Optional[np.ndarray] = None):
        """Snapshot of a pod's current contribution to the aggregates:
        (cols, req copy, present copy), or None if it contributes nothing.
        ``cols`` skips the mask-row nonzero when the caller knows the row
        cannot have changed (the label-stable delta-capture fast path —
        the nonzero over a 16k-wide row is the single largest slice of
        full-scale event-ingest cost, paid 4× per event without it)."""
        row = self.index.pod_row(pod_key)
        if row is None or not self.pod_valid[row] or not self.counted[row]:
            return None
        if cols is None:
            cols = self.index.row_cols(row)
        if cols.size == 0:
            return None
        return (cols, self.pod_req[row].copy(), self.pod_present[row].copy())

    def capture_pod_delta_begin(self, pod_key: str) -> None:
        self._delta_old = self._pod_contribution(pod_key)

    def capture_pod_delta_end(self, pod_key: str, row_stable: bool = False) -> None:
        """``row_stable=True`` asserts the pod's labels+namespace did not
        change between begin and end (the dominant churn shape), so its
        mask row — hence its matched cols — is identical to begin's and
        the nonzero can be skipped. Only an optimization hint: counted /
        request changes are still re-read either way."""
        old, self._delta_old = self._delta_old, None
        self.finish_pod_delta(pod_key, old, row_stable=row_stable)

    def finish_pod_delta(self, pod_key: str, old, row_stable: bool = False) -> None:
        """capture_pod_delta_end against an EXPLICITLY captured ``old``
        contribution. The batched pod-event path holds one open capture per
        distinct pod at once, which the single-slot ``_delta_old`` cannot;
        it snapshots every old contribution first, applies the batch, then
        finishes each delta through here."""
        if row_stable and old is not None:
            new = self._pod_contribution(pod_key, cols=old[0])
        else:
            new = self._pod_contribution(pod_key)
        # the post-update matched cols, already paid for above — _on_pod
        # publishes them as the event's affected-keys cache so the
        # controllers' handlers don't re-take the main lock to recompute
        # the same nonzero (None when the pod contributes nothing: not
        # counted / no matches — those shapes keep the locked slow path)
        self.last_event_cols = None if new is None else new[0]
        if old is not None and new is not None:
            if (
                np.array_equal(old[0], new[0])
                and np.array_equal(old[1], new[1])
                and np.array_equal(old[2], new[2])
            ):
                return  # no contribution change (e.g. status-only update)
        if old is None and new is None:
            return
        if old is not None:
            self._agg_pending.append((old[0], -1, old[1], old[2]))
        if new is not None:
            self._agg_pending.append((new[0], +1, new[1], new[2]))
        if len(self._agg_pending) > self._agg_pending_max:
            # backstop only: the vectorized pending pass is O(burst), so the
            # threshold is sized to bound the LIST's host memory (~500B per
            # entry), not to route bursts into the full rebase — that scan
            # is the expensive path now (~1-2s reader stall at 100k×10k)
            self._agg_full_rebase = True
            self._agg_pending.clear()

    def mark_col_rebase(self, col: Optional[int]) -> None:
        """A throttle add/update/delete changed column membership — its
        incremental aggregate is invalid; recompute it at next flush."""
        if col is not None:
            self._agg_rebase_cols.add(int(col))

    def mark_full_rebase(self) -> None:
        self._agg_full_rebase = True
        self._agg_pending.clear()
        self._agg_rebase_cols.clear()

    def _device_counted(self):
        if (
            self._counted_device is None
            or self._counted_dirty
            or self._counted_device.shape != (self.pcap,)
        ):
            self._counted_device = jnp.asarray(self.counted & self.pod_valid)
            self._counted_dirty = False
        return self._counted_device

    @staticmethod
    def _bincount_scatter(pc, req_rows, present_rows, n, cnt, req, ctb):
        """Accumulate one entry batch into (cnt, req, ctb) via bincount.

        ``np.bincount`` is ~3-5× faster than ``np.add.at`` here, but its
        weighted form sums in float64 — unsafe for int64 milli quantities
        (a 4Gi memory request is ~4.3e12 milli; a batch of them overflows
        the 2^53 mantissa). So req sums limb-split: lo/hi 32-bit halves
        each sum exactly in float64 because a bucket (column) receives at
        most one entry per pod row — per-bucket sums are ≤ pcap × 2^32
        < 2^53 for any pcap < 2^21 — then recombine in int64. Present-flag
        counts are small ints — plain weighted bincount is exact for
        them."""
        cnt += np.bincount(pc, minlength=n)[:n].astype(np.int64)
        for j in range(req_rows.shape[1]):
            col = req_rows[:, j]
            lo = np.bincount(pc, weights=(col & 0xFFFFFFFF).astype(np.float64), minlength=n)[:n]
            hi = np.bincount(pc, weights=(col >> 32).astype(np.float64), minlength=n)[:n]
            req[:, j] += lo.astype(np.int64) + (hi.astype(np.int64) << 32)
            ctb[:, j] += np.bincount(
                pc, weights=present_rows[:, j].astype(np.float64), minlength=n
            )[:n].astype(np.int32)

    # row-chunk size for the full rebase: bounds the [CHUNK, tcap] mask
    # row-gather temporary (64MB bool at tcap=16384), NOT an exactness
    # limit (see _bincount_scatter — per-bucket sums are exact for any
    # pcap < 2^21)
    _REBASE_CHUNK = 4096

    def _host_rebase_full(self):
        """Exact-int64 full aggregate recomputed from the live HOST arrays
        as a sparse scatter: O(nnz of the mask), not O(P×T) arithmetic.

        Replaces the device limb-GEMM over the whole [P,T] mask
        (``aggregate_used``), which at 100k pods × 10k throttles cost
        minutes of single-core time degraded and a ~2.1 GB mask upload
        through the TPU tunnel — for a result that lands host-side anyway.

        Caller holds the main lock (reads the live mask/pod rows), so this
        IS a reader stall while it runs — ~1-2s at 100k×10k, floored by the
        mask scan itself. Acceptable because full rebases are rare by
        construction: namespace events, capacity growth, and R growth only.
        (Pod-event bursts do NOT land here — the pending-delta path is
        O(burst) and its escalation threshold is sized to keep it.)"""
        tcap, R = self.tcap, self.R
        cnt = np.zeros(tcap, dtype=np.int64)
        req = np.zeros((tcap, R), dtype=np.int64)
        ctb = np.zeros((tcap, R), dtype=np.int32)
        rows = np.flatnonzero(self.pod_valid & self.counted)
        CHUNK = self._REBASE_CHUNK  # bounds the row-gather temp + limb exactness
        for s in range(0, rows.size, CHUNK):
            rr = rows[s : s + CHUNK]
            block, counts = self.index.row_cols_block(rr)
            keep = np.arange(block.shape[1], dtype=np.int32)[None, :] < counts[:, None]
            pr, slot = np.nonzero(keep)
            if pr.size:
                pc = block[pr, slot]
                self._bincount_scatter(
                    pc, self.pod_req[rr[pr]], self.pod_present[rr[pr]], tcap, cnt, req, ctb
                )
        return cnt, req, ctb

    def _host_rebase_cols(self, cols: np.ndarray):
        """Per-column recompute for selector/threshold edits, same sparse
        host form as the full rebase but over ``mask[:, cols]`` only,
        chunked over cols to bound the [pcap, c] boolean temporary.
        Caller holds the main lock; steal_agg_work escalates to a full
        rebase past max(256, tcap/4) columns (the strided column gather
        scales worse than the row-major full scan)."""
        eligible_rows = np.flatnonzero(self.pod_valid & self.counted)
        n = cols.size
        cnt = np.zeros(n, dtype=np.int64)
        req = np.zeros((n, self.R), dtype=np.int64)
        ctb = np.zeros((n, self.R), dtype=np.int32)
        if n == 0:
            return cnt, req, ctb
        # map col id → position in ``cols`` via one sorted lookup table;
        # membership resolves against the sparse rows (sorted, so a
        # searchsorted hit test replaces the dense [pcap, c] gather)
        order = np.argsort(cols, kind="stable")
        sorted_cols = cols[order]
        CHUNK = self._REBASE_CHUNK
        for s in range(0, eligible_rows.size, CHUNK):
            rr = eligible_rows[s : s + CHUNK]
            block, counts = self.index.row_cols_block(rr)
            keep = np.arange(block.shape[1], dtype=np.int32)[None, :] < counts[:, None]
            pos = np.searchsorted(sorted_cols, block)
            pos_c = np.minimum(pos, n - 1)
            hit = keep & (sorted_cols[pos_c] == block)
            pr, slot = np.nonzero(hit)
            if pr.size:
                pc = order[pos_c[pr, slot]]
                self._bincount_scatter(
                    pc, self.pod_req[rr[pr]], self.pod_present[rr[pr]], n, cnt, req, ctb
                )
        return cnt, req, ctb

    def steal_agg_work(self) -> dict:
        """Under the MAIN lock: resolve every staged rebase against the live
        host arrays and capture the delta burst, resetting the staging so
        the landing (apply_agg_work, under the per-kind agg lock) never
        blocks check readers.

        Rebase sums are computed HERE, host-side (_host_rebase_full/_cols):
        they must read a coherent mask+pod snapshot, and the sparse scatter
        is cheaper than even capturing device handles was — the former
        device-rebase path paid a ``device_pods()`` dirty-row scatter
        (~22ms per drain at cfg5 max rate) plus a [P,T] mask upload before
        dispatching any arithmetic. The delta-only steal (the steady-state
        path) is just a list swap."""
        self.ensure_capacity()
        shapes_ok = (
            self.agg_cnt is not None
            and self.agg_cnt.shape == (self.tcap,)
            and self.agg_req.shape == (self.tcap, self.R)
        )
        work = {
            "full": None,
            "cols": None,
            "rebased": frozenset(),
            "pending": self._agg_pending,
            "tcap": self.tcap,
            "R": self.R,
        }
        if len(self._agg_rebase_cols) > max(256, self.tcap // 4):
            # a bulk selector edit touching a large column fraction: the
            # strided [pcap, c] column gathers cost more than one row-major
            # full scan, and the full path's temporaries are tighter
            self._agg_full_rebase = True
        if self._agg_full_rebase or not shapes_ok:
            work["full"] = self._host_rebase_full()
        elif self._agg_rebase_cols:
            cols = np.fromiter(
                self._agg_rebase_cols, dtype=np.int32, count=len(self._agg_rebase_cols)
            )
            work["cols"] = (cols, *self._host_rebase_cols(cols))
            work["rebased"] = frozenset(self._agg_rebase_cols)
        self._agg_full_rebase = False
        self._agg_rebase_cols = set()
        self._agg_pending = []
        return work

    def apply_agg_work(self, work: dict) -> None:
        """Land stolen aggregate maintenance in the HOST aggregate arrays.

        The whole data plane is host-resident exact int64 now: rebases
        arrive pre-computed from steal_agg_work's sparse host scatters and
        land as plain assignments; the streaming pod deltas (4-element
        scatter-adds with zero arithmetic intensity) apply as exact int64
        ``np.add``s. The reconcile read path (aggregate_used_for) then
        serves from host memory with no device sync anywhere — measured at
        cfg5 max rate, the former device-resident delta path cost ~15ms of
        dispatch+sync per 256-key drain for arithmetic worth microseconds,
        and the former device rebase cost minutes at 100k×10k. (This also
        settles VERDICT r3 weak #5: buffer donation on the aggregate path
        is moot — no device buffers remain in it.)

        Caller holds the per-kind agg lock (NOT the main lock): ``agg_*``
        are only ever touched under it, and consecutive flushes are
        serialized steal-to-apply so an older snapshot can never overwrite
        a newer one."""
        if work["full"] is not None:
            # a full rebase read live state that already included every
            # staged delta — the pending burst is subsumed
            cnt, req, ctb = work["full"]
            self.agg_cnt = cnt
            self.agg_req = req
            self.agg_contrib = ctb
            return
        pending = work["pending"]
        if work["cols"] is not None:
            # deltas targeting a rebased column are subsumed by the rebase
            # (it read live state) — drop them or they double-count
            rb_arr = np.fromiter(
                work["rebased"], dtype=np.int32, count=len(work["rebased"])
            )
            rb_arr.sort()
            kept = []
            for cols, sign, req, present in pending:
                cols_kept = cols[~np.isin(cols, rb_arr, assume_unique=False)]
                if cols_kept.size:
                    kept.append((cols_kept, sign, req, present))
            pending = kept
            arr, cnt, req, ctb = work["cols"]
            self.agg_cnt[arr] = cnt
            self.agg_req[arr] = req
            self.agg_contrib[arr] = ctb
        if pending:
            self.apply_pending_batched(pending)

    def _pending_batch_arrays(self, pending):
        """Encode a pending-delta burst into the canonical batched-delta
        form ``ops.aggregate.apply_pod_deltas_batched`` takes: per-event
        target rows ``ids int32[N,K]`` (padded with tcap — dropped by the
        scatter), ``sign int64[N,K]`` (0 on padding), and the event's
        request row/presence ``[N,R]`` (padded to the CURRENT aggregate
        width — entries may predate an R growth)."""
        n_ent = len(pending)
        R_cur = self.agg_req.shape[1]
        K = max(c.size for c, _, _, _ in pending)
        # pow2-bucket K so the device route's compiled shapes stay
        # logarithmic (the host route is shape-indifferent)
        kb = 4
        while kb < max(K, 1):
            kb *= 2
        ids = np.full((n_ent, kb), self.tcap, dtype=np.int32)
        sign = np.zeros((n_ent, kb), dtype=np.int64)
        req = np.zeros((n_ent, R_cur), dtype=np.int64)
        pres = np.zeros((n_ent, R_cur), dtype=bool)
        for i, (c, s, r, p) in enumerate(pending):
            ids[i, : c.size] = c
            sign[i, : c.size] = s
            req[i, : r.shape[0]] = r
            pres[i, : p.shape[0]] = p
        return ids, sign, req, pres

    def apply_pending_batched(self, pending) -> None:
        """Land a pending-delta burst (N pod events × ≤K affected columns)
        in ONE batched scatter-add — the ingest-path wiring of
        ``apply_pod_deltas_batched``, which until PR 5 only the sharded
        tick used (parallel/sharded.py sharded_apply_deltas).

        The aggregates are HOST-resident (see apply_agg_work), so the
        default route is the kernel's exact host mirror: the same flattened
        [N·K] scatter-add over the same (ids, sign, req, present) encoding
        — np.add.at commutes and associates exactly in int64 like the
        device scatter, and the parity is pinned by
        tests/test_batch_ingest.py against the real kernel.
        ``KT_AGG_DEVICE_DELTAS=1`` opts into dispatching the actual jitted
        kernel instead (accelerator-resident aggregate experiments); both
        routes are bit-identical by construction.

        Caller holds the per-kind agg lock. A per-entry Python loop of
        small adds measured ~16ms per 256-key drain at cfg5 max rate; this
        form is sub-ms either way."""
        if not pending:
            return
        ids, sign, req, pres = self._pending_batch_arrays(pending)
        if _agg_device_deltas():
            from ..ops.aggregate import apply_pod_deltas_batched

            cnt, reqa, ctb = apply_pod_deltas_batched(
                jnp.asarray(self.agg_cnt),
                jnp.asarray(self.agg_req),
                jnp.asarray(self.agg_contrib),
                jnp.asarray(ids), jnp.asarray(sign),
                jnp.asarray(req), jnp.asarray(pres),
            )
            self.agg_cnt = np.asarray(cnt)
            self.agg_req = np.asarray(reqa)
            self.agg_contrib = np.asarray(ctb, dtype=np.int32)
            return
        flat_ids = ids.ravel()
        flat_sign = sign.ravel()
        valid = flat_ids < self.agg_cnt.shape[0]  # strip the tcap padding
        rows = np.repeat(np.arange(len(pending)), ids.shape[1])[valid]
        tgt = flat_ids[valid]
        s = flat_sign[valid]
        np.add.at(self.agg_cnt, tgt, s)
        np.add.at(self.agg_req, tgt, s[:, None] * req[rows])
        np.add.at(
            self.agg_contrib, tgt, (s[:, None] * pres[rows]).astype(np.int32)
        )

    def flush_agg(self) -> None:
        """Single-threaded convenience (tests): steal + apply in one go.
        Production goes through DeviceStateManager.aggregate_used_for, which
        splits the phases across the two locks."""
        self.apply_agg_work(self.steal_agg_work())

    def flip_candidate_cols(self) -> np.ndarray:
        """Cols whose throttled flags, reclassified against the CURRENT
        aggregates, differ from the last PUBLISHED flags (the ``st_*``
        staging planes, which track the status-write echo) — the
        classification delta that feeds the two-lane status pipeline.

        This is the vectorized mirror of ``Threshold.is_throttled(used,
        True)`` (api/types.py:96-128) against ``effective_threshold``:

        - counts flag  = threshold counts present ∧ used materialized
          (cnt > 0) ∧ cnt ≥ threshold;
        - per-resource flag = threshold dim present ∧ used materialized ∧
          that dim contributed (ctb > 0) ∧ used ≥ threshold;
        - a flag-map PRESENCE change (threshold dims added/removed) also
          changes the status object, so it counts as a flip too.

        One pass of ~6 elementwise ops over [T,R] — sub-ms at 10k×8, paid
        once per reconcile drain. The result is a SCHEDULING HINT for lane
        assignment/queue promotion, never an input to what gets written:
        the planes compare against the current *effective* threshold, so a
        same-drain calculatedThreshold change can mispredict here — the
        controller's own calculated-change check catches those keys.

        Caller holds the per-kind AGG lock (the ``agg_*`` arrays). The
        ``thr_*``/``st_*`` plane reads are deliberately NOT under the main
        lock: a torn read can only mis-route one key's lane for one drain,
        and taking the main lock here would serialize every drain behind
        event ingest again."""
        agg_cnt = self.agg_cnt
        if agg_cnt is None:
            return np.empty(0, dtype=np.int64)
        # defensive minima: a concurrent capacity growth may have regrown
        # the staging planes mid-read (hint-only — see docstring)
        n = min(
            agg_cnt.shape[0], self.thr_cnt.shape[0], self.st_cnt_throttled.shape[0]
        )
        r = min(
            self.agg_req.shape[1], self.thr_req.shape[1],
            self.st_req_throttled.shape[1],
        )
        cnt = agg_cnt[:n]
        has_used = cnt > 0
        new_cnt = self.thr_cnt_present[:n] & has_used & (cnt >= self.thr_cnt[:n])
        flip = new_cnt != self.st_cnt_throttled[:n]
        tp = self.thr_req_present[:n, :r]
        new_req = (
            tp
            & has_used[:, None]
            & (self.agg_contrib[:n, :r] > 0)
            & (self.agg_req[:n, :r] >= self.thr_req[:n, :r])
        )
        old_req = self.st_req_flag_present[:n, :r] & self.st_req_throttled[:n, :r]
        flip |= (
            (new_req != old_req) | (tp != self.st_req_flag_present[:n, :r])
        ).any(axis=1)
        return np.flatnonzero(flip & self.thr_valid[:n])


class DeviceStateManager:
    """Wires both kinds' staging to a Store and serves batched checks."""

    # Static-analyzer guard table (see docs/STATIC_ANALYSIS.md). Only the
    # breaker state machine is listed: the _KindState staging planes are
    # guarded by THIS manager's main lock but live on another object (out
    # of the per-class convention's reach), and _event_affected /
    # _sharded_steps are deliberately lock-free (single-writer hint /
    # idempotent compile cache — see their comments).
    GUARDED_BY = {
        "_breaker_open": "self._breaker_lock",
        "_probe_inflight": "self._breaker_lock",
        "_device_down_until": "self._breaker_lock",
    }

    def __init__(
        self,
        store: Store,
        throttler_name: str,
        target_scheduler_name: str,
        dims: Optional[DimRegistry] = None,
    ):
        self.store = store
        self.throttler_name = throttler_name
        self.target_scheduler_name = target_scheduler_name
        self.dims = dims or DimRegistry()
        self._lock = make_rlock("devicestate.main")
        self.tracer = NoopTracer()  # set by the plugin; times device checks
        # check_pod uses the indexed hot path up to this many affected
        # throttles, the dense [1,T] sweep beyond (tunable for tests)
        self.indexed_check_max = 1024
        # single-pod check routing, resolved lazily from the backend on
        # first use (see _resolve_single_check_route): accelerators always
        # route host (a dispatch is a ~70ms tunnel round trip for [K,R]
        # arithmetic); on the CPU backend the native C++ host tier beats
        # the fused XLA kernel, which in turn beats the numpy tier — so
        # kernel only without the native lib. KT_SINGLE_CHECK_DEVICE=1/0
        # forces either route (parity tests force both).
        self._single_check_device: Optional[bool] = None
        # columnar store: both kinds' indexes share the arena's intern
        # pool (one interning per label string per process), retain no pod
        # objects (Store.materialize_pod resolves the rare full-object
        # needs), and the staging encodes requests from the arena's
        # per-shape cache
        arena = getattr(store, "pod_arena", None)
        interner = arena.pool if arena is not None else None
        self.throttle = _KindState("throttle", self.dims, interner=interner)
        self.clusterthrottle = _KindState("clusterthrottle", self.dims, interner=interner)
        if arena is not None:
            for ks in (self.throttle, self.clusterthrottle):
                ks.arena = arena
                ks.index.pod_resolver = store.materialize_pod
        # per-kind aggregate-flush locks: agg_* arrays are touched only
        # under these, so the reconcile's device dispatches never hold the
        # main lock (lock order: agg → main; nothing takes main → agg)
        self._agg_locks = {
            "throttle": make_lock("devicestate.agg.throttle"),
            "clusterthrottle": make_lock("devicestate.agg.clusterthrottle"),
        }
        # compiled shard_map steps for full_tick_sharded, keyed
        # (mesh, on_equal, step3) — rebuilding the jit wrapper per call
        # would recompile every tick
        self._sharded_steps: dict = {}
        # {id(pod): (pod object, {kind: keys|None})} — see _handle_pod /
        # _on_pod_run; read lock-free (swapped wholesale under the GIL)
        self._event_affected: Optional[dict] = None
        # {kind: workqueue.add_all_priority} wired by the plugin: the
        # micro-batched ingest's single per-batch flip promotion
        # (_promote_ingest_flips) pushes keys whose throttled flags just
        # went stale straight into the controllers' priority lanes
        self.flip_promoters: Dict[str, Callable] = {}
        # device circuit breaker: a failed dispatch (backend/tunnel died)
        # opens it for a cooldown so callers fall back to their host-oracle
        # paths instead of paying a failing dispatch per decision. The host
        # staging keeps accumulating during an outage (handlers are pure
        # numpy) and the pending-overflow full-rebase mark self-heals the
        # aggregates on recovery, so reopening needs no special resync.
        #
        # Three states (closed → open → half-open → closed/open): after the
        # cooldown expires the breaker goes HALF-OPEN and admits exactly ONE
        # probe dispatch; success closes it, failure re-opens for another
        # cooldown. The former blind reopen let every concurrent caller pile
        # onto a still-dead backend the instant the 30s elapsed — under a
        # hard outage that is a synchronized multi-second dispatch stall per
        # cooldown period across every serving thread.
        self.device_retry_cooldown = 30.0
        self._device_down_until = 0.0
        self._breaker_lock = make_lock("devicestate.breaker")
        self._breaker_open = False  # False = closed; half-open is derived
        self._probe_inflight = False
        self._monotonic = None  # test injection point; defaults to time.monotonic
        # optional FaultPlan: site "device.dispatch" fails guarded dispatches
        # deterministically (chaos tests drive the breaker through it)
        self.faults = None
        self.fallback_counter = None  # CounterVec set by the plugin
        # {kind: ReservedResourceAmounts} wired by the plugin once the
        # controllers exist: lets _on_any_throttle replay standing
        # reservations onto freshly allocated columns (see there)
        self.reservation_sources: Dict[str, ReservedResourceAmounts] = {}
        # per-pod-object request-encode memo (see check_pod), keyed by
        # id(pod) because Pod is unhashable (dict fields); a weakref
        # finalizer evicts the entry when the pod is collected, and lookups
        # verify identity (`ref() is pod`) against id reuse
        self._encode_cache: Dict[int, tuple] = {}
        # per-pod-object verdict-FINGERPRINT memo (see verdict_fingerprint):
        # same id()+weakref discipline as _encode_cache, revalidated against
        # both indexes' matching generation so a selector/namespace change
        # can never serve a stale matched-cols set
        self._fp_memo: Dict[int, tuple] = {}

        store.add_event_handler("Namespace", self._on_namespace)
        store.add_event_handler("Pod", self._on_pod)
        store.add_event_handler("Throttle", self._on_throttle)
        store.add_event_handler("ClusterThrottle", self._on_cluster_throttle)
        # micro-batched ingest: one on_batch call per apply_events /
        # batched status drain replaces the per-event handler calls above
        # (they skip while store.in_batch_dispatch is set)
        store.add_batch_listener(self)

    def _now_monotonic(self) -> float:
        return (self._monotonic or time.monotonic)()

    def device_available(self) -> bool:
        """False while the circuit breaker is open (recent device failure);
        callers should serve from their host-oracle paths meanwhile. True
        once the cooldown has expired (half-open: a probe is allowed)."""
        with self._breaker_lock:
            return (
                not self._breaker_open
                or self._now_monotonic() >= self._device_down_until
            )

    def breaker_state(self) -> str:
        """``closed`` | ``open`` | ``half-open`` — the metrics gauge and the
        /readyz device component read this. Half-open means the cooldown has
        elapsed and the next guarded dispatch (or the one in flight) is the
        probe that decides."""
        with self._breaker_lock:
            if not self._breaker_open:
                return "closed"
            if self._now_monotonic() < self._device_down_until:
                return "open"
            return "half-open"

    def guarded(self, surface: str, fn, *args, **kwargs):
        """Run one device dispatch behind the circuit breaker.

        Returns the dispatch result, or None when the breaker is open or
        the dispatch raised (opening it). While HALF-OPEN exactly one
        caller becomes the probe; everyone else keeps falling back to host
        until the probe's verdict is in — so a still-dead backend costs one
        thread one failing dispatch per cooldown, not a stampede. THE
        single guard implementation — every serving surface (per-pod check,
        batch triage, reconcile) routes through here so breaker semantics
        cannot drift between hand-rolled copies. All guarded dispatches
        return dicts, so None is unambiguous."""
        probe = False
        with self._breaker_lock:
            if self._breaker_open:
                if self._now_monotonic() < self._device_down_until:
                    return None  # open: cooldown running
                if self._probe_inflight:
                    return None  # half-open: someone else is probing
                self._probe_inflight = True
                probe = True
        try:
            if self.faults is not None:
                self.faults.maybe_raise(
                    "device.dispatch",
                    default=lambda: RuntimeError("injected device fault"),
                )
            result = fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — any dispatch failure opens it
            self.note_device_failure(surface, e)
            return None
        if probe:
            with self._breaker_lock:
                self._breaker_open = False
                self._probe_inflight = False
            logger.info(
                "device probe on %s succeeded; circuit breaker closed", surface
            )
        return result

    def note_device_failure(self, surface: str, exc: BaseException) -> None:
        """Open the breaker for ``device_retry_cooldown`` seconds and count
        the fallback. Called by controllers when a device dispatch raises
        (tunnel drop, backend death) right before they fall back to host."""
        with self._breaker_lock:
            self._breaker_open = True
            self._probe_inflight = False
            self._device_down_until = (
                self._now_monotonic() + self.device_retry_cooldown
            )
        if self.fallback_counter is not None:
            self.fallback_counter.inc({"surface": surface})
        logger.warning(
            "device dispatch failed on %s (%s: %s); serving host-side for %.0fs",
            surface, exc.__class__.__name__, str(exc)[:200], self.device_retry_cooldown,
        )

    def prewarm(self) -> int:
        """Compile the steady-state CHECK kernels for every bucket shape the
        serving path can hit, so serving never pays a mid-burst XLA
        compile — one compile is ~10-100ms on CPU and can be seconds
        through a cold TPU tunnel, which lands straight in the
        event→status lag tail. (The aggregate data plane is all-host now —
        see steal/apply_agg_work — so no aggregate kernels exist to warm.)
        All warm dispatches are semantic no-ops (padding-only indices)
        against the live handles. Returns the number of kernel dispatches
        issued. Call after cache sync, before serving."""
        import jax

        from ..ops.fastcheck import fast_check_pod_packed

        ladder = _bucket_ladder(CHECK_LADDER_MAX)
        # warm dispatches EXECUTE, not just compile, so walk only shapes the
        # serving path can actually hit. The aggregate data plane needs NO
        # warming at all: deltas AND rebases are host numpy now
        # (steal_agg_work/apply_agg_work), so the only device shapes left
        # are the check kernels. Notably this also keeps prewarm off the
        # dense [P,T] device mask entirely (device_pods(need_mask=False)):
        # at 100k×10k that upload is ~2.1 GB through the TPU tunnel.
        on_cpu = jax.devices()[0].platform == "cpu"
        n = 0
        for kind in ("throttle", "clusterthrottle"):
            ks = self._kind(kind)
            with self._lock:
                ks.ensure_capacity()
                packed = ks.device_packed()
                R = ks.R
            # the indexed single-pod check (the PreFilter fast path): the
            # K-affected buckets actually seen are small; warm the bottom
            # two rungs with the kind's live step3 variant (pre_filter
            # always passes on_equal=False, plugin.go:153,165)
            step3 = kind == "throttle"
            row_req = np.zeros(R, dtype=np.int64)
            row_present = np.zeros(R, dtype=bool)
            for kb in ladder[:2]:
                idx = np.zeros(kb, dtype=np.int32)
                idx_valid = np.zeros(kb, dtype=bool)
                np.asarray(
                    fast_check_pod_packed(
                        packed, row_req, row_present, idx, idx_valid, False, step3
                    )
                )
                n += 1
            # the sparse [P,K] batch-triage kernel (the served
            # pre_filter_batch path): walk the K-ladder rungs the sparse
            # path can occupy — a pod relabel can grow the rung at runtime
            # (K 4→16), and an unwarmed rung would stall the next batch
            # dispatch mid-serving. Bottom rungs only on CPU (execution is
            # real work there); every sparse-eligible rung on TPU. Dense
            # fallback is NOT warmed: it only activates on near-dense
            # masks, where one [P,T,R] execution is exactly the
            # multi-second dispatch prewarm must not issue.
            with self._lock:
                state = ks.device_state()
                pods, _ = ks.device_pods(need_mask=False)
                live_cols = ks.device_cols()
            # true pow2 like _rebuild_cols' K (NOT the ×4 ladder) so every
            # rung the live cols can occupy is warm
            k_rungs = []
            k = 4
            while k * 4 < max(ks.tcap, 16):
                k_rungs.append(k)
                k *= 2
            if on_cpu:
                k_rungs = k_rungs[:2]
            if live_cols is not None and live_cols.shape[1] not in k_rungs:
                k_rungs.append(live_cols.shape[1])
            for kb in k_rungs:
                warm_cols = jnp.full((ks.pcap, kb), -1, dtype=jnp.int32)
                _, ok = check_pods_gather(
                    state, pods, warm_cols, on_equal=False, step3_on_equal=step3
                )
                jax.device_get(ok)
                n += 1
        return n

    # -- event wiring -----------------------------------------------------

    def on_batch(self, events: List[Event]) -> None:
        """Store batch-listener hook (one call per ``apply_events`` /
        batched status drain, under the store lock): process the batch's
        events in order, coalescing CONSECUTIVE Pod-event runs through the
        batched mirror path (_on_pod_run — one main-lock hold, batched
        index upsert, telescoped same-pod deltas), then — when any pod
        deltas accumulated — land them in the aggregates via the batched
        delta kernel encoding and promote the resulting flip candidates to
        the controllers' priority lanes ONCE per batch
        (_promote_ingest_flips). Per-event handlers re-fire afterwards with
        ``store.in_batch_dispatch`` set; _on_pod & co. skip on it."""
        run: List[Event] = []
        saw_pods = False
        for event in events:
            if event.kind == "Pod":
                run.append(event)
                continue
            if run:
                self._on_pod_run(run)
                run = []
                saw_pods = True
            if event.kind == "Namespace":
                self._handle_namespace(event)
            elif event.kind == "Throttle":
                self._handle_any_throttle(self.throttle, event)
            else:
                self._handle_any_throttle(self.clusterthrottle, event)
        if run:
            self._on_pod_run(run)
            saw_pods = True
        if saw_pods and self.flip_promoters:
            self._promote_ingest_flips()

    def _on_namespace(self, event: Event) -> None:
        if self.store.in_batch_dispatch:
            return  # already processed by on_batch
        self._handle_namespace(event)

    def _handle_namespace(self, event: Event) -> None:
        self._event_affected = None  # ns changes can re-route matching
        with self._lock:
            for ks in (self.throttle, self.clusterthrottle):
                if event.type == EventType.DELETED:
                    # deletion must NOT re-upsert: pods of a deleted
                    # namespace can no longer match any clusterthrottle
                    ks.index.remove_namespace(event.obj.name)
                else:
                    ks.index.upsert_namespace(event.obj)
            # only clusterthrottle mask rows can flip on namespace events
            # (the throttle index's upsert/remove drop bookkeeping only), so
            # only that kind pays the device mask re-upload and the full
            # aggregate rebase
            self.clusterthrottle.refresh_mask()
            self.clusterthrottle.mark_full_rebase()
            # ns add/edit/delete can re-route clusterthrottle matching for
            # any pod (and flips the unknown-ns → ERROR contract), with no
            # single-col footprint — invalidate every cached verdict whose
            # key includes clusterthrottle cols (all keys include the
            # kind's global epoch)
            self.clusterthrottle.global_epoch += 1

    def _on_pod(self, event: Event) -> None:
        if self.store.in_batch_dispatch:
            return  # already processed by on_batch
        self._handle_pod(event)

    def _handle_pod(self, event: Event) -> None:
        pod = event.obj
        count_in = (
            pod.spec.scheduler_name == self.target_scheduler_name and pod.is_scheduled()
        )
        counted = count_in and pod.is_not_finished()
        with self._lock:
            # evict the request-encode memo for BOTH event-object versions:
            # updates normally arrive as new objects (new id), but a caller
            # that mutated the stored object in place and re-updated it
            # keeps the id — without this, check_pod would serve the stale
            # encoded row
            self._encode_cache.pop(id(pod), None)
            if event.old_obj is not None:
                self._encode_cache.pop(id(event.old_obj), None)
            # computed ONCE against the manager's registry — the SAME
            # object both kinds encode against (they are constructed with
            # self.dims), so the shared-entry handoff is structural, not a
            # docstring promise. Previously the Fraction arithmetic + dim
            # interning ran twice per event, once per kind.
            entries = (
                None
                if event.type == EventType.DELETED
                # arena-absorbed pods resolve from the interned
                # request-shape cache (zero per-pod Fraction math)
                else self.throttle.pod_request_entries(pod)
            )
            # labels+namespace unchanged ⇒ neither kind's mask row can have
            # moved ⇒ delta-capture may reuse begin's matched cols (skips
            # 2 of the 4 per-event mask-row nonzeros)
            row_stable = (
                event.type == EventType.MODIFIED
                and event.old_obj is not None
                and event.old_obj.labels == pod.labels
                and event.old_obj.namespace == pod.namespace
            )
            affected: Dict[str, Optional[List[str]]] = {}
            for ks in (self.throttle, self.clusterthrottle):
                ks.capture_pod_delta_begin(pod.key)
                if event.type == EventType.DELETED:
                    ks.remove_pod_row(pod.key)
                else:
                    ks.set_pod_row(
                        pod, counted=counted, count_in=count_in, entries=entries
                    )
                ks.capture_pod_delta_end(pod.key, row_stable=row_stable)
                # no refresh_mask: a pod event only changes its own mask row,
                # which the incremental row scatter ships
                affected[ks.kind] = self._affected_from_cols_locked(
                    ks, pod, event.type, ks.last_event_cols
                )
            # per-event affected-keys cache: the controllers' pod handlers
            # (and reserve/unreserve walks on the same stored object) query
            # affected_throttle_keys for THIS pod right after this handler,
            # each paying a main-lock round trip under drain contention for
            # a nonzero the delta capture above already did. Keyed by object
            # identity (the entry holds a strong ref — no id() reuse),
            # swapped atomically (dict assignment under the GIL),
            # invalidated by any event that can change pod↔throttle
            # matching (throttle selector change/add/delete, namespace
            # change). The batched pod path publishes one entry per
            # distinct pod of the batch through the same shape.
            self._event_affected = {id(pod): (pod, affected)}

    def _affected_from_cols_locked(self, ks: _KindState, pod, etype, cols):
        """The event's affected-throttle key list for the per-event cache.
        When the delta capture produced no cols (pod not counted — e.g.
        Pending — or zero matches), the mask row is still authoritative for
        any indexed pod, so read it directly: publishing None here sent
        EVERY such query (notably the no-clusterthrottle common case) down
        the locked fallback, a main-lock round trip per event per kind
        under drain contention."""
        if cols is None and etype != EventType.DELETED:
            row = ks.index.pod_row(pod.key)
            if row is not None:
                cols = ks.index.row_cols(row)
        if cols is None:
            return None
        with ks.index._lock:  # noqa: SLF001 — _col_keys' declared guard
            ck = ks.index._col_keys
            return [ck[c] for c in cols.tolist() if c in ck]

    def _on_pod_run(self, events: List[Event]) -> None:
        """Batched mirror update for a consecutive run of Pod events.

        Same-pod events TELESCOPE: the aggregate delta of (old→v1) + (v1→v2)
        equals (old→v2), and only the final version's staging row survives —
        so each distinct pod is processed once, against its FIRST old
        contribution and its FINAL object. Distinct pods' rows, captures,
        and deltas are independent (a pod event touches only its own mask
        row), so snapshot-all-olds → batch-apply → finish-all-deltas is
        observably identical to per-event processing (property-tested in
        tests/test_batch_ingest.py). The index upsert is the batched form:
        label columns for the whole run land before one re-match pass."""
        if len(events) == 1:
            self._handle_pod(events[0])
            return
        finals: Dict[str, Event] = {}
        stable: Dict[str, bool] = {}
        for ev in events:
            k = ev.obj.key
            finals[k] = ev  # dict keeps first-seen order
            # per-event label/ns stability chains: old_obj is the previous
            # stored object, so all-stable links ⇒ first-old → final-new
            # stable ⇒ the mask row never moved across the whole run
            stable[k] = stable.get(k, True) and (
                ev.type == EventType.MODIFIED
                and ev.old_obj is not None
                and ev.old_obj.labels == ev.obj.labels
                and ev.old_obj.namespace == ev.obj.namespace
            )
        affected_cache: dict = {}
        with self._lock:
            for ev in events:
                # evict the request-encode memo for EVERY version the batch
                # carried, exactly like the per-event path
                self._encode_cache.pop(id(ev.obj), None)
                if ev.old_obj is not None:
                    self._encode_cache.pop(id(ev.old_obj), None)
            plans = []  # (key, final event, counted, count_in, entries)
            for key, ev in finals.items():
                pod = ev.obj
                if ev.type == EventType.DELETED:
                    plans.append((key, ev, False, False, None))
                    continue
                count_in = (
                    pod.spec.scheduler_name == self.target_scheduler_name
                    and pod.is_scheduled()
                )
                counted = count_in and pod.is_not_finished()
                entries = self.throttle.pod_request_entries(pod)
                plans.append((key, ev, counted, count_in, entries))
            for ks in (self.throttle, self.clusterthrottle):
                # phase 1: old contributions for every distinct pod (no
                # mutation has happened yet, so these are the begin-side
                # snapshots of every per-event capture)
                olds = {key: ks._pod_contribution(key) for key in finals}
                # phase 2: one batched row apply — deletions drop rows,
                # upserts go through the index's batch path (one lock
                # hold, label columns first, one re-match pass)
                ks.set_pod_rows(
                    [p for p in plans if p[1].type != EventType.DELETED]
                )
                for key, ev, _, _, _ in plans:
                    if ev.type == EventType.DELETED:
                        ks.remove_pod_row(key)
                # phase 3: finish every delta against its old snapshot
                for key, ev, _, _, _ in plans:
                    pod = ev.obj
                    row_stable = stable[key] and olds[key] is not None
                    ks.finish_pod_delta(key, olds[key], row_stable=row_stable)
                    entry = affected_cache.setdefault(id(pod), (pod, {}))
                    entry[1][ks.kind] = self._affected_from_cols_locked(
                        ks, pod, ev.type, ks.last_event_cols
                    )
            self._event_affected = affected_cache

    def _promote_ingest_flips(self) -> None:
        """ONE flip-candidate detection + ONE priority-lane promotion per
        ingest batch: land the batch's accumulated pod deltas in the host
        aggregates (apply_pending_batched — the batched delta kernel
        encoding), reclassify against the published ``st_*`` planes, and
        push every key whose flags just went stale into its controller's
        priority lane. The promoted keys were already enqueued normal-lane
        by the controllers' handlers, so add_all_priority MOVES them — the
        flip overtakes the refresh backlog without waiting for the next
        reconcile drain's classification pass.

        Skips (leaving everything to the next reconcile's steal) whenever
        a rebase is staged: recomputing a column — let alone the full
        [P,T] scan — inside the store's dispatch would stall every
        ingest producer behind it. Lock order: store (held by caller) →
        agg → main, consistent with aggregate_used_for's agg → main."""
        for kind in ("throttle", "clusterthrottle"):
            promoter = self.flip_promoters.get(kind)
            if promoter is None:
                continue
            ks = self._kind(kind)
            keys: List[str] = []
            with self._agg_locks[kind]:
                with self._lock:
                    shapes_ok = (
                        ks.agg_cnt is not None
                        and ks.agg_cnt.shape == (ks.tcap,)
                        and ks.agg_req.shape == (ks.tcap, ks.R)
                    )
                    if (
                        not shapes_ok
                        or ks._agg_full_rebase
                        or ks._agg_rebase_cols
                        or not ks._agg_pending
                    ):
                        continue
                    pending, ks._agg_pending = ks._agg_pending, []
                ks.apply_pending_batched(pending)
                cols = ks.flip_candidate_cols()
                if cols.size:
                    with ks.index._lock:  # noqa: SLF001 — declared guard
                        ck = ks.index._col_keys
                        keys = [ck[c] for c in cols.tolist() if c in ck]
            if keys:
                promoter(keys)

    def _on_any_throttle(self, ks: _KindState, event: Event) -> None:
        if self.store.in_batch_dispatch:
            return  # already processed by on_batch
        self._handle_any_throttle(ks, event)

    def _handle_any_throttle(self, ks: _KindState, event: Event) -> None:
        thr = event.obj
        responsible = thr.spec.throttler_name == self.throttler_name
        with self._lock:
            if event.type == EventType.DELETED or not responsible:
                self._event_affected = None  # membership changed
                # also handles a throttlerName edit AWAY from this throttler:
                # the mirrored row must disappear, or it would keep blocking
                # pods this throttler no longer governs
                col = ks.remove_throttle_row(thr.key)
                ks.mark_col_rebase(col)
                ks.refresh_mask()
                return
            # a MODIFIED whose selector is unchanged — overwhelmingly the
            # status write-back echo of our own reconcile — cannot flip any
            # mask cell: skip the O(P) column re-match, the full-mask device
            # re-upload, and the aggregate column rebase. Without this,
            # every reconcile's own status write invalidates the [P,T] mask
            # (at 100k×10k that is a ~1GB upload per reconcile batch).
            # The throttle must ALREADY be indexed: a throttlerName handover
            # TO this throttler arrives as MODIFIED with an unchanged
            # selector, but its column has yet to be built — treating it as
            # unchanged would leave the throttle silently unenforced.
            selector_changed = not (
                event.type == EventType.MODIFIED
                and event.old_obj is not None
                and event.old_obj.spec.selector == thr.spec.selector
                and ks.index.throttle_col(thr.key) is not None
            )
            fresh_col = ks.index.throttle_col(thr.key) is None
            col = ks.set_throttle_row(
                thr, selector_changed=selector_changed, old=event.old_obj
            )
            if fresh_col:
                # reservations OUTLIVE the throttle object (the reference's
                # cache is keyed by name and never cleared on deletion —
                # reserved_resource_amounts.go has no delete hook), but a
                # re-created throttle — or a throttlerName handover back,
                # which arrives as MODIFIED — gets a FRESH zeroed column
                # here. Replay the standing reserved amount or the device
                # check under-counts reserved until the next
                # reserve/unreserve touches the key (differential soak
                # seed 20: device said not-throttled where the host oracle
                # said insufficient). Only on fresh columns, so status
                # echoes pay nothing.
                cache = self.reservation_sources.get(ks.kind)
                if cache is not None:
                    amount, _ = cache.reserved_resource_amount(thr.key)
                    ks.set_reserved_row(thr.key, amount)
            if selector_changed:
                self._event_affected = None  # membership changed
                ks.mark_col_rebase(col)
                ks.refresh_mask()

    def _on_throttle(self, event: Event) -> None:
        self._on_any_throttle(self.throttle, event)

    def _on_cluster_throttle(self, event: Event) -> None:
        self._on_any_throttle(self.clusterthrottle, event)

    def install_flip_promoters(self, promoters: Dict[str, Callable]) -> None:
        """Wire {kind: add_all_priority} for the per-ingest-batch flip
        promotion (the plugin calls this once the controllers exist)."""
        self.flip_promoters = dict(promoters)

    def on_reservation_change(
        self, kind: str, throttle_key: str, cache: ReservedResourceAmounts
    ) -> None:
        # read the amount INSIDE the same lock hold that writes the row:
        # read-then-lock let two concurrent updates for one key commit out
        # of order, leaving a stale reserved row until the next touch. The
        # reservation locks are leaf locks, so nesting under _lock is safe
        # (the fresh-column replay in _on_any_throttle nests the same way).
        with self._lock:
            amount, _ = cache.reserved_resource_amount(throttle_key)
            ks = self.throttle if kind == "throttle" else self.clusterthrottle
            ks.set_reserved_row(throttle_key, amount)

    def _kind(self, kind: str) -> _KindState:
        return self.throttle if kind == "throttle" else self.clusterthrottle

    def published_flags(self) -> Dict[str, Dict[str, dict]]:
        """Per-key decode of the published ``st_*`` planes: ``{kind:
        {throttle_key: {"pod": bool, "requests": {resource: bool}}}}`` —
        the last PUBLISHED throttled flags each live column carries.
        Snapshots record this (engine/snapshot.py) and recovery compares
        the rebuilt planes against the restored statuses with it
        (engine/recovery.py's divergence oracle).

        Reads the planes lock-free like flip_candidate_cols: call under
        the store lock (the snapshot path does — every plane writer is a
        store handler) or with ingest quiescent (the recovery path)."""
        names = self.dims.names
        out: Dict[str, Dict[str, dict]] = {}
        for kind in ("throttle", "clusterthrottle"):
            ks = self._kind(kind)
            per_key: Dict[str, dict] = {}
            cnt = ks.st_cnt_throttled
            pres, req = ks.st_req_flag_present, ks.st_req_throttled
            r = min(len(names), pres.shape[1])
            for key, col in ks.index.throttle_cols_snapshot().items():
                if col is None or col >= cnt.shape[0]:  # pragma: no cover — racing growth
                    continue
                requests = {
                    names[j]: bool(req[col, j])
                    for j in np.nonzero(pres[col, :r])[0]
                }
                per_key[key] = {"pod": bool(cnt[col]), "requests": requests}
            out[kind] = per_key
        return out

    # -- index-backed collection queries (replace the O(T)/O(P) store scans
    # of throttle_controller.go:221-269) ----------------------------------

    def affected_throttle_keys(self, kind: str, pod: Pod) -> List[str]:
        """affectedThrottles via the incremental mask: O(K) when the queried
        object is the indexed one, a fresh compiled-row evaluation otherwise
        (old side of a MODIFIED event, or a pod not yet stored).

        Lock-free fast path: when the queried object IS the pod of the most
        recent pod event (the controllers' handlers run synchronously right
        after the mirror's), _on_pod already published its matched keys —
        skipping the main-lock round trip that otherwise serializes every
        handler behind in-flight reconcile flushes (measured ~25% of
        remote-wire ingest cost at 10k×1k)."""
        cached = self._event_affected
        if cached is not None:
            entry = cached.get(id(pod))
            if entry is not None and entry[0] is pod:
                keys = entry[1].get(kind)
                if keys is not None:
                    return list(keys)
        with self._lock:
            return self._kind(kind).index.affected_throttle_keys_for(pod)

    def matched_pods(self, kind: str, throttle_key: str) -> List[Pod]:
        """affectedPods' selector part via the mask column (latest objects)."""
        with self._lock:
            return self._kind(kind).index.matched_pods(throttle_key)

    def indexed_pod(self, kind: str, pod_key: str) -> Optional[Pod]:
        with self._lock:
            return self._kind(kind).index.indexed_pod(pod_key)

    # -- gang admission (batched group feasibility, ops/gang_check.py) -----

    def has_accel_thresholds(self, kind: str) -> bool:
        """True when any mirrored throttle of ``kind`` declares
        accelClassThresholds — the gate that routes accel-class pods'
        single-pod checks to the class-aware host oracle (the per-pod
        device planes carry only the base thresholds). Lock-free len
        probe: a torn read mis-routes at most one decision between two
        CORRECT paths."""
        return bool(self._kind(kind).accel_cols)

    def gang_check_groups(self, groups) -> Dict[str, dict]:
        """Batched all-or-nothing feasibility for a tick's worth of pod
        groups: ``groups`` is ``[(group_key, [member Pod, ...],
        accel_class|None)]``. ONE fused dispatch (``gang_check_both``)
        evaluates every group against BOTH kinds' full throttle state —
        per-(group, col) totals as segment-sum scatters over the same
        (N, K) sparse matched-cols encoding the batch check uses — no
        per-rank host loop and no per-kind second dispatch.

        Returns ``{group_key: {"ok": bool, "kinds": {kind: {"ok",
        "exceeds", "active", "blocked": [throttle_key, ...]}}}}`` — the
        blocked keys feed reference-style reason strings host-side.

        Locking mirrors check_pod: the main lock covers only the host
        snapshot (member encodes, matched cols, plane copies, class-plane
        encode); the dispatch and decode run outside it. Shapes ladder-pad
        (members, groups, per-kind K) so a tick burst never recompiles."""
        from ..ops.gang_check import gang_check_both
        from ..ops.overrides import encode_class_thresholds

        if not groups:
            return {}
        classes: List[str] = []
        for _gk, _pods, cls in groups:
            if cls and cls not in classes:
                classes.append(cls)
        members: List[Tuple[int, Pod]] = []
        for g, (_gk, pods, _cls) in enumerate(groups):
            for pod in pods:
                members.append((g, pod))
        N = _next_pow2(max(len(members), 1))
        G = _next_pow2(max(len(groups), 1), lo=4)
        gid = np.zeros(N, dtype=np.int32)
        member_valid = np.zeros(N, dtype=bool)
        gvalid = np.zeros(G, dtype=bool)
        gvalid[: len(groups)] = True
        gclass = np.zeros(G, dtype=np.int32)
        for g, (_gk, _pods, cls) in enumerate(groups):
            gclass[g] = (classes.index(cls) + 1) if cls else 0

        per_kind: Dict[str, dict] = {}
        col_key_maps: Dict[str, dict] = {}
        with self._lock:
            for kind in ("throttle", "clusterthrottle"):
                self._kind(kind).ensure_capacity()
            R = self.dims.capacity
            pod_req = np.zeros((N, R), dtype=np.int64)
            pod_present = np.zeros((N, R), dtype=bool)
            member_cols: Dict[str, List[np.ndarray]] = {
                "throttle": [], "clusterthrottle": []
            }
            for i, (g, pod) in enumerate(members):
                gid[i] = g
                member_valid[i] = True
                row_req, row_pres = self._encoded_row(self.throttle, pod)
                pod_req[i, : row_req.shape[1]] = row_req[0]
                pod_present[i, : row_pres.shape[1]] = row_pres[0]
                for kind in ("throttle", "clusterthrottle"):
                    ks = self._kind(kind)
                    prow = ks.index.pod_row(pod.key)
                    if prow is not None:
                        cols = ks.index.row_cols(prow)
                    else:
                        # pending pod not yet stored: compiled-row match,
                        # same path as check_pod's PreFilter case
                        with ks.index._lock:  # noqa: SLF001 — same-package access
                            rowmask = (
                                ks.index.match_row_cached_locked(pod)
                                & ks.index._thr_valid
                            )
                        cols = np.nonzero(rowmask[: ks.tcap])[0]
                    member_cols[kind].append(cols.astype(np.int32))
            for kind in ("throttle", "clusterthrottle"):
                ks = self._kind(kind)
                kmax = max((c.size for c in member_cols[kind]), default=0)
                K = _next_pow2(max(kmax, 1), lo=4)
                cols_arr = np.full((N, K), -1, dtype=np.int32)
                for i, cols in enumerate(member_cols[kind]):
                    cols_arr[i, : cols.size] = cols
                cls_cnt, cls_cnt_p, cls_req, cls_req_p = encode_class_thresholds(
                    ks.thr_cnt, ks.thr_cnt_present, ks.thr_req,
                    ks.thr_req_present, ks.accel_cols, classes, self.dims,
                )
                per_kind[kind] = {
                    "pod_req": pod_req,
                    "pod_present": pod_present,
                    "member_valid": member_valid,
                    "cols": cols_arr,
                    "gid": gid,
                    "thr_valid": ks.thr_valid.copy(),
                    "cls_cnt": cls_cnt,
                    "cls_cnt_present": cls_cnt_p,
                    "cls_req": cls_req,
                    "cls_req_present": cls_req_p,
                    "st_cnt_throttled": ks.st_cnt_throttled.copy(),
                    "st_req_flag_present": ks.st_req_flag_present.copy(),
                    "st_req_throttled": ks.st_req_throttled.copy(),
                    "au_cnt": (ks.used_cnt + ks.res_cnt),
                    "au_req": (ks.used_req + ks.res_req),
                }
                with ks.index._lock:  # noqa: SLF001 — declared guard
                    col_key_maps[kind] = dict(ks.index._col_keys)

        # ---- outside the lock: the single fused dispatch + decode --------
        ok, (out_t, out_c) = gang_check_both(
            per_kind["throttle"], per_kind["clusterthrottle"],
            jnp.asarray(gclass), jnp.asarray(gvalid), num_groups=G,
        )
        ok = np.asarray(ok)
        details = {"throttle": out_t, "clusterthrottle": out_c}
        decoded = {
            kind: tuple(np.asarray(a) for a in out)
            for kind, out in details.items()
        }
        results: Dict[str, dict] = {}
        for g, (gk, _pods, _cls) in enumerate(groups):
            kinds_out = {}
            for kind in ("throttle", "clusterthrottle"):
                okk, exceeds, active, blocked = decoded[kind]
                ckmap = col_key_maps[kind]
                kinds_out[kind] = {
                    "ok": bool(okk[g]),
                    "exceeds": bool(exceeds[g]),
                    "active": bool(active[g]),
                    "blocked": [
                        ckmap[c]
                        for c in np.nonzero(blocked[g])[0].tolist()
                        if c in ckmap
                    ],
                }
            results[gk] = {"ok": bool(ok[g]), "kinds": kinds_out}
        return results

    # -- used aggregation (replaces reconcile's per-throttle pod-sum loop,
    # throttle_controller.go:103-119) -------------------------------------

    def aggregate_used_for(
        self,
        kind: str,
        keys: Sequence[str],
        reserved: Optional[Dict[str, set]] = None,
        flips_out: Optional[dict] = None,
    ) -> Dict[str, Tuple[ResourceAmount, List[Pod]]]:
        """status.used for the given throttles from the device aggregates,
        plus — per throttle — the reserved pods eligible for the reconcile
        unreserve walk (shouldCountIn ∧ selector-match, including terminated
        pods; throttle_controller.go:135-155).

        ``flips_out``, when a dict, is filled with the classification delta
        (``flip_candidate_cols``) partitioned against ``keys``:
        ``flips_out["drained"]`` — drained keys whose throttled flags are
        about to change (the controller commits these FIRST and routes them
        to the committer's priority lane); ``flips_out["promote"]`` — keys
        NOT in this drain whose published flags disagree with the fresh
        aggregates (the controller promotes these to the front of its
        workqueue so the next drain publishes their flip instead of cycling
        the whole refresh backlog first). The index only mirrors throttles
        this throttler is responsible for, so promoted keys never enqueue
        foreign objects.

        One flush (at most three scatter/reduce dispatches for any event
        burst) plus one gather serves the whole batch — this is the
        streaming-reconcile data plane: cost is O(events) not
        O(throttles × pods).

        The unreserve set MUST come from the same snapshot as the aggregate
        (hence one call, one lock hold): deriving it later would unreserve a
        pod that got counted AFTER the flush, whose contribution is not in
        the status about to be written — reopening the double-count window
        the reserve-until-observed handshake exists to close.

        Locking: the MAIN lock covers the host-side snapshot — the steal of
        staged aggregate work (including any rebase recompute, which must
        read a coherent mask; steady-state steals are a list swap, rebases
        are rare and bounded — see _host_rebase_full) plus the unreserve
        walk, one coherent point. The landing and the host gather run under
        the per-kind AGG lock only, so concurrent check_pod readers never
        queue behind another drain's aggregate work — the moral of the
        reference's RWMutex split (reserved_resource_amounts.go:154)."""
        from ..quantity import from_milli

        reserved = reserved or {}
        ks = self._kind(kind)
        # the agg lock is held steal→apply so two concurrent reconcile
        # batches cannot apply an older snapshot over a newer one; phases
        # are traced individually (lock wait / host snapshot / device apply
        # / gather / decode) so saturation profiles can apportion the cost
        with self.tracer.trace("agg_lock_wait"):
            self._agg_locks[kind].acquire()
        try:
            with self.tracer.trace("agg_main_lock_wait"):
                self._lock.acquire()
            try:
                with self.tracer.trace("agg_snapshot"):
                    work = ks.steal_agg_work()
                    out: Dict[str, Tuple[ResourceAmount, List[Pod]]] = {}
                    cols: List[int] = []
                    valid_keys: List[str] = []
                    for key in keys:
                        unres: List[Pod] = []
                        col = ks.index.throttle_col(key)
                        if col is not None:
                            for pod_key in reserved.get(key, ()):
                                row = ks.index.pod_row(pod_key)
                                if row is None:
                                    continue
                                if ks.count_in[row] and ks.index.row_has_col(row, col):
                                    pod = ks.index.indexed_pod(pod_key)
                                    if pod is not None:
                                        unres.append(pod)
                        if col is None:
                            # zero counted pods: both fields stay nil (the Go
                            # accumulator never materializes on an empty sum)
                            out[key] = (ResourceAmount(), unres)
                        else:
                            out[key] = (ResourceAmount(), unres)  # used filled below
                            cols.append(col)
                            valid_keys.append(key)
            finally:
                self._lock.release()
            with self.tracer.trace("agg_apply"):
                try:
                    ks.apply_agg_work(work)
                except Exception:
                    with self._lock:
                        ks.mark_full_rebase()  # stolen state consumed; recover
                    raise
            if flips_out is not None:
                # the classification delta reads the just-applied aggregates,
                # so it must run under the agg lock too
                with self.tracer.trace("agg_flips"):
                    keyset = set(keys)
                    flip_cols = ks.flip_candidate_cols().tolist()
                    # col→key rows are GUARDED_BY the index lock and this
                    # runs after the main lock is released (agg lock only):
                    # an unlocked read here can decode a flip through a
                    # col being deleted/reused concurrently and route the
                    # priority status write to the WRONG throttle — found
                    # by the lockset race detector (gen-3). Resolve just
                    # the flip cols under the index lock: O(flips), never
                    # O(tcap).
                    with ks.index._lock:  # noqa: SLF001 — same-package access
                        ck = ks.index._col_keys
                        flip_keys = [ck.get(c) for c in flip_cols]
                    drained: set = set()
                    promote: set = set()
                    for key in flip_keys:
                        if key is None:
                            continue
                        (drained if key in keyset else promote).add(key)
                    flips_out["drained"] = drained
                    flips_out["promote"] = promote
            if not cols:
                return out
            # host arrays mutate IN PLACE under the agg lock, so the gather
            # must run before releasing it; numpy fancy indexing copies, so
            # what leaves the lock is a consistent snapshot. A plain host
            # gather — no device round trip, no shape bucketing (the former
            # device-resident gather paid a pow2-padded dispatch + a
            # blocking sync per drain).
            with self.tracer.trace("agg_gather"):
                idx = np.asarray(cols, dtype=np.int32)
                cnt = ks.agg_cnt[idx]
                req = ks.agg_req[idx]
                ctb = ks.agg_contrib[idx]
        finally:
            self._agg_locks[kind].release()
        with self.tracer.trace("agg_decode"):
            names = self.dims.names
            raw_cache = ks._used_raw
            for i, key in enumerate(valid_keys):
                if cnt[i] <= 0:
                    continue  # stays the nil ResourceAmount
                requests = {
                    names[j]: from_milli(int(req[i, j]))
                    for j in range(min(len(names), req.shape[1]))
                    if ctb[i, j] > 0
                }
                amt = ResourceAmount(
                    resource_counts=int(cnt[i]), resource_requests=requests
                )
                # stash the raw int64 row beside the decoded amount so the
                # status-write echo (set_throttle_row) writes the staging
                # row without re-deriving milli values from Fractions
                pres = ctb[i] > 0
                try:
                    ref = weakref.ref(
                        amt, lambda _, k=id(amt), c=raw_cache: c.pop(k, None)
                    )
                except TypeError:
                    pass
                else:
                    raw_cache[id(amt)] = (
                        ref, int(cnt[i]), np.where(pres, req[i], 0), pres
                    )
                out[key] = (amt, out[key][1])
            # tick boundary for the runtime retrace budget: with
            # KT_JIT_RETRACE_BUDGET armed, a drain that recompiled any
            # registered jit entry after warmup fails HERE, naming the
            # entry — not as a 100ms-class latency regression two PRs out
            _retrace_on_tick()
            return out

    # -- queries ----------------------------------------------------------

    def _resolve_single_check_route(self) -> bool:
        """True ⇒ single-pod checks use the device kernel; False ⇒ the host
        classifier. Resolved once from KT_SINGLE_CHECK_DEVICE (1/0 forces)
        or the live backend+tiers: on accelerators always host (a dispatch
        is a real round trip there — ~70ms through the CI's TPU tunnel —
        for [K,R] arithmetic). On the CPU backend it depends on the host
        TIER: the native C++ classifier beats the fused XLA kernel
        (~100µs vs ~157µs per full-scale served decision, measured), but
        the numpy tier loses to it (~30 tiny numpy ops at ~86µs vs the
        kernel's ~43µs) — so kernel only when the native lib is absent."""
        if self._single_check_device is None:
            import jax

            forced = os.environ.get("KT_SINGLE_CHECK_DEVICE")
            if forced in ("0", "1"):
                self._single_check_device = forced == "1"
            else:
                self._single_check_device = (
                    jax.default_backend() == "cpu" and _native_cls_lib() is None
                )
        return self._single_check_device

    @staticmethod
    def _gather_check_rows(ks: _KindState, cols: np.ndarray):
        """Coherent [K]-row snapshot of everything the 4-step check reads,
        gathered from the host staging arrays (fancy indexing copies).
        Caller holds the main lock; the classification itself
        (_host_classify_rows) runs outside it."""
        c = cols
        return (
            ks.thr_valid[c],
            ks.thr_cnt[c], ks.thr_cnt_present[c],
            ks.thr_req[c], ks.thr_req_present[c],
            ks.st_cnt_throttled[c],
            ks.st_req_flag_present[c], ks.st_req_throttled[c],
            ks.used_cnt[c] + ks.res_cnt[c],
            ks.used_cnt_present[c] | ks.res_cnt_present[c],
            ks.used_req[c] + ks.res_req[c],
            ks.used_req_present[c] | ks.res_req_present[c],
        )

    def _encoded_row(self, ks: _KindState, pod: Pod):
        """Request encode (Fraction arithmetic over containers) for one pod
        → ([1,R] int64, [1,R] bool). Identical for both kinds and across
        scheduler retries of the same stored object — memoized per pod
        OBJECT (a pod update is a new object; GC evicts via weakref
        finalizer). Caller holds the main lock. Shared by check_pod and
        check_pods_multi: the encode is the dominant per-pod host cost
        (~25µs of Fraction math), so an unmemoized batch path would erase
        the fused dispatch's win."""
        cached = self._encode_cache.get(id(pod))
        if cached is not None and cached[0]() is pod and cached[1] == ks.R:
            return cached[2], cached[3]
        row_req = np.zeros((1, ks.R), dtype=np.int64)
        row_present = np.zeros((1, ks.R), dtype=bool)
        row_req, row_present = ks.encode_pod_requests_into(
            row_req, row_present, 0, pod
        )
        row_req.setflags(write=False)
        row_present.setflags(write=False)
        key = id(pod)
        # the finalizer must capture only the dict, not self: a lambda over
        # `self` would chain pod → weakref → manager and pin discarded
        # managers (and their device state) alive for as long as any
        # checked pod object lives
        cache = self._encode_cache
        try:
            ref = weakref.ref(pod, lambda _, k=key, c=cache: c.pop(k, None))
        except TypeError:
            pass  # non-weakref-able stand-ins: skip caching
        else:
            cache[key] = (ref, ks.R, row_req, row_present)
        return row_req, row_present

    def verdict_fingerprint(self, pod: Pod) -> Optional[Tuple[tuple, int]]:
        """``(key, epoch_sum)`` for the interned-verdict cache
        (engine/verdictcache.py), or ``None`` when the pod is uncacheable.

        A PreFilter verdict is a pure function of (request-shape id, accel
        class, matched cols of both kinds, per-col state): the 4-step check
        reads nothing else (api/types.py:535-558 — thresholds resolve from
        WRITTEN status via effective_threshold, never the live clock, so
        override windows reach verdicts only through status writes, which
        bump ``col_epoch``). The key is the pure-function domain; the
        epoch-sum is the state version. Per-col epochs are monotonic, so
        for a FIXED cols set an equal sum proves elementwise equality —
        no ABA.

        Uncacheable: no arena (no interned shape ids), or the pod's
        namespace is unknown to the clusterthrottle index (the oracle
        answers ERROR there, and an unknown-ns pod would otherwise collide
        with known-ns pods sharing its (shape, accel, empty-cols) key).

        The (sid, accel, cols) half is memoized per pod OBJECT (scheduler
        retries re-probe the same Pending pod) and revalidated against both
        indexes' matching generation — ``_gen`` bumps on every column or
        namespace mutation, exactly the set of events that can change a
        pod's matched cols. Epoch reads happen under the main lock, where
        every bump is performed, so the returned sum is a coherent point in
        the mutation order."""
        tks, cks = self.throttle, self.clusterthrottle
        with self._lock:
            memo = self._fp_memo.get(id(pod))
            if memo is not None and memo[0]() is pod:
                _, key, tcols, ccols, gt, gc = memo
                if (
                    gt == tks.index.generation()
                    and gc == cks.index.generation()
                ):
                    esum = tks.global_epoch + cks.global_epoch
                    if tcols.size:
                        esum += int(tks.col_epoch[tcols].sum())
                    if ccols.size:
                        esum += int(cks.col_epoch[ccols].sum())
                    return key, esum
            return self._build_fingerprint_locked(pod)

    def _build_fingerprint_locked(self, pod: Pod) -> Optional[Tuple[tuple, int]]:
        from ..api.pod import accel_class_of

        tks, cks = self.throttle, self.clusterthrottle
        arena = tks.arena
        if arena is None:
            return None
        # generations BEFORE the match reads: if a concurrent mutation
        # lands between them, the memo is stamped with the older gen and
        # simply rebuilds on the next probe — stale-toward-miss, never
        # stale-toward-hit
        gt = tks.index.generation()
        gc = cks.index.generation()
        if not cks.index.has_namespace(pod.namespace):
            return None
        if pod.__dict__.get("_kt_arena") is arena.token:
            sid = pod.__dict__["_kt_req_sid"]
        else:
            sid = arena.request_shape_id(pod.spec)
        accel = accel_class_of(pod)
        cols_by_kind = []
        esum = 0
        for ks in (tks, cks):
            ks.ensure_capacity()
            prow = ks.index.pod_row(pod.key)
            if prow is not None:
                row = ks.index.mask_rows(np.array([prow]))[0]
            else:
                with ks.index._lock:  # noqa: SLF001 — same-package access
                    row = ks.index.match_row_cached_locked(pod) & ks.index._thr_valid
            n = min(row.shape[0], ks.tcap)
            cols = np.nonzero(row[:n] & ks.thr_valid[:n])[0]
            cols_by_kind.append(cols)
            if cols.size:
                esum += int(ks.col_epoch[cols].sum())
            esum += ks.global_epoch
        tcols, ccols = cols_by_kind
        key = (sid, accel, tcols.tobytes(), ccols.tobytes())
        mkey = id(pod)
        memo_map = self._fp_memo
        try:
            ref = weakref.ref(pod, lambda _, k=mkey, c=memo_map: c.pop(k, None))
        except TypeError:
            pass  # non-weakref-able stand-ins: skip the memo
        else:
            memo_map[mkey] = (ref, key, tcols, ccols, gt, gc)
        return key, esum

    def check_pod(self, pod: Pod, kind: str, on_equal: bool = False) -> Dict[str, str]:
        """Single-pod check → {throttle_key: status_name} over affected
        throttles. The device kernel sees a 1-row pod batch + its mask row.

        Concurrency: the lock guards only the HOST-side snapshot (request
        encode, mask row copy, device-handle grab, key decode tables); the
        kernel dispatch + blocking device read — the dominant cost — run
        outside it. The device caches are replaced functionally (``.at[]``
        scatters / wholesale re-uploads build NEW arrays), so a grabbed
        handle is an immutable point-in-time snapshot and concurrent
        checkers don't queue behind each other or behind writers — the
        intent of the reference's RWMutex + keymutex split
        (reserved_resource_amounts.go:154-170)."""
        from ..ops.fastcheck import fast_check_pod_packed

        with self.tracer.trace("device_check"):
            dense = None
            rows = None
            packed = None
            out_k = None
            with self._lock:
                ks = self.throttle if kind == "throttle" else self.clusterthrottle
                ks.ensure_capacity()
                row_req, row_present = self._encoded_row(ks, pod)
                prow = ks.index.pod_row(pod.key)
                if prow is not None:
                    mask_row = ks.index.mask_rows(np.array([prow]))
                else:
                    # pod not (yet) in the store — the PreFilter common case:
                    # evaluate its row via the index's compiled columns
                    # (native C++ row-match behind a (ns,labels) probe LRU —
                    # scheduler retries of the same Pending pod skip the
                    # O(T) evaluation entirely; NOT a Python loop over T)
                    with ks.index._lock:  # noqa: SLF001 — same-package access
                        row = ks.index.match_row_cached_locked(pod) & ks.index._thr_valid
                    mask_row = np.zeros((1, ks.tcap), dtype=bool)
                    mask_row[0, : row.shape[0]] = row[: ks.tcap]

                step3 = True if kind == "throttle" else on_equal
                cols = np.nonzero(mask_row[0])[0]
                if cols.size == 0:
                    # no affected throttles — nothing to classify; skip the
                    # kernel dispatch entirely (with an empty clusterthrottle
                    # set this halves every pre_filter's device round trips)
                    return {}
                if cols.size <= self.indexed_check_max:
                    # tolist() converts the whole cols vector in C; the
                    # per-element int(c) form paid a numpy-scalar box per
                    # col (~240k dict.get+int calls per 6k decisions)
                    with ks.index._lock:  # noqa: SLF001 — declared guard
                        ck = ks.index._col_keys
                        col_keys = list(map(ck.get, cols.tolist()))
                    if not self._resolve_single_check_route():
                        # HOST path — the default on every backend when
                        # the native tier loads: a single pod's check is a
                        # [K,R] computation over rows that live in host
                        # staging anyway. On accelerators host arithmetic
                        # beats a device ROUND TRIP (~70ms through a
                        # remote-TPU tunnel) by orders of magnitude; on
                        # CPU the native tier beats even the fused XLA
                        # kernel. Native tier runs the whole 4-step pass
                        # in C++ against the live planes under the lock
                        # (sub-µs — the ~20-numpy-op pass measured
                        # ~50µs/kind at 100k×10k); numpy tier snapshots
                        # rows under the lock and classifies outside. The
                        # device keeps the BATCH surfaces, where
                        # parallelism actually pays. (CPU without the
                        # native lib routes to the fused kernel instead —
                        # see _resolve_single_check_route.)
                        lib = _native_cls_lib()
                        if lib is not None:
                            out_k = _native_classify_cols(
                                lib, ks, cols, row_req[0], row_present[0],
                                on_equal, step3,
                            )
                        else:
                            rows = self._gather_check_rows(ks, cols)
                    else:
                        packed = ks.device_packed()
                else:
                    dense = (ks.device_state(), dict(ks.index._thr_cols))

            # ---- outside the lock: dispatch + blocking read + decode ----
            if dense is None:
                if rows is not None:
                    out_k = _host_classify_rows(
                        rows, row_req[0], row_present[0], on_equal, step3
                    )
                elif out_k is None:
                    # device A/B path (KT_SINGLE_CHECK_DEVICE=1): classify
                    # the K affected rows against the cached packed
                    # precomp — O(K·R) device AND host work, independent
                    # of tcap. K buckets (powers of two) bound compiles.
                    k = _next_pow2(cols.size)
                    idx = np.zeros(k, dtype=np.int32)
                    idx_valid = np.zeros(k, dtype=bool)
                    idx[: cols.size] = cols
                    idx_valid[: cols.size] = True
                    out_k = np.asarray(
                        fast_check_pod_packed(
                            packed, row_req[0], row_present[0],
                            idx, idx_valid, on_equal, step3,
                        )
                    )
                result = {}
                for slot, key in enumerate(col_keys):
                    status = int(out_k[slot])
                    if status != CHECK_NOT_AFFECTED and key is not None:
                        result[key] = STATUS_NAMES[status]
                return result
            state, thr_cols = dense
            batch = PodBatch(
                valid=np.ones(1, dtype=bool), req=row_req, req_present=row_present
            )
            out = np.asarray(
                check_pods(state, batch, mask_row, on_equal=on_equal, step3_on_equal=step3)
            )[0]
            result = {}
            for key, col in thr_cols.items():
                if out[col] != CHECK_NOT_AFFECTED:
                    result[key] = STATUS_NAMES[int(out[col])]
            return result

    def check_pods_multi(
        self, pod_list: Sequence[Pod], kind: str, on_equal: bool = False
    ) -> List[Dict[str, str]]:
        """Several DISTINCT pods classified in one call — the
        micro-batching front-end's kernel. Same per-pod result shape as
        ``check_pod`` ({throttle_key: status_name}).

        Routing mirrors ``check_pod``'s resolver: on the HOST route the
        native classifier runs B sub-µs passes under the snapshot lock —
        no device involvement at all, which matters most where the
        coalescer is aimed (remote-accelerator deployments: a fused
        device dispatch still pays a full tunnel round trip per window —
        the capture-2 TPU bench measured the coalesced path at 28/s on
        exactly that). On the device route it stays ONE fused dispatch
        bucketed on (B, K) ladder rungs.

        Host-side snapshot under the lock (encode + mask rows + state
        handles); the device dispatch and all decode run outside — same
        locking discipline as check_pod."""
        from ..ops.check import check_pods_gather_statuses

        if not pod_list:
            return []
        native_out = None
        host_rows = None
        with self._lock:
            ks = self.throttle if kind == "throttle" else self.clusterthrottle
            ks.ensure_capacity()
            R, tcap = ks.R, ks.tcap
            step3 = True if kind == "throttle" else on_equal
            host_route = not self._resolve_single_check_route()
            rows, colss = [], []
            for pod in pod_list:
                row_req, row_present = self._encoded_row(ks, pod)
                prow = ks.index.pod_row(pod.key)
                if prow is not None:
                    cols = ks.index.row_cols(prow)
                else:
                    with ks.index._lock:  # noqa: SLF001 — same-package access
                        rowm = ks.index.match_row_cached_locked(pod) & ks.index._thr_valid
                    cols = np.nonzero(rowm[:tcap])[0]
                rows.append((row_req, row_present))
                colss.append(cols.astype(np.int32))
            if ks.R != R:
                # a mid-batch pod introduced a never-seen resource name:
                # encode_pod_requests_into grew ks.R and reallocated the
                # staging planes, leaving EARLIER pods' encoded rows at the
                # old width. The native tier re-registers planes at the new
                # R and would read pod_req[r]/pod_present[r] past the end of
                # those shorter rows — silent garbage verdicts (the device
                # path at least failed loudly on the shape mismatch).
                # Re-encode the whole batch: the encode memo keys on ks.R,
                # so stale-width entries miss and fresh [1, ks.R] rows come
                # back; the R-grown pod's entry is already current and hits.
                R = ks.R
                rows = [self._encoded_row(ks, pod) for pod in pod_list]
            # host tiers only while every pod's K is indexed-sized: the
            # lock-held native work stays ≤ B × indexed_check_max × R, and
            # an oversize (near-dense) pod sends the whole batch to the
            # fused dispatch, which runs outside the lock (check_pod's
            # dense-fallback analog)
            host_route = host_route and all(
                c.size <= self.indexed_check_max for c in colss
            )
            state = None
            if host_route:
                lib = _native_cls_lib()
                if lib is not None:
                    native_out = [
                        _native_classify_cols(
                            lib, ks, cc, rq[0], rp[0], on_equal, step3
                        )
                        for (rq, rp), cc in zip(rows, colss)
                    ]
                else:
                    # numpy tier: [K]-row snapshots under the lock,
                    # classification outside (mirrors check_pod)
                    host_rows = [self._gather_check_rows(ks, cc) for cc in colss]
            else:
                state = ks.device_state()
            with ks.index._lock:  # noqa: SLF001 — declared guard
                col_keys = dict(ks.index._col_keys)

        if host_rows is not None:
            native_out = [
                _host_classify_rows(hr, rq[0], rp[0], on_equal, step3)
                for hr, (rq, rp) in zip(host_rows, rows)
            ]
        if native_out is not None:
            results: List[Dict[str, str]] = []
            for cc, out_k in zip(colss, native_out):
                res: Dict[str, str] = {}
                for slot, col in enumerate(cc.tolist()):
                    status = int(out_k[slot])
                    if status != CHECK_NOT_AFFECTED:
                        key = col_keys.get(col)
                        if key is not None:
                            res[key] = STATUS_NAMES[status]
                results.append(res)
            return results

        B = len(pod_list)
        Bp = _next_pow2(B, lo=4)
        K = _next_pow2(max((c.size for c in colss), default=1) or 1, lo=4)
        req = np.zeros((Bp, R), dtype=np.int64)
        present = np.zeros((Bp, R), dtype=bool)
        valid = np.zeros(Bp, dtype=bool)
        cols_arr = np.full((Bp, K), -1, dtype=np.int32)
        for i, ((rq, rp), cc) in enumerate(zip(rows, colss)):
            req[i] = rq[0]
            present[i] = rp[0]
            valid[i] = True
            cols_arr[i, : cc.size] = cc
        # numpy args go straight into the jitted call: jit's argument path
        # converts them ~an order of magnitude cheaper than explicit
        # jnp.asarray device_puts (measured 361µs vs 39µs per call here)
        batch = PodBatch(valid=valid, req=req, req_present=present)
        out = np.asarray(
            check_pods_gather_statuses(
                state, batch, cols_arr,
                on_equal=on_equal, step3_on_equal=step3,
            )
        )
        results: List[Dict[str, str]] = []
        for i in range(B):
            res: Dict[str, str] = {}
            cc = colss[i]
            for slot in range(cc.size):
                status = int(out[i, slot])
                if status != CHECK_NOT_AFFECTED:
                    key = col_keys.get(int(cc[slot]))
                    if key is not None:
                        res[key] = STATUS_NAMES[status]
            results.append(res)
        return results

    def _grab_batch_handles(self, kind: str, on_equal: bool):
        """Under the caller's lock: one kind's immutable device handles +
        decode table for a batch check. ``cols`` is the sparse [P,K]
        companion of the mask (None ⇒ dense kernel)."""
        ks = self.throttle if kind == "throttle" else self.clusterthrottle
        state = ks.device_state()
        # the gather path never reads the [P,T] device mask — skip its
        # refresh; only the dense fallback (cols None) pays for it
        pods, mask = ks.device_pods(need_mask=False)
        cols = ks.device_cols()
        if cols is None:
            pods, mask = ks.device_pods(need_mask=True)
        step3 = True if kind == "throttle" else on_equal
        return state, pods, mask, cols, step3, dict(ks.index._pod_rows)

    @staticmethod
    def _dispatch_batch_check(state, pods, mask, cols, on_equal, step3):
        """Gather kernel over [P,K] matched cols when the mask is sparse
        (the normal cluster shape — each pod matches a handful of
        throttles); dense [P,T] broadcast kernel otherwise."""
        if cols is not None:
            return check_pods_gather(
                state, pods, cols, on_equal=on_equal, step3_on_equal=step3
            )
        return check_pods_compact(
            state, pods, mask, on_equal=on_equal, step3_on_equal=step3
        )

    def check_batch(self, kind: str, on_equal: bool = False):
        """All stored pods vs all stored throttles (bench / bulk admission).
        Returns (counts int32[P,4], schedulable bool[P], row→pod-key map).
        Handle grab under the lock; kernel dispatch outside (see check_pod)."""
        with self._lock:
            state, pods, mask, cols, step3, row_map = self._grab_batch_handles(
                kind, on_equal
            )
        counts, schedulable = self._dispatch_batch_check(
            state, pods, mask, cols, on_equal, step3
        )
        return counts, schedulable, row_map

    def full_tick_sharded(self, mesh, on_equal: bool = False, now=None,
                          dense_mesh: bool = False):
        """Both kinds' COMPLETE tick over a ("pods","throttles") device
        Mesh — the multi-chip serving path for bulk triage at cluster
        scale. One shard_map program per kind (parallel/sharded.py)
        resolves time-varying thresholds from the override schedule,
        re-aggregates ``used`` from the live pod set, recomputes the
        throttled flags, and classifies every (pod × throttle) admission
        cell; the only cross-device traffic is two psum all-reduces (used
        partials over the pods axis, verdict counts over the throttles
        axis) — no [P,T] global tensor ever exists on any device.

        Route: whenever the sparse [P,K] cols companion exists it is the
        program on EVERY mesh — single-chip ``full_update_step_gather``,
        multi-chip ``sharded_full_update_gather`` (O(P·K/dp) per-device
        work; cols rebase per throttle tile). The dense [P/dp, T/tp]
        tiled program remains for near-dense masks and under
        ``dense_mesh=True`` (A/B and parity testing).

        Semantics: unlike ``check_batch`` (which classifies against the
        WRITTEN statuses, exactly what the reference's PreFilter reads —
        plugin.go:148-215), the full tick derives used/thresholds/flags
        from one coherent snapshot: the fused reconcile+PreFilter sweep.
        On a static store both agree (tested); under churn the tick is
        ahead of the written statuses by design.

        Returns {kind: (counts int32[P,4], schedulable bool[P], row_map,
        used_cnt int64[T], used_req int64[T,R], col_map)}.
        """
        from datetime import datetime, timezone

        from ..ops.overrides import _datetime_to_ns, encode_override_schedule
        from ..parallel.sharded import (
            full_update_step_gather,
            sharded_full_update,
            sharded_full_update_gather,
        )
        from ..utils.jaxcompat import require_shard_map

        dp, tp = (mesh.shape["pods"], mesh.shape["throttles"])
        single = dp == 1 and tp == 1
        if not single:
            # fail now with a clear env message, not mid-compile inside a
            # cache miss (shard_map's import location drifts across jax
            # versions — utils/jaxcompat.py owns the spelling)
            require_shard_map()
        now_ns = jnp.asarray(
            _datetime_to_ns(now or datetime.now(timezone.utc)), dtype=jnp.int64
        )
        snaps = {}
        with self.tracer.trace("tick_snapshot"), self._lock:
            for kind in ("throttle", "clusterthrottle"):
                ks = self._kind(kind)
                ks.ensure_capacity()
                if ks.pcap % dp or ks.tcap % tp:
                    raise ValueError(
                        f"mesh shape ({dp},{tp}) must divide padded capacities "
                        f"({ks.pcap},{ks.tcap}); capacities are ladder rungs "
                        "(multiples of 8), so use power-of-two mesh axes"
                    )
                # prefer the sparse [P,K] cols companion on EVERY mesh —
                # the tick then needs no [P,T] tensor at all (the dense
                # mask upload alone is ~2.1GB at 100k×10k): 1×1 runs
                # full_update_step_gather, multi-chip the shard_map form
                # (cols rows shard over "pods", global ids rebase per
                # throttle tile). ``dense_mesh`` forces the dense tiled
                # program (A/B + its parity tests); small states whose
                # cols ladder opted out fall back to dense regardless.
                cols = None
                if not dense_mesh:
                    pods, mask = ks.device_pods(need_mask=False)
                    cols = ks.device_cols()
                if cols is None:
                    pods, mask = ks.device_pods()
                specs = [None] * ks.tcap
                for col, thr in ks.index._col_thrs.items():
                    specs[col] = thr.spec
                snaps[kind] = dict(
                    pods=pods,
                    mask=mask,
                    cols=cols,
                    counted=ks._device_counted(),
                    res=(
                        ks.res_cnt.copy(), ks.res_cnt_present.copy(),
                        ks.res_req.copy(), ks.res_req_present.copy(),
                    ),
                    thr_valid=ks.thr_valid.copy(),
                    specs=specs,
                    tcap=ks.tcap,
                    row_map=dict(ks.index._pod_rows),
                    col_map={c: t.key for c, t in ks.index._col_thrs.items()},
                )
        out = {}
        for kind, snap in snaps.items():
            # encode outside the lock: O(T) host work over spec objects
            with self.tracer.trace("tick_encode"):
                max_o = max(
                    (len(s.temporary_threshold_overrides) for s in snap["specs"] if s),
                    default=0,
                )
                sched = encode_override_schedule(
                    snap["specs"],
                    self.dims,
                    throttle_capacity=snap["tcap"],
                    override_capacity=_next_pow2(max_o, lo=1),
                )
            step3 = True if kind == "throttle" else on_equal
            res_cnt, res_cnt_p, res_req, res_req_p = snap["res"]
            with self.tracer.trace("tick_device"):
                if snap["cols"] is not None and single:
                    counts, schedulable, used_cnt, used_req, _, _ = (
                        full_update_step_gather(
                            sched, snap["pods"], snap["cols"], snap["counted"],
                            res_cnt, res_cnt_p, res_req, res_req_p,
                            snap["thr_valid"], now_ns,
                            on_equal=on_equal, step3_on_equal=step3,
                        )
                    )
                elif snap["cols"] is not None:
                    key = (mesh, on_equal, step3, "gather")
                    step = self._sharded_steps.get(key)
                    if step is None:
                        step = self._sharded_steps[key] = sharded_full_update_gather(
                            mesh, on_equal=on_equal, step3_on_equal=step3
                        )
                    counts, schedulable, used_cnt, used_req, _, _ = step(
                        sched, snap["pods"], snap["cols"], snap["counted"],
                        res_cnt, res_cnt_p, res_req, res_req_p,
                        snap["thr_valid"], now_ns,
                    )
                else:
                    key = (mesh, on_equal, step3)
                    step = self._sharded_steps.get(key)
                    if step is None:
                        step = self._sharded_steps[key] = sharded_full_update(
                            mesh, on_equal=on_equal, step3_on_equal=step3
                        )
                    counts, schedulable, used_cnt, used_req, _, _ = step(
                        sched, snap["pods"], snap["mask"], snap["counted"],
                        res_cnt, res_cnt_p, res_req, res_req_p,
                        snap["thr_valid"], now_ns,
                    )
                out[kind] = (
                    np.asarray(counts), np.asarray(schedulable), snap["row_map"],
                    np.asarray(used_cnt), np.asarray(used_req), snap["col_map"],
                )
        return out

    def check_batch_all(self, on_equal: bool = False):
        """Both kinds' batch checks against ONE coherent device snapshot:
        a single lock hold grabs both kinds' handles, so the composed
        verdict corresponds to one point in the event stream (previously
        pre_filter_batch composed two separately-locked snapshots — a
        concurrent store event between them could yield a verdict matching
        no single point in time). Returns {kind: (counts, schedulable,
        row_map)}."""
        with self._lock:
            handles = {
                kind: self._grab_batch_handles(kind, on_equal)
                for kind in ("throttle", "clusterthrottle")
            }
        out = {}
        for kind, (state, pods, mask, cols, step3, row_map) in handles.items():
            counts, schedulable = self._dispatch_batch_check(
                state, pods, mask, cols, on_equal, step3
            )
            out[kind] = (counts, schedulable, row_map)
        return out
