"""The scheduler-cycle reservation ledger.

Mirrors reserved_resource_amounts.go:32-156: throttle-key → (pod-key →
ResourceAmount), guarded by a global RW lock plus hashed per-throttle-key
locks (keymutex.NewHashed(n)); add is idempotent-overwrite, remove returns
whether the pod was present, and assignment moves are remove+add over the
symmetric difference (moveThrottleAssignmentForPods,
reserved_resource_amounts.go:92-111).

A reservation exists only between the scheduler's Reserve call and the first
reconcile that observes the pod counted in status.used (or pod deletion /
Unreserve) — the reserve-until-observed handshake (SURVEY §3.3).

**TTL deadlines.** ``add_pod(..., ttl=...)`` attaches an expiry deadline
(injectable clock): a reservation whose scheduler died mid-cycle must not
pin capacity forever. Expired entries are invisible to every read and are
purged lazily under the same locks the reads already hold. Deadlines are
snapshot/restore-aware (engine/snapshot.py / engine/recovery.py):
``snapshot_state`` serializes REMAINING seconds, and ``restore_state``
rebases them against the restoring process's clock — so a deadline can
never resurrect an already-expired reservation just because wall time
moved while the process was dead, and a frozen test clock restores exact
remaining budgets.
"""

from __future__ import annotations

from datetime import datetime, timedelta
from typing import Dict, Iterable, List, Optional, Set, Tuple, Union

from ..api.pod import Pod
from ..api.types import ResourceAmount, resource_amount_of_pod
from ..utils.clock import Clock, RealClock
from ..utils.lockorder import make_rlock
from ..utils.tracing import vlog

TTL = Union[None, float, int, timedelta]


def _ttl_seconds(ttl: TTL) -> Optional[float]:
    if ttl is None:
        return None
    if isinstance(ttl, timedelta):
        return ttl.total_seconds()
    return float(ttl)


class ReservedResourceAmounts:
    # the top-level cache/deadline maps are guarded by the global lock; the
    # per-key pod maps inside them are guarded by the hashed key locks
    # (lock order: key lock -> global lock, never the reverse)
    GUARDED_BY = {"_cache": "self._lock", "_deadlines": "self._lock"}

    def __init__(self, num_key_mutex: int = 128, clock: Optional[Clock] = None):
        self._lock = make_rlock("reservations.global")
        # hashed per-throttle-key mutexes share one name: distinct slots
        # are never nested (one hash bucket per operation), so a shared
        # name loses no order information
        self._key_locks = [
            make_rlock("reservations.key") for _ in range(max(1, num_key_mutex))
        ]
        self._clock = clock or RealClock()
        # throttle key -> pod key -> amount
        self._cache: Dict[str, Dict[str, ResourceAmount]] = {}
        # throttle key -> pod key -> expiry deadline (only TTL'd entries)
        self._deadlines: Dict[str, Dict[str, datetime]] = {}
        # reservations dropped by TTL expiry (single-writer-ish counter,
        # read by tests/probes)
        self.expired_total = 0

    def _key_lock(self, key: str):
        return self._key_locks[hash(key) % len(self._key_locks)]

    def _pod_map(self, throttle_key: str) -> Dict[str, ResourceAmount]:
        with self._lock:
            return self._cache.setdefault(throttle_key, {})

    def _deadline_map(self, throttle_key: str) -> Dict[str, datetime]:
        with self._lock:
            return self._deadlines.setdefault(throttle_key, {})

    def _purge_expired(self, throttle_key: str, now: datetime) -> None:
        """Drop expired entries for one throttle key. Caller holds that
        key's hashed lock (the pod/deadline inner maps move under it)."""
        dm = self._deadline_map(throttle_key)
        if not dm:
            return
        expired = [pk for pk, deadline in dm.items() if deadline <= now]
        if not expired:
            return
        m = self._pod_map(throttle_key)
        for pk in expired:
            dm.pop(pk, None)
            if m.pop(pk, None) is not None:
                self.expired_total += 1
                vlog(4, "reservation expired: pod=%s throttle=%s", pk, throttle_key)

    def add_pod(self, throttle_key: str, pod: Pod, ttl: TTL = None) -> bool:
        vlog(5, "reservation add: pod=%s throttle=%s", pod.key, throttle_key)
        """Overwrite-insert; True if the pod was newly reserved. ``ttl``
        (seconds or timedelta) attaches an expiry deadline; None keeps the
        reference's reserve-until-observed lifetime."""
        ttl_s = _ttl_seconds(ttl)
        now = self._clock.now()
        with self._key_lock(throttle_key):
            self._purge_expired(throttle_key, now)
            m = self._pod_map(throttle_key)
            dm = self._deadline_map(throttle_key)
            existed = pod.key in m
            m[pod.key] = resource_amount_of_pod(pod)
            if ttl_s is not None:
                dm[pod.key] = now + timedelta(seconds=ttl_s)
            else:
                dm.pop(pod.key, None)
            return not existed

    def remove_pod(self, throttle_key: str, pod: Pod) -> bool:
        vlog(5, "reservation remove: pod=%s throttle=%s", pod.key, throttle_key)
        return self.remove_pod_key(throttle_key, pod.key)

    def remove_pod_key(self, throttle_key: str, pod_key: str) -> bool:
        with self._key_lock(throttle_key):
            m = self._pod_map(throttle_key)
            self._deadline_map(throttle_key).pop(pod_key, None)
            return m.pop(pod_key, None) is not None

    def move_throttle_assignment(
        self, pod: Pod, from_keys: Iterable[str], to_keys: Iterable[str]
    ) -> None:
        """reserved_resource_amounts.go:92-111."""
        for key in from_keys:
            self.remove_pod(key, pod)
        for key in to_keys:
            self.add_pod(key, pod)

    def reserved_resource_amount(self, throttle_key: str) -> Tuple[ResourceAmount, Set[str]]:
        """Sum of reserved amounts + reserved pod keys for one throttle
        (expired entries purged first — they must never count toward
        ``reserved`` in the admission inequality)."""
        now = self._clock.now()
        with self._key_lock(throttle_key):
            self._purge_expired(throttle_key, now)
            with self._lock:
                m = self._cache.get(throttle_key)
                entries = list(m.items()) if m else []
        result = ResourceAmount()
        pod_keys: Set[str] = set()
        for pod_key, amount in entries:
            pod_keys.add(pod_key)
            result = result.add(amount)
        return result, pod_keys

    def reserved_pod_keys(self, throttle_key: str) -> Set[str]:
        now = self._clock.now()
        with self._lock:
            m = self._cache.get(throttle_key)
            if not m:
                return set()
            dm = self._deadlines.get(throttle_key) or {}
            # filter without purging: this read holds only the global lock,
            # and the inner maps move under the hashed key locks
            return {pk for pk in m if not (pk in dm and dm[pk] <= now)}

    def throttle_keys(self) -> Set[str]:
        with self._lock:
            return set(self._cache.keys())

    # -- snapshot / restore (engine/snapshot.py, engine/recovery.py) --------

    def snapshot_state(self, now: Optional[datetime] = None) -> Dict[str, dict]:
        """Serializable ledger state: ``{throttle_key: {pod_key: {"amount":
        <ResourceAmount dict>, "ttlRemainingSeconds": float | None}}}``.
        TTLs are stored as remaining budget relative to ``now`` so the
        restoring process can rebase them on ITS clock; already-expired
        entries are omitted (a snapshot must never carry a dead
        reservation)."""
        now = now or self._clock.now()
        with self._lock:
            throttle_keys = list(self._cache.keys())
        out: Dict[str, dict] = {}
        for tk in throttle_keys:
            with self._key_lock(tk):
                with self._lock:
                    m = dict(self._cache.get(tk) or {})
                    dm = dict(self._deadlines.get(tk) or {})
            entries = {}
            for pk, amount in m.items():
                deadline = dm.get(pk)
                if deadline is not None and deadline <= now:
                    continue
                entries[pk] = {
                    "amount": amount.to_dict(),
                    "ttlRemainingSeconds": (
                        (deadline - now).total_seconds()
                        if deadline is not None
                        else None
                    ),
                }
            if entries:
                out[tk] = entries
        return out

    def restore_state(
        self,
        state: Dict[str, dict],
        now: Optional[datetime] = None,
        elapsed_s: float = 0.0,
    ) -> Tuple[int, int, List[str]]:
        """Merge a ``snapshot_state`` payload into this ledger. Each
        remaining TTL is first charged ``elapsed_s`` — the wall time
        between the snapshot cut and this restore (the process was dead;
        the scheduler that held the reservation certainly is) — then
        REBASED onto ``now`` (this process's clock, so clock skew between
        runs can never extend a deadline). Entries whose charged budget is
        <= 0 are DROPPED, never resurrected. Returns ``(restored,
        dropped_expired, touched_throttle_keys)`` — the caller replays
        touched keys into the device mirror."""
        from ..api.serialization import resource_amount_from_dict

        now = now or self._clock.now()
        elapsed_s = max(0.0, float(elapsed_s))
        restored = dropped = 0
        touched: List[str] = []
        for tk, pods in (state or {}).items():
            wrote = False
            with self._key_lock(tk):
                m = self._pod_map(tk)
                dm = self._deadline_map(tk)
                for pk, entry in pods.items():
                    remaining = entry.get("ttlRemainingSeconds")
                    if remaining is not None:
                        remaining = float(remaining) - elapsed_s
                        if remaining <= 0.0:
                            dropped += 1
                            continue
                    m[pk] = resource_amount_from_dict(entry.get("amount"))
                    if remaining is not None:
                        dm[pk] = now + timedelta(seconds=remaining)
                    else:
                        dm.pop(pk, None)
                    restored += 1
                    wrote = True
            if wrote:
                touched.append(tk)
        return restored, dropped, touched
