"""The scheduler-cycle reservation ledger.

Mirrors reserved_resource_amounts.go:32-156: throttle-key → (pod-key →
ResourceAmount), guarded by a global RW lock plus hashed per-throttle-key
locks (keymutex.NewHashed(n)); add is idempotent-overwrite, remove returns
whether the pod was present, and assignment moves are remove+add over the
symmetric difference (moveThrottleAssignmentForPods,
reserved_resource_amounts.go:92-111).

A reservation exists only between the scheduler's Reserve call and the first
reconcile that observes the pod counted in status.used (or pod deletion /
Unreserve) — the reserve-until-observed handshake (SURVEY §3.3).
"""

from __future__ import annotations

from ..utils.lockorder import make_rlock
from ..utils.tracing import vlog
from typing import Dict, Iterable, Optional, Set, Tuple

from ..api.pod import Pod
from ..api.types import ResourceAmount, resource_amount_of_pod


class ReservedResourceAmounts:
    # the top-level cache map is guarded by the global lock; the per-key
    # pod maps inside it are guarded by the hashed key locks (lock order:
    # key lock -> global lock, never the reverse)
    GUARDED_BY = {"_cache": "self._lock"}

    def __init__(self, num_key_mutex: int = 128):
        self._lock = make_rlock("reservations.global")
        # hashed per-throttle-key mutexes share one name: distinct slots
        # are never nested (one hash bucket per operation), so a shared
        # name loses no order information
        self._key_locks = [
            make_rlock("reservations.key") for _ in range(max(1, num_key_mutex))
        ]
        # throttle key -> pod key -> amount
        self._cache: Dict[str, Dict[str, ResourceAmount]] = {}

    def _key_lock(self, key: str):
        return self._key_locks[hash(key) % len(self._key_locks)]

    def _pod_map(self, throttle_key: str) -> Dict[str, ResourceAmount]:
        with self._lock:
            return self._cache.setdefault(throttle_key, {})

    def add_pod(self, throttle_key: str, pod: Pod) -> bool:
        vlog(5, "reservation add: pod=%s throttle=%s", pod.key, throttle_key)
        """Overwrite-insert; True if the pod was newly reserved."""
        with self._key_lock(throttle_key):
            m = self._pod_map(throttle_key)
            existed = pod.key in m
            m[pod.key] = resource_amount_of_pod(pod)
            return not existed

    def remove_pod(self, throttle_key: str, pod: Pod) -> bool:
        vlog(5, "reservation remove: pod=%s throttle=%s", pod.key, throttle_key)
        return self.remove_pod_key(throttle_key, pod.key)

    def remove_pod_key(self, throttle_key: str, pod_key: str) -> bool:
        with self._key_lock(throttle_key):
            m = self._pod_map(throttle_key)
            return m.pop(pod_key, None) is not None

    def move_throttle_assignment(
        self, pod: Pod, from_keys: Iterable[str], to_keys: Iterable[str]
    ) -> None:
        """reserved_resource_amounts.go:92-111."""
        for key in from_keys:
            self.remove_pod(key, pod)
        for key in to_keys:
            self.add_pod(key, pod)

    def reserved_resource_amount(self, throttle_key: str) -> Tuple[ResourceAmount, Set[str]]:
        """Sum of reserved amounts + reserved pod keys for one throttle."""
        with self._key_lock(throttle_key):
            with self._lock:
                m = self._cache.get(throttle_key)
                entries = list(m.items()) if m else []
        result = ResourceAmount()
        pod_keys: Set[str] = set()
        for pod_key, amount in entries:
            pod_keys.add(pod_key)
            result = result.add(amount)
        return result, pod_keys

    def reserved_pod_keys(self, throttle_key: str) -> Set[str]:
        with self._lock:
            m = self._cache.get(throttle_key)
            return set(m.keys()) if m else set()

    def throttle_keys(self) -> Set[str]:
        with self._lock:
            return set(self._cache.keys())
