"""client-go-style rate-limiting workqueue.

Reproduces the semantics the controllers depend on
(controller.go:34-122 uses workqueue.NewNamedRateLimitingQueue with the
DefaultControllerRateLimiter):

- **dedup**: an item Add()ed while queued is not duplicated; an item Add()ed
  while *processing* is marked dirty and re-queued when Done() is called —
  so a reconcile never misses the latest state and never runs concurrently
  for the same key;
- **AddAfter**: delayed insertion (override-boundary self-wakeups,
  controller.go:64-72);
- **AddRateLimited / Forget**: per-item exponential backoff
  (5ms · 2^fails, capped at 1000s — client-go's ItemExponentialFailureRateLimiter
  defaults) reset by Forget on success.

Two extensions client-go does not have, both serving the two-lane status
pipeline (flip-first publication):

- **ordered priority lane** (``add_priority`` / ``add_all_priority``): a
  second lane drained before the normal one, ordered by **(priority desc,
  age)** — a heap of ``(-priority, enqueue seq, item)``. Without explicit
  priorities every item enters at priority 0 and the lane degenerates to
  the original FIFO (age order), so the flip-first pipeline is unchanged;
  WITH priorities (``add_all_priority(items, priorities={item: int})``)
  candidates drain highest-priority-first, ties oldest-first — the
  preemption-ordered admission lane (docs/gang_admission.md): when
  capacity opens, flip candidates no longer drain in arbitrary key order.
  Promoting an item already queued normal MOVES it (an item is only ever
  queued once — dedup is lane-global); promoting an item in processing
  re-queues it into the priority lane at Done() with its recorded
  priority. Promoting an item ALREADY in the hi lane at a different
  priority re-orders it in place (lazy heap supersede) — a pod/group
  priority-annotation update reorders already-queued work instead of
  riding the stale enqueue-time priority. Used for throttles whose ``status.throttled`` flag is about
  to flip: they overtake the value-only refresh backlog, which at full
  scale is the difference between ~100ms and multi-second flip
  publication.
- **enqueue timestamps** (``claim_ts``): the wall (monotonic) time of the
  FIRST add since the item was last handed out, claimed by the consumer at
  commit time — the "event" end of the event→publication lag histograms.

The delay waker sleeps on a condition variable until the EARLIEST delayed
deadline (no unconditional polling — an idle daemon makes zero wakeups);
``add_after`` re-arms it, and a FakeClock jump notifies it via the clock's
subscribe hook, keeping FakeClock tests deterministic.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from datetime import timedelta
from typing import Dict, List, Optional, Set, Tuple

from ..utils.clock import Clock, RealClock
from ..utils.lockorder import assert_held, guard_attrs, make_rlock

logger = logging.getLogger(__name__)

_BASE_DELAY = 0.005  # 5ms
_MAX_DELAY = 1000.0  # 1000s


class ShutDown(Exception):
    pass


@guard_attrs
class RateLimitingQueue:
    # every queue structure below moves only under the single shared lock
    # (held directly or via either condition); see docs/STATIC_ANALYSIS.md
    GUARDED_BY = {
        "_queue": "self._lock",
        "_queue_hi": "self._lock",
        "_hi": "self._lock",
        "_hi_pending": "self._lock",
        "_dirty": "self._lock",
        "_processing": "self._lock",
        "_failures": "self._lock",
        "_enqueue_ts": "self._lock",
        "_claim_ts": "self._lock",
        "_delayed": "self._lock",
        "_seq": "self._lock",
        "_shutdown": "self._lock",
    }

    def __init__(self, name: str = "", clock: Optional[Clock] = None):
        self.name = name
        self._clock = clock or RealClock()
        # consumers (get) and the delay waker wait on separate conditions
        # over ONE shared lock, so add()/done() can notify exactly one
        # consumer without waking (or losing the wakeup to) the waker
        self._lock = make_rlock(f"workqueue.{name or 'unnamed'}")
        self._cond = threading.Condition(self._lock)
        self._waker_cond = threading.Condition(self._lock)
        self._queue: List[str] = []  # FIFO of ready items (normal lane)
        # priority lane, drained first: heap of (-priority, seq, item) —
        # highest priority first, ties in enqueue (age) order
        self._queue_hi: List[Tuple[int, int, str]] = []
        # members of _queue_hi: item → its LIVE heap entry (-priority,
        # seq). Re-prioritizing a queued item pushes a fresh entry and
        # rebinds the mapping; the superseded heap entry is skipped lazily
        # at pop (it no longer matches). Without this, hi-lane priority
        # was pinned at enqueue time — a pod/group priority-annotation
        # update could not reorder already-queued work.
        self._hi: Dict[str, Tuple[int, int]] = {}
        # promoted while processing: done() re-queues into the hi lane at
        # the recorded priority (item → priority)
        self._hi_pending: Dict[str, int] = {}
        self._dirty: Set[str] = set()
        self._processing: Set[str] = set()
        self._failures: Dict[str, int] = {}
        # item → monotonic time of the first add since it was last handed
        # out (get/try_get move it to _claim_ts; claim_ts pops it)
        self._enqueue_ts: Dict[str, float] = {}
        self._claim_ts: Dict[str, float] = {}
        self._delayed: List[Tuple[float, int, str]] = []  # (ready_ts, seq, item)
        self._seq = 0
        self._shutdown = False
        self._clock.subscribe(self._on_clock_jump)
        self._waker = threading.Thread(target=self._delay_loop, daemon=True)
        self._waker.start()

    def _on_clock_jump(self) -> None:
        with self._lock:
            self._cond.notify_all()
            self._waker_cond.notify_all()

    # -- core queue semantics (client-go workqueue/queue.go) ---------------

    def add(self, item: str) -> None:
        with self._cond:
            if self._shutdown:
                return
            if item in self._dirty:
                return
            self._dirty.add(item)
            self._enqueue_ts.setdefault(item, time.monotonic())
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    def add_all(self, items) -> None:
        """Batch add under ONE lock hold: a pod event at full scale
        enqueues 20+ affected throttle keys — per-key lock round trips
        were ~10% of event-ingest cost."""
        with self._cond:
            if self._shutdown:
                return
            added = False
            now = time.monotonic()
            for item in items:
                if item in self._dirty:
                    continue
                self._dirty.add(item)
                self._enqueue_ts.setdefault(item, now)
                if item in self._processing:
                    continue  # re-queued by done()
                self._queue.append(item)
                added = True
            if added:
                self._cond.notify()

    def add_priority(self, item: str, priority: int = 0) -> None:
        self.add_all_priority((item,), priorities={item: priority} if priority else None)

    def _push_hi_locked(self, item: str, priority: int) -> None:
        assert_held(self._lock, "RateLimitingQueue._push_hi_locked")
        self._seq += 1
        entry = (-int(priority), self._seq)
        heapq.heappush(self._queue_hi, (entry[0], entry[1], item))
        self._hi[item] = entry

    def add_all_priority(self, items, priorities: Optional[Dict[str, int]] = None) -> None:
        """Add/promote items into the ordered priority lane (one lock
        hold). ``priorities`` (item → int, default 0) orders the drain
        (priority desc, age); omitted, the lane is the original FIFO. An
        item already queued normal MOVES — the single-queued-once dedup
        invariant is lane-global, which is also what makes per-key
        ordering trivial (an item is never drained twice for one add). An
        item in processing is re-queued into the hi lane by done()."""
        with self._cond:
            if self._shutdown:
                return
            move: Set[str] = set()
            added = False
            now = time.monotonic()
            for item in items:
                prio = int(priorities.get(item, 0)) if priorities else 0
                if item in self._hi:
                    if self._hi[item][0] == -prio:
                        continue  # already queued at this priority
                    # RE-prioritize in place: a priority-annotation update
                    # must reorder already-queued work, not ride the stale
                    # enqueue-time priority. Push a fresh entry (rebinding
                    # _hi); the superseded heap entry is skipped at pop.
                    self._push_hi_locked(item, prio)
                    added = True
                    continue
                if item in self._dirty:
                    if item in self._processing:
                        self._hi_pending[item] = prio
                        continue
                    move.add(item)  # queued normal: relocate below
                else:
                    self._dirty.add(item)
                    self._enqueue_ts.setdefault(item, now)
                    if item in self._processing:
                        self._hi_pending[item] = prio
                        continue
                self._push_hi_locked(item, prio)
                added = True
            if move:
                # one filter pass relocates every promoted normal-lane item
                self._queue = [i for i in self._queue if i not in move]
            if added:
                self._cond.notify()

    def _pop_ready_locked(self, hi_only: bool = False) -> Optional[Tuple[str, bool]]:
        """Caller holds the lock (the `_locked` contract — asserted under
        KT_LOCK_ASSERT=1). Priority lane first; ``hi_only`` refuses to touch
        the normal lane (the flip express drain). Returns (item, was_hi)."""
        assert_held(self._lock, "RateLimitingQueue._pop_ready_locked")
        item = None
        while self._queue_hi:
            negp, seq, cand = heapq.heappop(self._queue_hi)
            if self._hi.get(cand) != (negp, seq):
                continue  # superseded by a re-prioritize: skip the stale entry
            del self._hi[cand]
            item = cand
            break
        if item is not None:
            was_hi = True
        elif self._queue and not hi_only:
            item = self._queue.pop(0)
            was_hi = False
        else:
            return None
        self._processing.add(item)
        self._dirty.discard(item)
        ts = self._enqueue_ts.pop(item, None)
        if ts is not None:
            self._claim_ts[item] = ts
        return item, was_hi

    def get(self, timeout: Optional[float] = None) -> str:
        """Blocks until an item is available. Raises ShutDown."""
        return self.get_lane(timeout)[0]

    def get_lane(self, timeout: Optional[float] = None) -> Tuple[str, bool]:
        """``get`` plus which lane the item came from (True = priority) —
        the workers use the lane to shape the drain (a priority first-key
        triggers the flip express drain, controllers/base._drain_more)."""
        with self._cond:
            # _hi, not _queue_hi: the heap may hold only superseded
            # (re-prioritized) entries, which pop as nothing
            while not (self._queue or self._hi) and not self._shutdown:
                # untimed callers still wake on every add/done/shutdown
                # notify; the 1s re-check is only a lost-wakeup safety net
                if not self._cond.wait(timeout=timeout if timeout is not None else 1.0):
                    if timeout is not None:
                        raise TimeoutError
            if self._shutdown and not (self._queue or self._hi):
                raise ShutDown
            return self._pop_ready_locked()

    def try_get(self, hi_only: bool = False) -> Optional[str]:
        """Non-blocking get: an immediately-ready item or None (batch
        drain). ``hi_only`` drains the priority lane exclusively."""
        with self._cond:
            popped = self._pop_ready_locked(hi_only=hi_only)
            return popped[0] if popped is not None else None

    def claim_ts(self, item: str) -> Optional[float]:
        """Monotonic time of the first add that made the in-flight ``item``
        dirty (pops it — one lag sample per hand-out). The consumer calls
        this at commit time to observe event→publication lag."""
        with self._cond:
            return self._claim_ts.pop(item, None)

    def done(self, item: str) -> None:
        with self._cond:
            self._processing.discard(item)
            self._claim_ts.pop(item, None)  # unclaimed: drop, don't leak
            if item in self._dirty:
                if item in self._hi_pending:
                    self._push_hi_locked(item, self._hi_pending.pop(item))
                else:
                    self._queue.append(item)
                self._cond.notify()
            else:
                self._hi_pending.pop(item, None)

    # -- delay / rate limiting --------------------------------------------

    def add_after(self, item: str, delay: timedelta) -> None:
        secs = delay.total_seconds()
        if secs <= 0:
            self.add(item)
            return
        ready = self._now_ts() + secs
        with self._lock:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (ready, self._seq, item))
            self._waker_cond.notify_all()  # new earliest deadline, re-arm

    def add_rate_limited(self, item: str) -> None:
        with self._cond:
            fails = self._failures.get(item, 0)
            self._failures[item] = fails + 1
        delay = min(_BASE_DELAY * (2**fails), _MAX_DELAY)
        self.add_after(item, timedelta(seconds=delay))

    def forget(self, item: str) -> None:
        with self._cond:
            self._failures.pop(item, None)

    def num_requeues(self, item: str) -> int:
        with self._cond:
            return self._failures.get(item, 0)

    # -- lifecycle ---------------------------------------------------------

    def shut_down(self) -> None:
        with self._lock:
            self._shutdown = True
            self._cond.notify_all()
            self._waker_cond.notify_all()
        # a shut-down queue must not stay referenced by a long-lived clock
        self._clock.unsubscribe(self._on_clock_jump)

    def __len__(self) -> int:
        with self._cond:
            # _hi, not _queue_hi: the heap may carry superseded
            # (re-prioritized) entries that no longer represent items
            return len(self._queue) + len(self._hi)

    # -- internals ---------------------------------------------------------

    def _now_ts(self) -> float:
        return self._clock.now().timestamp()

    def _delay_loop(self) -> None:
        """Move due delayed items onto the ready queue, sleeping until the
        earliest deadline (condition wait, not a poll): zero wakeups while
        idle. A FakeClock advance notifies via _on_clock_jump; add_after
        notifies when a new item becomes the earliest."""
        with self._waker_cond:
            while True:
                # loop-level routing (threads checker): a dead waker means
                # delayed retries are never delivered again — silently
                try:
                    if self._shutdown:
                        return
                    now = self._now_ts()
                    while self._delayed and self._delayed[0][0] <= now:
                        _, _, item = heapq.heappop(self._delayed)
                        if item not in self._dirty:
                            self._dirty.add(item)
                            self._enqueue_ts.setdefault(item, time.monotonic())
                            if item not in self._processing:
                                self._queue.append(item)
                                self._cond.notify()
                    timeout = self._delayed[0][0] - now if self._delayed else None
                    self._waker_cond.wait(timeout=timeout)
                except Exception:  # noqa: BLE001 — keep the waker alive
                    logger.exception("delay-queue waker error")
                    self._waker_cond.wait(timeout=0.1)
