"""client-go-style rate-limiting workqueue.

Reproduces the semantics the controllers depend on
(controller.go:34-122 uses workqueue.NewNamedRateLimitingQueue with the
DefaultControllerRateLimiter):

- **dedup**: an item Add()ed while queued is not duplicated; an item Add()ed
  while *processing* is marked dirty and re-queued when Done() is called —
  so a reconcile never misses the latest state and never runs concurrently
  for the same key;
- **AddAfter**: delayed insertion (override-boundary self-wakeups,
  controller.go:64-72);
- **AddRateLimited / Forget**: per-item exponential backoff
  (5ms · 2^fails, capped at 1000s — client-go's ItemExponentialFailureRateLimiter
  defaults) reset by Forget on success.

The delay waker sleeps on a condition variable until the EARLIEST delayed
deadline (no unconditional polling — an idle daemon makes zero wakeups);
``add_after`` re-arms it, and a FakeClock jump notifies it via the clock's
subscribe hook, keeping FakeClock tests deterministic.
"""

from __future__ import annotations

import heapq
import threading
from datetime import timedelta
from typing import Dict, List, Optional, Set, Tuple

from ..utils.clock import Clock, RealClock

_BASE_DELAY = 0.005  # 5ms
_MAX_DELAY = 1000.0  # 1000s


class ShutDown(Exception):
    pass


class RateLimitingQueue:
    def __init__(self, name: str = "", clock: Optional[Clock] = None):
        self.name = name
        self._clock = clock or RealClock()
        # consumers (get) and the delay waker wait on separate conditions
        # over ONE shared lock, so add()/done() can notify exactly one
        # consumer without waking (or losing the wakeup to) the waker
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._waker_cond = threading.Condition(self._lock)
        self._queue: List[str] = []  # FIFO of ready items
        self._dirty: Set[str] = set()
        self._processing: Set[str] = set()
        self._failures: Dict[str, int] = {}
        self._delayed: List[Tuple[float, int, str]] = []  # (ready_ts, seq, item)
        self._seq = 0
        self._shutdown = False
        self._clock.subscribe(self._on_clock_jump)
        self._waker = threading.Thread(target=self._delay_loop, daemon=True)
        self._waker.start()

    def _on_clock_jump(self) -> None:
        with self._lock:
            self._cond.notify_all()
            self._waker_cond.notify_all()

    # -- core queue semantics (client-go workqueue/queue.go) ---------------

    def add(self, item: str) -> None:
        with self._cond:
            if self._shutdown:
                return
            if item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    def add_all(self, items) -> None:
        """Batch add under ONE lock hold: a pod event at full scale
        enqueues 20+ affected throttle keys — per-key lock round trips
        were ~10% of event-ingest cost."""
        with self._cond:
            if self._shutdown:
                return
            added = False
            for item in items:
                if item in self._dirty:
                    continue
                self._dirty.add(item)
                if item in self._processing:
                    continue  # re-queued by done()
                self._queue.append(item)
                added = True
            if added:
                self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> str:
        """Blocks until an item is available. Raises ShutDown."""
        with self._cond:
            while not self._queue and not self._shutdown:
                # untimed callers still wake on every add/done/shutdown
                # notify; the 1s re-check is only a lost-wakeup safety net
                if not self._cond.wait(timeout=timeout if timeout is not None else 1.0):
                    if timeout is not None:
                        raise TimeoutError
            if self._shutdown and not self._queue:
                raise ShutDown
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def try_get(self) -> Optional[str]:
        """Non-blocking get: an immediately-ready item or None (batch drain)."""
        with self._cond:
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: str) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    # -- delay / rate limiting --------------------------------------------

    def add_after(self, item: str, delay: timedelta) -> None:
        secs = delay.total_seconds()
        if secs <= 0:
            self.add(item)
            return
        ready = self._now_ts() + secs
        with self._lock:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (ready, self._seq, item))
            self._waker_cond.notify_all()  # new earliest deadline, re-arm

    def add_rate_limited(self, item: str) -> None:
        with self._cond:
            fails = self._failures.get(item, 0)
            self._failures[item] = fails + 1
        delay = min(_BASE_DELAY * (2**fails), _MAX_DELAY)
        self.add_after(item, timedelta(seconds=delay))

    def forget(self, item: str) -> None:
        with self._cond:
            self._failures.pop(item, None)

    def num_requeues(self, item: str) -> int:
        with self._cond:
            return self._failures.get(item, 0)

    # -- lifecycle ---------------------------------------------------------

    def shut_down(self) -> None:
        with self._lock:
            self._shutdown = True
            self._cond.notify_all()
            self._waker_cond.notify_all()
        # a shut-down queue must not stay referenced by a long-lived clock
        self._clock.unsubscribe(self._on_clock_jump)

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    # -- internals ---------------------------------------------------------

    def _now_ts(self) -> float:
        return self._clock.now().timestamp()

    def _delay_loop(self) -> None:
        """Move due delayed items onto the ready queue, sleeping until the
        earliest deadline (condition wait, not a poll): zero wakeups while
        idle. A FakeClock advance notifies via _on_clock_jump; add_after
        notifies when a new item becomes the earliest."""
        with self._waker_cond:
            while True:
                if self._shutdown:
                    return
                now = self._now_ts()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, item = heapq.heappop(self._delayed)
                    if item not in self._dirty:
                        self._dirty.add(item)
                        if item not in self._processing:
                            self._queue.append(item)
                            self._cond.notify()
                timeout = self._delayed[0][0] - now if self._delayed else None
                self._waker_cond.wait(timeout=timeout)
