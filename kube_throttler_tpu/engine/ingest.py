"""Micro-batched event ingest: the adaptive group-apply front-end.

PR 2's full-scale capacity window measured the engine saturating at ~1.4k
sustained events/s while the fired rate was higher — the watch→store→index→
device ingest path, not the kernels, had become the ceiling, because every
event paid its own store-lock acquisition (against reconcile drains holding
the lock for whole batched status writes), its own journal write+flush
syscall pair, and its own per-event Python dispatch overhead.

This module amortizes all of that with a classic group-commit shape:

- producers (the watch/reflector layer, the bench's churn driver, any
  embedder) ``submit()`` ops into a BOUNDED queue and return immediately —
  they never touch the store lock;
- one dispatcher thread drains the queue into a micro-batch and applies it
  via :meth:`Store.apply_events` — ONE store-lock acquisition, ONE journal
  group commit, ONE device-mirror pass, ONE controller enqueue per batch;
- the batch size is ADAPTIVE: it grows (×2 up to ``max_batch``) while a
  backlog remains after a drain and collapses back toward 1 when the queue
  runs dry — so an UNLOADED pipeline applies single events on the exact
  pre-batching path (no added latency), and a loaded one pays the per-event
  overhead 1/N times.

Overflow policy mirrors the bounded Watch queues (client/watch.py):
``drop-oldest`` — the producer never blocks, the newest events win, and
``dropped``/``overflowed`` record the gap PER EVENT so a consumer knows to
relist. (Counting per batch would under-report the gap by the batch size —
the exact single-event assumption this subsystem must not reintroduce.)

Overload posture (the scenario corpus' storm gates): shedding is
VERDICT-SAFE. Only pod upserts — status-lag refreshes the resync loop
regenerates — are eligible; Throttle/ClusterThrottle/Namespace ops and
every DELETE are verdict-critical (a shed throttle spec or pod delete
changes admission answers until a relist nobody scheduled), so they are
never dropped: the queue prefers shedding the oldest sheddable op, drops a
sheddable *incoming* op when nothing queued is sheddable, and briefly
exceeds the bound rather than shed a critical op. Every shed is recorded
per kind; ``take_overflow(kind)`` hands the gap to that kind's reflector,
which forces a relist — the overflow flag is now a repair trigger, not a
note. Under overload the pipeline therefore sheds non-flip status
freshness, never verdict correctness.

Fault site ``ingest.batch.partial`` (faults/plan.py): a firing makes one op
of the current batch fail mid-apply; the dispatcher splits around it — the
ops before AND after still land, the failure is counted in ``op_errors``
and surfaced per op — so a poisoned event can never wedge or tear the
batch.

Equivalence contract (property-tested in tests/test_batch_ingest.py): for
any partition of an op stream into micro-batches, the final store dump,
the published ``st_*`` device planes, and every ``pre_filter`` verdict are
identical to one-at-a-time ingest.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import List, Optional, Sequence, Tuple

from ..utils.lockorder import guard_attrs, make_lock
from .store import Store

logger = logging.getLogger(__name__)

# (verb, kind, payload) — the Store.apply_events op shape
IngestOp = Tuple[str, str, object]


@guard_attrs
class MicroBatchIngest:
    """Adaptive micro-batching front-end over :meth:`Store.apply_events`.

    ``batch_policy``: ``"adaptive"`` (default — grow under backlog, decay
    to 1 when idle) or a fixed positive int (every drain takes up to that
    many ops; the bench's fixed rungs). ``max_batch`` caps the adaptive
    growth. ``maxsize`` bounds the queue (drop-oldest on overflow).
    """

    DEFAULT_MAXSIZE = 65536

    # the queue, its counters, and the adaptive batch size only move under
    # the single pipeline lock (held directly or via the condition)
    GUARDED_BY = {
        "_queue": "self._lock",
        "_cur_max": "self._lock",
        "_applying": "self._lock",
        "_stopped": "self._lock",
        "dropped": "self._lock",
        "overflowed": "self._lock",
        "events_in": "self._lock",
        "_overflow_kinds": "self._lock",
    }

    def __init__(
        self,
        store: Store,
        max_batch: int = 256,
        batch_policy="adaptive",
        maxsize: Optional[int] = None,
        faults=None,
        metrics_registry=None,
    ) -> None:
        self.store = store
        self.max_batch = max(1, int(max_batch))
        if batch_policy != "adaptive":
            batch_policy = max(1, int(batch_policy))
        self.batch_policy = batch_policy
        self.maxsize = self.DEFAULT_MAXSIZE if maxsize is None else max(1, int(maxsize))
        self.faults = faults
        self._lock = make_lock("ingest")
        self._cond = threading.Condition(self._lock)
        self._queue: "deque[IngestOp]" = deque()
        self._cur_max = 1 if batch_policy == "adaptive" else int(batch_policy)
        self._applying = False
        self._stopped = False
        # single-writer stats (dispatcher thread) + producer-side drop
        # accounting; read by /metrics and tests
        self.events_in = 0  # ops accepted into the queue
        self.events_applied = 0  # ops applied to the store
        self.batches = 0  # apply_events calls issued (incl. size-1)
        self.op_errors = 0  # per-op failures (incl. injected partials)
        self.dropped = 0  # ops shed by drop-oldest (PER EVENT)
        self.overflowed = False  # the stream has a gap — consumer should relist
        # kinds with an unrepaired gap; reflectors consume via take_overflow
        self._overflow_kinds: set = set()
        self.max_batch_seen = 0
        self._batch_hist = None
        self._events_ctr = None
        if metrics_registry is not None:
            from ..metrics import register_ingest_metrics

            register_ingest_metrics(metrics_registry, self)
        self._thread = threading.Thread(
            target=self._run, name="ingest-batcher", daemon=True
        )
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def submit(self, verb: str, kind: str, payload) -> None:
        """Queue one op; never blocks. On a full queue the OLDEST op is
        shed (counted per event in ``dropped``, gap flagged)."""
        self.submit_many(((verb, kind, payload),))

    def submit_many(self, ops: Sequence[IngestOp]) -> None:
        """Queue a producer-side batch under one lock hold. Overflow sheds
        one op per overflowing op — the counter moves PER EVENT even when a
        whole producer batch is shed at once — under the verdict-safe
        policy (see the module docstring's overload posture)."""
        with self._cond:
            if self._stopped:
                return
            for op in ops:
                if len(self._queue) >= self.maxsize and not self._shed_for_locked(op):
                    continue  # the incoming op itself was shed
                self._queue.append(op)
                self.events_in += 1
            self._cond.notify()

    @staticmethod
    def _sheddable(op: IngestOp) -> bool:
        """Only pod upserts may be shed: a dropped pod refresh costs status
        lag until the forced relist; a dropped throttle spec, namespace, or
        ANY delete costs verdict correctness until a relist nobody runs."""
        verb, kind, _ = op
        return kind == "Pod" and verb != "delete"

    def _shed_one_locked(self, op: IngestOp) -> None:
        self.dropped += 1
        self.overflowed = True
        self._overflow_kinds.add(op[1])

    def _shed_for_locked(self, incoming: IngestOp) -> bool:
        """Make room for ``incoming`` on a full queue. True ⇒ append it;
        False ⇒ ``incoming`` itself was dropped (queued ops were all
        verdict-critical and the incoming op was not)."""
        while len(self._queue) >= self.maxsize:
            idx = next(
                (i for i, op in enumerate(self._queue) if self._sheddable(op)),
                None,
            )
            if idx is None:
                if self._sheddable(incoming):
                    self._shed_one_locked(incoming)
                    return False
                # verdict-critical op against a verdict-critical backlog:
                # exceed the bound rather than shed correctness (critical
                # ops are bounded by spec-churn rates, not pod storms)
                return True
            shed = self._queue[idx]
            del self._queue[idx]
            self._shed_one_locked(shed)
        return True

    def take_overflow(self, kind: str) -> bool:
        """Consume ``kind``'s pending-gap marker (True exactly once per
        overflow episode): the kind's reflector forces a relist to repair
        the shed events' gap. ``overflowed``/``dropped`` stay as the
        sticky stats."""
        with self._cond:
            if kind in self._overflow_kinds:
                self._overflow_kinds.discard(kind)
                return True
            return False

    # typed convenience (the watch/reflector layer's vocabulary)

    def upsert(self, kind: str, obj) -> None:
        self.submit("upsert", kind, obj)

    def delete(self, kind: str, key: str) -> None:
        self.submit("delete", kind, key)

    # -- dispatcher --------------------------------------------------------

    def _drain_locked(self) -> List[IngestOp]:
        n = min(len(self._queue), self._cur_max)
        batch = [self._queue.popleft() for _ in range(n)]
        if self.batch_policy == "adaptive":
            if self._queue:
                # backlog remains: next drain may take twice as much
                self._cur_max = min(self._cur_max * 2, self.max_batch)
            else:
                # queue ran dry: collapse toward the unloaded single-event
                # path (halving, not snapping to 1, rides out pacing jitter)
                self._cur_max = max(1, self._cur_max // 2)
        return batch

    def _apply(self, batch: List[IngestOp]) -> None:
        fault = (
            self.faults.check("ingest.batch.partial")
            if self.faults is not None and len(batch) > 1
            else None
        )
        if fault is not None:
            # a poisoned op mid-batch: apply the prefix, fail the op,
            # apply the suffix — the batch tears into two, never wedges
            k = len(batch) // 2
            self._apply_ops(batch[:k])
            self.op_errors += 1
            logger.warning(
                "ingest: injected partial-batch failure dropped op %d/%d "
                "(site ingest.batch.partial, hit %d)", k, len(batch), fault.hit
            )
            self._apply_ops(batch[k + 1 :])
            return
        self._apply_ops(batch)

    def _apply_ops(self, ops: List[IngestOp]) -> None:
        if not ops:
            return
        if len(ops) == 1:
            # unloaded path: single events go through the exact pre-batching
            # single-op store path (no batch listeners, no group commit)
            verb, kind, payload = ops[0]
            try:
                with self.store._lock:  # noqa: SLF001 — same-package access
                    self.store._dispatch_locked(  # noqa: SLF001
                        self.store._apply_op_locked(verb, kind, payload)  # noqa: SLF001
                    )
                self.events_applied += 1
            except Exception:  # noqa: BLE001 — counted, never kills the loop
                self.op_errors += 1
                logger.warning("ingest: single op failed", exc_info=True)
            return
        results = self.store.apply_events(ops)
        ok = sum(1 for r in results if not isinstance(r, Exception))
        self.events_applied += ok
        errs = len(results) - ok
        if errs:
            self.op_errors += errs
            logger.warning("ingest: %d/%d ops failed in batch", errs, len(results))

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(0.2)
                if self._stopped and not self._queue:
                    return
                batch = self._drain_locked()
                self._applying = True
            try:
                self._apply(batch)
            except Exception:  # noqa: BLE001 — a batch must never kill ingest
                self.op_errors += len(batch)
                logger.exception("ingest: batch apply failed (%d ops)", len(batch))
            finally:
                self.batches += 1
                if len(batch) > self.max_batch_seen:
                    self.max_batch_seen = len(batch)
                if self._batch_hist is not None:
                    self._batch_hist.observe_key((), float(len(batch)))
                if self._events_ctr is not None:
                    self._events_ctr.inc({}, float(len(batch)))
                with self._cond:
                    self._applying = False
                    self._cond.notify_all()  # wake flush()

    # -- lifecycle ---------------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until the queue is drained and no batch is in flight (or
        timeout). True when fully drained."""
        import time

        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._queue or self._applying) and time.monotonic() < deadline:
                self._cond.wait(0.05)
            return not self._queue and not self._applying

    def qsize(self) -> int:
        with self._cond:
            return len(self._queue)

    def stop(self, timeout: float = 5.0) -> None:
        """Drain what's queued, then stop the dispatcher."""
        self.flush(timeout)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=2.0)

    def stats(self) -> dict:
        with self._cond:
            return {
                "events_in": self.events_in,
                "events_applied": self.events_applied,
                "batches": self.batches,
                "op_errors": self.op_errors,
                "dropped": self.dropped,
                "overflowed": self.overflowed,
                "queue_depth": len(self._queue),
                "cur_max": self._cur_max,
                "max_batch_seen": self.max_batch_seen,
            }


__all__ = ["MicroBatchIngest", "IngestOp"]
