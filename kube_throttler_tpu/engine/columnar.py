"""Columnar arena object store — the interned, struct-of-arrays hot core.

Why (measured, docs/PERFORMANCE.md "What bounds each path"): the
frozen-dict object model was the ceiling everywhere the kernels aren't —
~8k Python status writes/s/core, a 1.4M-object steady-state heap that
forced the gc.freeze posture (500-750 ms gen-2 pauses), per-event
hydration costs, and — since the sharding front keeps a merged store
while each shard keeps its slice — full-object RSS that multiplied with
shard count. A Pod here is ~10 heap objects (Pod, PodSpec, PodStatus,
labels/annotations dicts, container list + Container + requests dict,
strings); at the 1M-pod target that is >10M tracked objects before the
first throttle exists.

The arena replaces per-pod object graphs with columns:

- **InternPool** — one append-only str↔id pool shared by the store's
  pod arena and the selector indexes (names, namespaces, uids, label
  keys AND values intern here);
- **shape tables** — a pod's label set, annotation set, and request
  structure (containers × init-containers × overhead) intern as whole
  shapes: all pods with the same labels share ONE canonical dict, all
  pods with the same resource requests share ONE tuple of Container
  objects and ONE cached ``[(dim, milli)]`` device-encoding row — the
  struct-of-arrays ``[P, R]`` feed with zero per-pod dict hydration;
- **PodArena** — int32 parallel arrays (name/ns/uid/sched/node/phase
  ids + the three shape ids) over recycled slots with generation
  counters; per-pod marginal cost is ~40 bytes of array plus one dict
  entry in the key→slot map.

Full API objects are materialized **lazily at the serialization/API
edge only** (``materialize``): store reads, snapshot/journal writes, and
wire serialization build a real ``api.pod.Pod`` on demand (sharing the
canonical label/annotation dicts and container tuples), and the object
dies young — reference counting frees it without the cycle collector
ever seeing the pod population.

Equivalence: ``materialize(absorb(pod))`` round-trips every field the
wire format carries (pinned by tests/test_columnar_store.py, the
seeded columnar-vs-frozen-dict sweeps, and the snapshot fixtures).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.pod import Container, Pod, PodSpec, PodStatus
from ..quantity import format_quantity, parse_quantity
from ..resourcelist import ResourceList, add, set_max
from ..utils.lockorder import make_lock

__all__ = [
    "InternPool",
    "PodArena",
    "ColumnarEventFrame",
    "render_request_shape",
    "parse_request_shape",
]


def render_request_shape(containers, init_containers, overhead) -> dict:
    """JSON-able render of one request shape — the canonical columnar
    wire form shared by the snapshot-v2 pod block and the shared-memory
    event plane (sharding/shmring.py): quantities out as
    ``format_quantity`` strings, keys sorted, empty sections omitted."""

    def ctrs(cs):
        return [
            [
                c.name,
                {k: format_quantity(v) for k, v in sorted((c.requests or {}).items())},
            ]
            for c in cs
        ]

    out = {"containers": ctrs(containers)}
    if init_containers:
        out["initContainers"] = ctrs(init_containers)
    if overhead:
        out["overhead"] = {
            k: format_quantity(v) for k, v in sorted(overhead.items())
        }
    return out


def parse_request_shape(d: dict) -> tuple:
    """Inverse of :func:`render_request_shape`:
    ``(containers, init_containers, overhead)`` with shared Container
    tuples and parsed quantities — every pod of the same shape can share
    one decode."""

    def parse_ctrs(items):
        return tuple(
            Container(
                requests={k: parse_quantity(v) for k, v in reqs.items()}, name=name
            )
            for name, reqs in items
        )

    return (
        parse_ctrs(d.get("containers", [])),
        parse_ctrs(d.get("initContainers", [])),
        {k: parse_quantity(v) for k, v in d["overhead"].items()}
        if d.get("overhead")
        else None,
    )


class InternPool:
    """Append-only string interner: ``id_of`` assigns dense ids,
    ``name_of`` reverses them. Thread-safe: misses take the lock; hits
    are plain dict reads (coherent under the GIL — the dict only ever
    grows). Compatible with SelectorIndex's ``_Interner`` duck type so
    one pool can back both the arena and the label indexes."""

    __slots__ = ("_ids", "_names", "_lock")

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self._names: List[str] = []
        self._lock = threading.Lock()

    def id_of(self, value: str) -> int:
        idx = self._ids.get(value)
        if idx is not None:
            return idx
        with self._lock:
            idx = self._ids.get(value)
            if idx is None:
                idx = len(self._names)
                self._names.append(value)
                self._ids[value] = idx
            return idx

    def name_of(self, idx: int) -> str:
        return self._names[idx]

    def __len__(self) -> int:
        return len(self._names)


class _ReqShape:
    """One interned request structure: the canonical containers /
    init-containers / overhead triple, its materialized (shared)
    objects, and the derived encodings every consumer of "what does
    this pod request" needs — computed once per distinct shape instead
    of once per pod event."""

    __slots__ = ("containers", "init_containers", "overhead", "_eff", "_entries")

    def __init__(self, containers, init_containers, overhead) -> None:
        self.containers: Tuple[Container, ...] = containers
        self.init_containers: Tuple[Container, ...] = init_containers
        self.overhead: Optional[ResourceList] = overhead
        self._eff: Optional[ResourceList] = None
        # {id(dims): [(dim index, milli)]} — see PodArena.entries_for
        self._entries: Dict[int, list] = {}

    def effective(self) -> ResourceList:
        """The pod's effective request (resourcelist.go:27-46 semantics:
        max(per-init max, sum of app containers) + overhead), cached.
        Returns the SHARED dict — callers must not mutate."""
        if self._eff is None:
            ic: ResourceList = {}
            for c in self.init_containers:
                set_max(ic, c.requests)
            res: ResourceList = {}
            for c in self.containers:
                add(res, c.requests)
            set_max(res, ic)
            if self.overhead:
                add(res, self.overhead)
            self._eff = res
        return self._eff


class ColumnarEventFrame:
    """The columnar batch payload accompanying one dispatched event
    batch: parallel columns (verb/kind codes, keys, rvs, arena slots)
    instead of N object-bearing Events. Batch listeners that prefer
    flat arrays (the sharding front's router, and — ROADMAP item 3 —
    the zero-copy IPC rings) read this; everyone else keeps consuming
    the Event list. Slots are -1 for non-pod events and for the
    frozen-dict reference store."""

    VERBS = {"ADDED": 0, "MODIFIED": 1, "DELETED": 2}
    KINDS = {"Pod": 0, "Namespace": 1, "Throttle": 2, "ClusterThrottle": 3}

    __slots__ = ("verbs", "kinds", "keys", "rvs", "slots", "arena")

    def __init__(self, events, key_of: Callable, arena: Optional["PodArena"]) -> None:
        n = len(events)
        self.verbs = np.empty(n, dtype=np.int8)
        self.kinds = np.empty(n, dtype=np.int8)
        self.rvs = np.empty(n, dtype=np.int64)
        self.slots = np.full(n, -1, dtype=np.int32)
        self.keys: List[str] = []
        self.arena = arena
        slot_of = arena.slot_of if arena is not None else None
        for i, ev in enumerate(events):
            self.verbs[i] = self.VERBS[ev.type.value]
            self.kinds[i] = self.KINDS[ev.kind]
            self.rvs[i] = ev.rv if ev.rv is not None else -1
            key = key_of(ev.kind, ev.obj)
            self.keys.append(key)
            if slot_of is not None and ev.kind == "Pod" and ev.type.value != "DELETED":
                slot = slot_of(key)
                if slot is not None:
                    self.slots[i] = slot

    def __len__(self) -> int:
        return len(self.keys)


def _canon_requests(requests) -> tuple:
    return tuple(sorted(requests.items()))


def _canon_containers(containers) -> tuple:
    return tuple((c.name, _canon_requests(c.requests)) for c in containers)


class PodArena:
    """Struct-of-arrays pod storage with slot recycling and generation
    counters.

    Locking: the arena carries its own LEAF lock — it never acquires
    another lock while held (the intern pool's internal mutex is a plain
    untracked primitive), so any component may materialize through it
    regardless of what else it holds. Mutations (absorb/free) happen
    under the store lock AND the arena lock; lazy readers (resolvers,
    metrics, snapshot export) take only the arena lock, which is what
    lets the selector indexes resolve pods without a store→index /
    index→store order cycle."""

    _GROW = 2

    # every column/table below moves only under the arena's leaf lock
    GUARDED_BY = {
        "_free": "self.lock",
        "_next": "self.lock",
        # _cap/_slots are deliberately unlisted: _grow_locked/_absorb_locked
        # mutate them under the lock, while slot_of/__contains__/keys serve
        # GIL-coherent lock-free dict reads (single-mutator via the store)
        "_label_ids": "self.lock",
        "_label_shapes": "self.lock",
        "_ann_ids": "self.lock",
        "_ann_shapes": "self.lock",
        "_req_ids": "self.lock",
        "_dims_refs": "self.lock",
        # _req_shapes is deliberately unlisted: the list is append-only
        # under the lock and hot readers (req_shape_of / entries_for's
        # first probe) index it lock-free — GIL-coherent like the intern
        # pool's dict reads
    }

    def __init__(self, pool: Optional[InternPool] = None, capacity: int = 64) -> None:
        self.lock = make_lock("store.arena")
        self.pool = pool or InternPool()
        # identity token stamped on absorbed/materialized pods next to
        # their request-shape id: a shape id is meaningless outside ITS
        # arena (a pickled pod crossing the shard IPC, or an oracle
        # store's pod probed against the serving stack, would otherwise
        # resolve against the wrong shape table — silently wrong request
        # rows). Unpickling clones the token, so foreign pods always
        # fail the identity check and take the full encode path.
        self.token = object()
        self._cap = max(8, int(capacity))
        # parallel columns: identity + spec scalars + the three shape ids
        zi = lambda: np.full(self._cap, -1, dtype=np.int32)
        self.name_id = zi()
        self.ns_id = zi()
        self.uid_id = zi()
        self.sched_id = zi()
        self.node_id = zi()
        self.phase_id = zi()
        self.labels_sid = zi()
        self.ann_sid = zi()
        self.req_sid = zi()
        self.gen = np.zeros(self._cap, dtype=np.int32)
        self.valid = np.zeros(self._cap, dtype=bool)
        self._free: List[int] = []
        self._next = 0
        self._slots: Dict[str, int] = {}  # store key -> live slot

        # shape tables: canonical key -> shape id; shape id -> shared object
        self._label_ids: Dict[tuple, int] = {}
        self._label_shapes: List[Dict[str, str]] = []
        self._ann_ids: Dict[tuple, int] = {}
        self._ann_shapes: List[Dict[str, str]] = []
        self._req_ids: Dict[tuple, int] = {}
        self._req_shapes: List[_ReqShape] = []
        # strong refs to DimRegistry objects entries_for has cached
        # against (keyed by id() — the ref pins the id)
        self._dims_refs: Dict[int, object] = {}

        # stats (metrics.register_store_metrics samples these)
        self.materializations_total = 0
        self.recycled_total = 0
        self.absorbed_total = 0

    # -- capacity ---------------------------------------------------------

    def _grow_locked(self) -> None:
        new_cap = self._cap * self._GROW
        for name in (
            "name_id", "ns_id", "uid_id", "sched_id", "node_id", "phase_id",
            "labels_sid", "ann_sid", "req_sid",
        ):
            arr = getattr(self, name)
            grown = np.full(new_cap, -1, dtype=np.int32)
            grown[: self._cap] = arr
            setattr(self, name, grown)
        grown_gen = np.zeros(new_cap, dtype=np.int32)
        grown_gen[: self._cap] = self.gen
        self.gen = grown_gen
        grown_valid = np.zeros(new_cap, dtype=bool)
        grown_valid[: self._cap] = self.valid
        self.valid = grown_valid
        self._cap = new_cap

    # -- shape interning --------------------------------------------------

    def _labels_shape_locked(self, labels: Dict[str, str], table, ids) -> int:
        key = tuple(sorted(labels.items()))
        sid = ids.get(key)
        if sid is None:
            sid = len(table)
            table.append(dict(key))
            ids[key] = sid
        return sid

    def _req_shape_locked(self, spec: PodSpec) -> int:
        key = (
            _canon_containers(spec.containers),
            _canon_containers(spec.init_containers),
            _canon_requests(spec.overhead) if spec.overhead else None,
        )
        sid = self._req_ids.get(key)
        if sid is None:
            sid = len(self._req_shapes)
            containers = tuple(
                Container(requests=dict(reqs), name=name) for name, reqs in key[0]
            )
            init = tuple(
                Container(requests=dict(reqs), name=name) for name, reqs in key[1]
            )
            overhead = dict(key[2]) if key[2] is not None else None
            self._req_shapes.append(_ReqShape(containers, init, overhead))
            self._req_ids[key] = sid
        return sid

    def request_shape_id(self, spec: PodSpec) -> int:
        """Intern ``spec``'s request shape and return its id — the public
        entry for consumers holding a pod that was never absorbed (e.g. a
        scheduler-fresh PreFilter pod): the verdict cache keys on the
        shape id, and an unpickled/foreign pod object carries no stamped
        ``_kt_req_sid``. Interning (not hashing) keeps the id space shared
        with absorbed pods, so fresh and stored pods of the same shape
        land on the same cache rows."""
        with self.lock:
            return self._req_shape_locked(spec)

    # -- absorb / free ----------------------------------------------------

    def absorb(self, key: str, pod: Pod) -> int:
        """Write ``pod`` into the arena (new slot, or overwriting the key's
        live slot) and CANONICALIZE the object in place: its labels and
        annotations are swapped for the equal shared shape dicts, and the
        request-shape id is stamped on it (``_kt_req_sid``) so downstream
        consumers (index retention, the device encode) key into shared
        state instead of keeping per-pod copies alive."""
        with self.lock:
            return self._absorb_locked(key, pod)

    def _absorb_locked(self, key: str, pod: Pod) -> int:
        slot = self._slots.get(key)
        if slot is None:
            if self._free:
                slot = self._free.pop()
            else:
                slot = self._next
                self._next += 1
                while slot >= self._cap:
                    self._grow_locked()
            self._slots[key] = slot
        pool = self.pool
        self.name_id[slot] = pool.id_of(pod.name)
        self.ns_id[slot] = pool.id_of(pod.namespace)
        self.uid_id[slot] = pool.id_of(pod.uid)
        self.sched_id[slot] = pool.id_of(pod.spec.scheduler_name)
        self.node_id[slot] = pool.id_of(pod.spec.node_name)
        self.phase_id[slot] = pool.id_of(pod.status.phase)
        lsid = self._labels_shape_locked(pod.labels, self._label_shapes, self._label_ids)
        asid = self._labels_shape_locked(pod.annotations, self._ann_shapes, self._ann_ids)
        rsid = self._req_shape_locked(pod.spec)
        self.labels_sid[slot] = lsid
        self.ann_sid[slot] = asid
        self.req_sid[slot] = rsid
        self.gen[slot] += 1
        self.valid[slot] = True
        self.absorbed_total += 1
        # canonicalize: share the interned dicts (equal content, shared
        # identity — makes the index's unchanged-labels check an identity
        # hit and drops the per-pod dict from the live heap)
        pod.labels = self._label_shapes[lsid]
        pod.annotations = self._ann_shapes[asid]
        pod.__dict__["_kt_req_sid"] = rsid
        pod.__dict__["_kt_arena"] = self.token
        return slot

    def free(self, key: str) -> Optional[int]:
        with self.lock:
            return self._free_locked(key)

    def _free_locked(self, key: str) -> Optional[int]:
        slot = self._slots.pop(key, None)
        if slot is None:
            return None
        self.valid[slot] = False
        self.gen[slot] += 1
        self._free.append(slot)
        self.recycled_total += 1
        return slot

    # -- reads ------------------------------------------------------------

    def slot_of(self, key: str) -> Optional[int]:
        return self._slots.get(key)

    def __contains__(self, key: str) -> bool:
        return key in self._slots

    def __len__(self) -> int:
        return len(self._slots)

    def keys(self):
        return self._slots.keys()

    def materialize(self, slot: int) -> Pod:
        """Build a full API Pod from the columns (the lazy edge). The
        labels/annotations dicts and Container objects are the SHARED
        canonical shapes — immutable by store convention."""
        with self.lock:
            return self._materialize_locked(slot)

    def _materialize_locked(self, slot: int) -> Pod:
        self.materializations_total += 1
        names = self.pool.name_of
        shape = self._req_shapes[self.req_sid[slot]]
        rsid = int(self.req_sid[slot])
        pod = Pod(
            name=names(self.name_id[slot]),
            namespace=names(self.ns_id[slot]),
            labels=self._label_shapes[self.labels_sid[slot]],
            annotations=self._ann_shapes[self.ann_sid[slot]],
            uid=names(self.uid_id[slot]),
            spec=PodSpec(
                scheduler_name=names(self.sched_id[slot]),
                node_name=names(self.node_id[slot]),
                containers=list(shape.containers),
                init_containers=list(shape.init_containers),
                overhead=shape.overhead,
            ),
            status=PodStatus(phase=names(self.phase_id[slot])),
        )
        pod.__dict__["_kt_req_sid"] = rsid
        pod.__dict__["_kt_arena"] = self.token
        return pod

    def materialize_key(self, key: str) -> Optional[Pod]:
        with self.lock:
            slot = self._slots.get(key)
            return self._materialize_locked(slot) if slot is not None else None

    # -- derived encodings -------------------------------------------------

    def req_shape_of(self, sid: int) -> _ReqShape:
        return self._req_shapes[sid]

    def entries_for(self, sid: int, dims) -> list:
        """``[(dim index, milli)]`` of request shape ``sid`` against the
        given DimRegistry — the canonical device row encode, computed
        once per (shape, registry) instead of once per pod event (dim
        indexes are append-only stable, so the cache never invalidates).
        This is the zero-hydration feed from the arena into the device
        staging's ``[P, R]`` planes."""
        from ..ops.schema import to_milli

        shape = self._req_shapes[sid]
        entries = shape._entries.get(id(dims))
        if entries is None:
            with self.lock:
                entries = shape._entries.get(id(dims))
                if entries is None:
                    self._dims_refs[id(dims)] = dims
                    entries = [
                        (dims.index_of(name), to_milli(q))
                        for name, q in shape.effective().items()
                    ]
                    shape._entries[id(dims)] = entries
        return entries

    # -- stats -------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self.lock:
            return self._stats_locked()

    def _stats_locked(self) -> Dict[str, int]:
        return {
            "slots_live": len(self._slots),
            "slots_recycled_total": self.recycled_total,
            "intern_pool_size": len(self.pool),
            "label_shapes": len(self._label_shapes),
            "annotation_shapes": len(self._ann_shapes),
            "request_shapes": len(self._req_shapes),
            "materializations_total": self.materializations_total,
            "absorbed_total": self.absorbed_total,
        }

    # -- snapshot v2 columnar block ----------------------------------------

    def export_columns(self, keys: Sequence[str]) -> Dict[str, Any]:
        """JSON-able columnar pod block for snapshot v2 — a LOCAL string
        table plus per-pod id rows in ``keys`` order. ~30 bytes of JSON
        per pod instead of ~1 KB of manifest dict (and no per-pod object
        materialization on the write path). Caller coherence: runs under
        the store lock (the snapshot gather), plus the arena lock here."""
        with self.lock:
            return self._export_columns_locked(keys)

    def _export_columns_locked(self, keys: Sequence[str]) -> Dict[str, Any]:
        local: Dict[int, int] = {}
        strings: List[str] = []

        def lid(gid: int) -> int:
            out = local.get(gid)
            if out is None:
                out = len(strings)
                strings.append(self.pool.name_of(gid))
                local[gid] = out
            return out

        cols: Dict[str, List[int]] = {
            f: [] for f in ("name", "ns", "uid", "sched", "node", "phase",
                            "labels", "ann", "req")
        }
        used_label: Dict[int, int] = {}
        used_ann: Dict[int, int] = {}
        used_req: Dict[int, int] = {}
        label_shapes: List[list] = []
        ann_shapes: List[list] = []
        req_shapes: List[dict] = []

        def shape_lid(sid, used, out_list, render):
            out = used.get(sid)
            if out is None:
                out = len(out_list)
                out_list.append(render(sid))
                used[sid] = out
            return out

        def render_labels(table):
            return lambda sid: [[k, v] for k, v in sorted(table[sid].items())]

        def render_req(sid):
            shape = self._req_shapes[sid]
            return render_request_shape(
                shape.containers, shape.init_containers, shape.overhead
            )

        for key in keys:
            slot = self._slots[key]
            cols["name"].append(lid(int(self.name_id[slot])))
            cols["ns"].append(lid(int(self.ns_id[slot])))
            cols["uid"].append(lid(int(self.uid_id[slot])))
            cols["sched"].append(lid(int(self.sched_id[slot])))
            cols["node"].append(lid(int(self.node_id[slot])))
            cols["phase"].append(lid(int(self.phase_id[slot])))
            cols["labels"].append(
                shape_lid(int(self.labels_sid[slot]), used_label, label_shapes,
                          render_labels(self._label_shapes))
            )
            cols["ann"].append(
                shape_lid(int(self.ann_sid[slot]), used_ann, ann_shapes,
                          render_labels(self._ann_shapes))
            )
            cols["req"].append(
                shape_lid(int(self.req_sid[slot]), used_req, req_shapes, render_req)
            )
        return {
            "strings": strings,
            "labelShapes": label_shapes,
            "annotationShapes": ann_shapes,
            "requestShapes": req_shapes,
            **cols,
        }


def pods_from_columns(block: Dict[str, Any]):
    """Yield ``Pod`` objects from a snapshot-v2 columnar block (the
    migration/read edge — replication bootstrap and recovery both
    consume this). Label/annotation dicts and container objects are
    shared across pods of the same shape, like the live arena."""
    strings = block["strings"]
    label_shapes = [dict(pairs) for pairs in block.get("labelShapes", [])]
    ann_shapes = [dict(pairs) for pairs in block.get("annotationShapes", [])]

    req_shapes = [parse_request_shape(d) for d in block.get("requestShapes", [])]
    n = len(block.get("name", []))
    for i in range(n):
        containers, init, overhead = req_shapes[block["req"][i]]
        yield Pod(
            name=strings[block["name"][i]],
            namespace=strings[block["ns"][i]],
            labels=label_shapes[block["labels"][i]],
            annotations=ann_shapes[block["ann"][i]],
            uid=strings[block["uid"][i]],
            spec=PodSpec(
                scheduler_name=strings[block["sched"][i]],
                node_name=strings[block["node"][i]],
                containers=list(containers),
                init_containers=list(init),
                overhead=overhead,
            ),
            status=PodStatus(phase=strings[block["phase"][i]]),
        )
