"""Durable event journal for the standalone Store.

The reference is crash-only because its state of record lives on the
kube-apiserver — informer caches resync on restart (plugin.go:114-130) and
reservations are scheduler-cycle-transient (SURVEY §5). This build has the
same stance in remote (``--kubeconfig``) mode: reflectors rebuild the cache
from the real apiserver. In STANDALONE mode, however, the in-memory Store
IS the apiserver, so crash-only needs a durable log: the journal appends
every watch event as a JSON line and replays it on startup, making
``status``/spec state survive a daemon restart.

Format: one ``{"type": ..., "kind": ..., "object": {...}}`` per line —
deliberately the watch wire-event shape (client/transport.py), so the
journal doubles as a replayable watch stream. A truncated trailing line
(crash mid-write) is tolerated and dropped. When the live log exceeds
``compact_after`` lines it is compacted to a snapshot of ADDED events
(written to a temp file, atomically renamed).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
from typing import Optional, Tuple

from ..api.serialization import object_from_dict, object_to_dict
from .store import Event, EventType, Store

logger = logging.getLogger(__name__)

# replay creation order: namespaced objects need their namespaces first
_KIND_ORDER = {"Namespace": 0, "Throttle": 1, "ClusterThrottle": 1, "Pod": 2}


class StoreJournal:
    """Attach with :func:`attach`; detach via :meth:`close`."""

    def __init__(self, store: Store, path: str, compact_after: int = 100_000):
        self.store = store
        self.path = path
        self.compact_after = compact_after
        self._lock = threading.Lock()
        self._lines = 0
        self._file = None

    # -- replay -------------------------------------------------------------

    def _replay(self) -> Tuple[int, Optional[int]]:
        """Apply journaled events to the (empty) store. Returns
        ``(applied, truncate_at)``: the event count, and — when a corrupt
        line stopped replay — the byte offset of the end of the last GOOD
        line. The caller MUST truncate there before appending: appending
        past a corrupt line would strand every later event behind the gap
        on all future replays (silent loss of post-crash history)."""
        if not os.path.exists(self.path):
            return 0, None
        applied = 0
        good_end = 0
        with open(self.path, "rb") as f:
            for lineno, raw in enumerate(f, 1):
                line = raw.strip()
                if not line:
                    good_end += len(raw)
                    continue
                try:
                    event = json.loads(line.decode("utf-8"))
                    self._apply(event)
                    applied += 1
                    good_end += len(raw)
                except (
                    json.JSONDecodeError,
                    KeyError,
                    ValueError,
                    UnicodeDecodeError,
                ) as e:
                    # only acceptable at the tail (crash mid-write); report
                    # either way and stop — replaying past a gap would
                    # reorder history
                    logger.warning(
                        "journal %s: stopping replay at line %d (%s); "
                        "truncating the corrupt tail",
                        self.path, lineno, e,
                    )
                    return applied, good_end
        return applied, None

    def _apply(self, event: dict) -> None:
        kind = event["kind"]
        etype = event["type"]
        obj = object_from_dict({**event["object"], "kind": kind})
        store = self.store
        if etype == "DELETED":
            try:
                if kind == "Pod":
                    store.delete_pod(obj.namespace, obj.name)
                elif kind == "Namespace":
                    store.delete_namespace(obj.name)
                elif kind == "Throttle":
                    store.delete_throttle(obj.namespace, obj.name)
                else:
                    store.delete_cluster_throttle(obj.name)
            except KeyError:
                pass
            return
        # ADDED/MODIFIED → upsert (replay must be idempotent-ish: a
        # compacted snapshot starts from ADDED lines)
        try:
            if kind == "Pod":
                store.create_pod(obj)
            elif kind == "Namespace":
                store.create_namespace(obj)
            elif kind == "Throttle":
                store.create_throttle(obj)
            else:
                store.create_cluster_throttle(obj)
        except ValueError:
            if kind == "Pod":
                store.update_pod(obj)
            elif kind == "Namespace":
                store.update_namespace(obj)
            elif kind == "Throttle":
                store.update_throttle(obj)
            else:
                store.update_cluster_throttle(obj)

    # -- live append ----------------------------------------------------------

    def _on_event(self, event: Event) -> None:
        line = json.dumps(
            {
                "type": event.type.value,
                "kind": event.kind,
                "object": object_to_dict(event.obj),
            }
        )
        with self._lock:
            if self._file is None:
                return
            self._file.write(line + "\n")
            self._file.flush()
            self._lines += 1
            if self._lines >= self.compact_after:
                self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite the journal as a snapshot of the CURRENT store contents
        (ADDED lines, namespaces first), atomically."""
        objs = []
        for ns in self.store.list_namespaces():
            objs.append(("Namespace", ns))
        for thr in self.store.list_throttles():
            objs.append(("Throttle", thr))
        for thr in self.store.list_cluster_throttles():
            objs.append(("ClusterThrottle", thr))
        for pod in self.store.list_pods():
            objs.append(("Pod", pod))
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".journal"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                for kind, obj in objs:
                    f.write(
                        json.dumps(
                            {"type": "ADDED", "kind": kind, "object": object_to_dict(obj)}
                        )
                        + "\n"
                    )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._file.close()
        self._file = open(self.path, "a", encoding="utf-8")
        self._lines = len(objs)
        logger.info("journal %s compacted to %d objects", self.path, len(objs))

    def close(self) -> None:
        for kind in Store.KINDS:
            self.store.remove_event_handler(kind, self._on_event)
        with self._lock:
            if self._file is not None:
                self._file.flush()
                self._file.close()
                self._file = None


def attach(store: Store, path: str, compact_after: int = 100_000) -> StoreJournal:
    """Replay ``path`` into the (freshly constructed, empty) store, then
    journal every subsequent event to it. Must run BEFORE other handlers
    are registered so replayed events don't double-dispatch."""
    journal = StoreJournal(store, path, compact_after=compact_after)
    n, truncate_at = journal._replay()
    if n:
        logger.info("journal %s: replayed %d events", path, n)
    if truncate_at is not None:
        with open(path, "r+b") as f:
            f.truncate(truncate_at)
    journal._file = open(path, "a", encoding="utf-8")
    journal._lines = n
    for kind in Store.KINDS:
        store.add_event_handler(kind, journal._on_event, replay=False)
    return journal
