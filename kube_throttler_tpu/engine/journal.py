"""Durable event journal for the standalone Store.

The reference is crash-only because its state of record lives on the
kube-apiserver — informer caches resync on restart (plugin.go:114-130) and
reservations are scheduler-cycle-transient (SURVEY §5). This build has the
same stance in remote (``--kubeconfig``) mode: reflectors rebuild the cache
from the real apiserver. In STANDALONE mode, however, the in-memory Store
IS the apiserver, so crash-only needs a durable log: the journal appends
every watch event as a JSON line and replays it on startup, making
``status``/spec state survive a daemon restart.

Format: one ``{"type": ..., "kind": ..., "object": {...}}`` per line —
deliberately the watch wire-event shape (client/transport.py), so the
journal doubles as a replayable watch stream. A truncated trailing line
(crash mid-write) is tolerated and truncated away; a corrupted INTERIOR
line (torn write followed by later appends, bit rot) is skipped and
counted — startup must not abort on one bad line when every event after it
is intact. When the live log exceeds ``compact_after`` lines it is
compacted to a snapshot of ADDED events (written to a temp file, atomically
renamed); a failed compaction (fsync/rename error) is logged and retried a
window later — it never breaks the store's dispatch.

Fault injection (faults/plan.py): site ``journal.append`` supports mode
``torn`` (write half the line, no newline — the next append turns it into
interior corruption) and ``error`` (drop the write); site ``journal.fsync``
fails the compaction fsync.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Optional, Tuple

from ..api.serialization import object_from_dict, object_to_dict
from ..utils.lockorder import assert_held, guard_attrs, make_lock
from .store import Event, EventType, Store

logger = logging.getLogger(__name__)

# replay creation order: namespaced objects need their namespaces first
_KIND_ORDER = {"Namespace": 0, "Throttle": 1, "ClusterThrottle": 1, "Pod": 2}


@guard_attrs
class StoreJournal:
    """Attach with :func:`attach`; detach via :meth:`close`."""

    # the live-append file handle and its line counter move only under the
    # journal lock (the robustness counters are single-writer ints read by
    # health probes — unguarded on purpose)
    GUARDED_BY = {
        "_file": "self._lock",
        "_lines": "self._lock",
    }

    def __init__(
        self, store: Store, path: str, compact_after: int = 100_000, faults=None
    ):
        self.store = store
        self.path = path
        self.compact_after = compact_after
        self.faults = faults
        self._lock = make_lock("journal")
        self._lines = 0
        self._file = None
        # robustness counters (health probe + tests read these)
        self.replay_skipped = 0  # corrupted interior lines skipped on replay
        self.write_errors = 0  # appends dropped (injected/real write failure)
        self.torn_writes = 0  # injected torn appends
        self.compact_failures = 0  # compactions aborted (old log kept)

    # -- replay -------------------------------------------------------------

    def _replay(self) -> Tuple[int, Optional[int]]:
        """Apply journaled events to the (empty) store. Returns
        ``(applied, truncate_at)``: the event count, and — when the file
        ends in a run of corrupt lines (crash mid-write) — the byte offset
        of the end of the last GOOD line. The caller MUST truncate there
        before appending: appending past a corrupt tail would strand every
        later event behind the gap on all future replays (silent loss of
        post-crash history).

        Corrupt INTERIOR lines — bad lines with good lines after them (a
        torn write the process survived, bit rot) — are skipped and counted
        in ``replay_skipped``, each logged with its line number. Aborting
        on them would trade one lost event for the whole post-gap history;
        replay applies everything that parses and lets the counter/health
        probe surface the gap."""
        if not os.path.exists(self.path):
            return 0, None
        applied = 0
        offset = 0  # byte offset after the current line
        good_end = 0  # byte offset after the last good line
        bad_run: list = []  # (lineno, error) since the last good line
        with open(self.path, "rb") as f:
            for lineno, raw in enumerate(f, 1):
                offset += len(raw)
                line = raw.strip()
                if not line:
                    continue  # blank line: harmless, neither good nor bad
                try:
                    event = json.loads(line.decode("utf-8"))
                    self._apply(event)
                except (
                    json.JSONDecodeError,
                    KeyError,
                    ValueError,
                    UnicodeDecodeError,
                ) as e:
                    bad_run.append((lineno, str(e)))
                    continue
                applied += 1
                # bad lines BEFORE a good line are interior corruption:
                # skip-and-count, never truncate (that would delete the
                # good history that follows)
                for bad_lineno, err in bad_run:
                    self.replay_skipped += 1
                    logger.warning(
                        "journal %s: skipping corrupted line %d (%s)",
                        self.path, bad_lineno, err,
                    )
                bad_run = []
                good_end = offset
        if bad_run:
            # trailing corrupt run (crash mid-write): truncate it away
            logger.warning(
                "journal %s: dropping %d corrupt trailing line(s) from "
                "line %d (%s); truncating",
                self.path, len(bad_run), bad_run[0][0], bad_run[0][1],
            )
            return applied, good_end
        return applied, None

    def _apply(self, event: dict) -> None:
        kind = event["kind"]
        etype = event["type"]
        obj = object_from_dict({**event["object"], "kind": kind})
        store = self.store
        if etype == "DELETED":
            try:
                if kind == "Pod":
                    store.delete_pod(obj.namespace, obj.name)
                elif kind == "Namespace":
                    store.delete_namespace(obj.name)
                elif kind == "Throttle":
                    store.delete_throttle(obj.namespace, obj.name)
                else:
                    store.delete_cluster_throttle(obj.name)
            except KeyError:
                pass
            return
        # ADDED/MODIFIED → upsert (replay must be idempotent-ish: a
        # compacted snapshot starts from ADDED lines)
        try:
            if kind == "Pod":
                store.create_pod(obj)
            elif kind == "Namespace":
                store.create_namespace(obj)
            elif kind == "Throttle":
                store.create_throttle(obj)
            else:
                store.create_cluster_throttle(obj)
        except ValueError:
            if kind == "Pod":
                store.update_pod(obj)
            elif kind == "Namespace":
                store.update_namespace(obj)
            elif kind == "Throttle":
                store.update_throttle(obj)
            else:
                store.update_cluster_throttle(obj)

    # -- live append ----------------------------------------------------------

    def _on_event(self, event: Event) -> None:
        line = json.dumps(
            {
                "type": event.type.value,
                "kind": event.kind,
                "object": object_to_dict(event.obj),
            }
        )
        fault = self.faults.check("journal.append") if self.faults is not None else None
        with self._lock:
            if self._file is None:
                return
            if fault is not None and fault.mode == "error":
                # simulated failed write: the event never reaches the log
                # (the gap is what replay-convergence soaks must tolerate)
                self.write_errors += 1
                return
            if fault is not None and fault.mode == "torn":
                # half the line, no newline: the NEXT append concatenates
                # onto the fragment, producing one corrupt interior line —
                # the exact artifact a crash between write() and the
                # newline leaves behind
                self._file.write(line[: max(1, len(line) // 2)])
                self._file.flush()
                self.torn_writes += 1
                self._lines += 1
                return
            self._file.write(line + "\n")
            self._file.flush()
            self._lines += 1
            if self._lines >= self.compact_after:
                try:
                    self._compact_locked()
                except OSError:
                    # a failed compaction (disk full, fsync error) must not
                    # propagate into the store's dispatch — the old log is
                    # intact and still growing; retry a full window later
                    self.compact_failures += 1
                    self._lines = 0
                    logger.warning(
                        "journal %s: compaction failed; keeping the "
                        "uncompacted log and retrying later",
                        self.path, exc_info=True,
                    )

    def _compact_locked(self) -> None:
        """Rewrite the journal as a snapshot of the CURRENT store contents
        (ADDED lines, namespaces first), atomically. Caller holds the
        journal lock (asserted under KT_LOCK_ASSERT=1)."""
        assert_held(self._lock, "StoreJournal._compact_locked")
        objs = []
        for ns in self.store.list_namespaces():
            objs.append(("Namespace", ns))
        for thr in self.store.list_throttles():
            objs.append(("Throttle", thr))
        for thr in self.store.list_cluster_throttles():
            objs.append(("ClusterThrottle", thr))
        for pod in self.store.list_pods():
            objs.append(("Pod", pod))
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".journal"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                for kind, obj in objs:
                    f.write(
                        json.dumps(
                            {"type": "ADDED", "kind": kind, "object": object_to_dict(obj)}
                        )
                        + "\n"
                    )
                f.flush()
                if self.faults is not None:
                    self.faults.maybe_raise(
                        "journal.fsync",
                        default=lambda: OSError("injected fsync failure"),
                    )
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._file.close()
        self._file = open(self.path, "a", encoding="utf-8")
        self._lines = len(objs)
        logger.info("journal %s compacted to %d objects", self.path, len(objs))

    def compact(self) -> None:
        """Force a compaction now (operational hook + the chaos soak's
        heal-the-log step): the journal becomes a clean snapshot of the
        live store, erasing any torn/corrupt interior lines."""
        # store lock FIRST — the same order as the dispatch path
        # (store._dispatch_locked -> _on_event -> journal lock). Taking
        # only the journal lock here and letting _compact_locked's
        # store.list_* acquire the store lock underneath was an ABBA
        # inversion against concurrent writers (found by KT_LOCK_ASSERT),
        # and it could also lose a concurrent event: one appended to the
        # old file after the snapshot was cut would vanish at rotation.
        with self.store._lock:  # noqa: SLF001 — same-package access
            with self._lock:
                if self._file is not None:
                    self._compact_locked()

    def health_state(self) -> Tuple[str, dict]:
        """Health-component contract (health.py): degraded while any
        corruption/write-loss counter is nonzero — the journal still works,
        but an operator should know recovery was lossy."""
        detail = {
            "replaySkipped": self.replay_skipped,
            "writeErrors": self.write_errors,
            "compactFailures": self.compact_failures,
        }
        degraded = self.replay_skipped or self.write_errors or self.compact_failures
        return ("degraded" if degraded else "ok"), detail

    def close(self) -> None:
        for kind in Store.KINDS:
            self.store.remove_event_handler(kind, self._on_event)
        with self._lock:
            if self._file is not None:
                self._file.flush()
                self._file.close()
                self._file = None


def attach(
    store: Store, path: str, compact_after: int = 100_000, faults=None
) -> StoreJournal:
    """Replay ``path`` into the (freshly constructed, empty) store, then
    journal every subsequent event to it. Must run BEFORE other handlers
    are registered so replayed events don't double-dispatch."""
    journal = StoreJournal(store, path, compact_after=compact_after, faults=faults)
    n, truncate_at = journal._replay()
    if n:
        logger.info(
            "journal %s: replayed %d events (%d corrupted line(s) skipped)",
            path, n, journal.replay_skipped,
        )
    if truncate_at is not None:
        with open(path, "r+b") as f:
            f.truncate(truncate_at)
    # under the lock although pre-publication: _file/_lines are declared
    # guarded, and the runtime guard (KT_LOCK_ASSERT=1) checks rebinds
    with journal._lock:
        journal._file = open(path, "a", encoding="utf-8")
        journal._lines = n
    for kind in Store.KINDS:
        store.add_event_handler(kind, journal._on_event, replay=False)
    return journal
