"""Durable event journal for the standalone Store.

The reference is crash-only because its state of record lives on the
kube-apiserver — informer caches resync on restart (plugin.go:114-130) and
reservations are scheduler-cycle-transient (SURVEY §5). This build has the
same stance in remote (``--kubeconfig``) mode: reflectors rebuild the cache
from the real apiserver. In STANDALONE mode, however, the in-memory Store
IS the apiserver, so crash-only needs a durable log: the journal appends
every watch event as a JSON line and replays it on startup, making
``status``/spec state survive a daemon restart.

Format: one ``{"type": ..., "kind": ..., "object": {...}}`` per line —
deliberately the watch wire-event shape (client/transport.py), so the
journal doubles as a replayable watch stream. Corruption handling on
replay distinguishes two cases:

- a torn FINAL line (crash between ``write()`` and the newline) is the
  legal crash artifact — it is silently truncated away and counted in
  ``torn_tails`` without degrading health;
- a corrupted INTERIOR line (torn write followed by later appends, bit
  rot) — and any extra bad lines in a trailing corrupt run beyond the
  final one — is skipped and counted in ``replay_skipped``: startup must
  not abort on one bad line when every event after it is intact, and the
  health probe surfaces the loss.

When the live log exceeds ``compact_after`` lines it is compacted to a
snapshot of ADDED events (written to a temp file, atomically renamed); a
failed compaction (fsync/rename error) is logged and retried a window
later — it never breaks the store's dispatch. Because compaction
invalidates every existing snapshot's recorded journal anchor, a bound
snapshotter is triggered immediately after each compaction so the newest
snapshot always carries a valid anchor (local recovery falls back to
genesis replay either way; streaming standbys re-bootstrap from the
newest snapshot and need its anchor to resolve).

The journal also maintains a running ``(byte offset, sha256)`` of its
content, exposed via :meth:`position`. Snapshots (engine/snapshot.py)
record that pair at cut time; recovery (engine/recovery.py) verifies the
prefix hash to decide whether the on-disk journal is a strict superset of
the snapshot (replay only the tail from ``start_offset``) or has been
compacted since (replay from genesis instead). ``set_snapshotter`` arms a
journal-size snapshot trigger fired every ``snapshot_every`` appended
lines (outside the journal lock, inside the store's dispatch).

Fault injection (faults/plan.py): site ``journal.append`` supports mode
``torn`` (write half the line, no newline — the next append turns it into
interior corruption) and ``error`` (drop the write); site ``journal.fsync``
fails the compaction fsync. The ``crash.journal.*`` sites SIGKILL the
process at the worst instants (see the crash harness, tools/crashtest.py).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
from typing import Optional, Tuple

from ..api.serialization import object_from_dict, object_to_dict
from ..faults.plan import maybe_crash
from ..utils.lockorder import assert_held, guard_attrs, make_lock
from .store import Event, EventType, Store

logger = logging.getLogger(__name__)

# replay creation order: namespaced objects need their namespaces first
_KIND_ORDER = {"Namespace": 0, "Throttle": 1, "ClusterThrottle": 1, "Pod": 2}

# every line type this reader understands: the three watch-event types
# (store.EventType) plus the journal's own control lines. The format
# registry (version.FORMAT_REGISTRY, ``journal:*`` rows) is the durable
# contract these map to — the protocol checker (analysis/protocol.py)
# cross-checks that every control type emitted here has a registry row.
_KNOWN_LINE_TYPES = frozenset(
    {"ADDED", "MODIFIED", "DELETED", "EPOCH", "GANG", "PREEMPT"}
)


class JournalFormatError(Exception):
    """An UNKNOWN-BUT-VERSIONED control line: an uppercase ``type`` this
    reader does not recognise and that carries no ``object`` payload —
    the shape a NEWER writer's control line takes (rolling-upgrade skew:
    the journal was written by a later build). Unlike torn/corrupt lines
    (skip-and-count — losing one event beats losing the post-gap
    history), this is a *format* boundary: silently skipping a control
    line whose semantics we do not know (a future fencing or rollback
    bracket, say) risks replaying into a state the writer never meant,
    so replay STOPS deterministically, the refusal is counted and named
    (``format_refused`` / ``format_refused_reason``), and the health
    probe reports ``down`` until a reader of at least the line's
    ``minReader`` version replays it."""


def hash_prefix(path: str, length: int):
    """sha256 object over the first ``length`` bytes of ``path``, or None
    when the file is missing or shorter than ``length`` (the prefix a
    snapshot recorded no longer exists as-is). Recovery compares its
    hexdigest against the snapshot's recorded journal hash, and on a match
    seeds :func:`attach`'s ``resume_hash`` with the returned object so the
    running hash stays continuous across the tail replay."""
    if length < 0 or not os.path.exists(path):
        return None
    h = hashlib.sha256()
    remaining = length
    with open(path, "rb") as f:
        while remaining > 0:
            chunk = f.read(min(1 << 20, remaining))
            if not chunk:
                return None  # file shorter than the recorded offset
            h.update(chunk)
            remaining -= len(chunk)
    return h


@guard_attrs
class StoreJournal:
    """Attach with :func:`attach`; detach via :meth:`close`."""

    # the live-append file handle, its line counter, and the running
    # content position move only under the journal lock (the robustness
    # counters are single-writer ints read by health probes — unguarded on
    # purpose)
    GUARDED_BY = {
        "_file": "self._lock",
        "_lines": "self._lock",
        "_bytes": "self._lock",
        "_sha": "self._lock",
        "_snapshotter": "self._lock",
        "snapshot_every": "self._lock",
        "_lines_since_snapshot": "self._lock",
    }

    def __init__(
        self, store: Store, path: str, compact_after: int = 100_000, faults=None
    ):
        self.store = store
        self.path = path
        self.compact_after = compact_after
        self.faults = faults
        self._lock = make_lock("journal")
        # HA fencing (engine/replication.py): when a FencingEpoch is bound
        # and marked stale (leadership lost), every append is refused and
        # counted — a paused-then-resumed old leader cannot extend a log a
        # promoted standby no longer follows. ``last_epoch`` is the highest
        # EPOCH control line seen (replay or set_epoch); single-writer,
        # read by probes/recovery (same stance as the counters below).
        self.fencing = None
        self.last_epoch = 0
        # GANG control lines (engine/gang.py): group_key → {"op": last op
        # seen ("begin"|"commit"|"rollback"), "members": [...]} — recovery
        # reads begin-without-commit as a mid-reserve crash and rolls the
        # group's member reservations back (GangLedger.rollback_uncommitted).
        # Single-writer under the journal lock, read after replay.
        self.gang_ops: dict = {}
        # PREEMPT control lines (policy/preempt.py): preempt_id → {"op":
        # last op seen ("begin"|"commit"|"rollback"), "victims": [...],
        # "objects": [...serialized victim pods...]}. A begin-without-
        # commit tail is a mid-eviction crash: recovery re-creates the
        # victims from the begin line's objects — an uncommitted
        # preemption rolls back to ZERO evictions (the GANG contract's
        # mirror, but over store state, so the rollback payload must ride
        # the journal). Single-writer under the journal lock.
        self.preempt_ops: dict = {}
        self._lines = 0
        self._file = None
        # running position of the journal content: byte length + sha256 of
        # everything up to it (seeded by attach() from the replay)
        self._bytes = 0
        self._sha = hashlib.sha256()
        # journal-size snapshot trigger (engine/snapshot.py binds these)
        self._snapshotter = None
        self.snapshot_every = 0
        self._lines_since_snapshot = 0
        # group-commit durability knob: True adds ONE fsync per batched
        # write (still "at most one fsync per batch"); False (default)
        # keeps the append path's flush-only durability, same as the
        # single-event path (fsync happens at compaction/close)
        self.fsync_batches = False
        # robustness counters (health probe + tests read these)
        self.replay_skipped = 0  # corrupted interior lines skipped on replay
        self.torn_tails = 0  # torn final lines truncated (normal crash artifact)
        self.write_errors = 0  # appends dropped (injected/real write failure)
        self.torn_writes = 0  # injected torn appends
        self.compact_failures = 0  # compactions aborted (old log kept)
        self.replayed_events = 0  # events applied by the last replay
        self.stale_epoch_rejected = 0  # appends refused by the fencing gate
        self.preempts_rolled_back = 0  # uncommitted preemptions rolled back
        self.preempt_victims_restored = 0  # victim pods re-created by rollback
        # rolling-upgrade format refusal (JournalFormatError): replay hit a
        # control line from a newer writer and stopped. Single-writer,
        # read by the health probe — the reason names the line type and
        # the minimum reader version it demands.
        self.format_refused = 0
        self.format_refused_reason: Optional[str] = None

    # -- replay -------------------------------------------------------------

    def _replay(
        self, start_offset: int = 0, resume_hash=None
    ) -> Tuple[int, Optional[int], int, "hashlib._Hash"]:
        """Apply journaled events (from ``start_offset``) to the store.
        Returns ``(applied, truncate_at, end_bytes, end_sha)``: the event
        count, the byte offset to truncate at when the file ends in a
        corrupt run (else None), and the byte length + sha256 of the
        journal content that remains valid. The caller MUST truncate before
        appending: appending past a corrupt tail would strand every later
        event behind the gap on all future replays (silent loss of
        post-crash history).

        Corruption classification:

        - a bad line with ANY write after it — a later line, or even just
          its own terminating newline — cannot be the crash-mid-write
          artifact: it is real corruption, skipped and counted in
          ``replay_skipped`` with its line number. Aborting would trade
          one lost event for the whole post-gap history, so it stays in
          the file (and is re-counted on every replay until a compaction
          heals the log).
        - only a FINAL line with no terminating newline is the legal
          crash-mid-write artifact: truncated silently, counted in
          ``torn_tails`` (health stays ok)."""
        h = resume_hash.copy() if resume_hash is not None else hashlib.sha256()
        if not os.path.exists(self.path):
            return 0, None, start_offset, h
        applied = 0
        offset = start_offset  # byte offset after the current line
        last_line_start = start_offset
        h_before_last = h.copy()  # content hash up to last_line_start
        last_newline = True
        bad_run: list = []  # (lineno, error) since the last good line
        with open(self.path, "rb") as f:
            f.seek(start_offset)
            for lineno, raw in enumerate(f, 1):
                last_line_start = offset
                h_before_last = h.copy()
                offset += len(raw)
                h.update(raw)
                last_newline = raw.endswith(b"\n")
                line = raw.strip()
                if not line:
                    continue  # blank line: harmless, neither good nor bad
                try:
                    event = json.loads(line.decode("utf-8"))
                    self._apply(event)
                except JournalFormatError as e:
                    # a control line from a NEWER writer: refuse replay
                    # deterministically — count, name the version demand,
                    # and STOP (skip-and-continue here could replay into a
                    # state the writer never meant). Pending bad lines are
                    # still counted so the probe's detail stays honest. The
                    # remainder of the file is hashed (not applied) so the
                    # accounted position stays consistent with the bytes on
                    # disk; health_state reports down until a new-enough
                    # reader replays the log.
                    for bad_lineno, err in bad_run:
                        self.replay_skipped += 1
                        logger.warning(
                            "journal %s: skipping corrupted line %d (%s)",
                            self.path, bad_lineno, err,
                        )
                    self.format_refused += 1
                    self.format_refused_reason = str(e)
                    logger.error(
                        "journal %s: replay REFUSED at line %d: %s",
                        self.path, lineno, e,
                    )
                    rest = f.read()
                    h.update(rest)
                    offset += len(rest)
                    return applied, None, offset, h
                except (
                    json.JSONDecodeError,
                    KeyError,
                    ValueError,
                    UnicodeDecodeError,
                ) as e:
                    bad_run.append((lineno, str(e)))
                    continue
                applied += 1
                # bad lines BEFORE a good line are interior corruption:
                # skip-and-count, never truncate (that would delete the
                # good history that follows)
                for bad_lineno, err in bad_run:
                    self.replay_skipped += 1
                    logger.warning(
                        "journal %s: skipping corrupted line %d (%s)",
                        self.path, bad_lineno, err,
                    )
                bad_run = []
        if bad_run and not last_newline:
            # the torn final line (no newline = the write never finished):
            # truncate it alone, silently. Bad lines ahead of it in the
            # run are newline-terminated — genuine corruption, counted,
            # and left in place like interior corruption.
            for bad_lineno, err in bad_run[:-1]:
                self.replay_skipped += 1
                logger.warning(
                    "journal %s: skipping corrupted line %d (%s)",
                    self.path, bad_lineno, err,
                )
            self.torn_tails += 1
            logger.debug(
                "journal %s: truncating torn final line %d (%s) — normal "
                "crash artifact",
                self.path, bad_run[-1][0], bad_run[-1][1],
            )
            return applied, last_line_start, last_line_start, h_before_last
        for bad_lineno, err in bad_run:
            # trailing but newline-terminated: a write landed after the
            # corruption, so this is interior-class corruption that merely
            # has no good line after it YET
            self.replay_skipped += 1
            logger.warning(
                "journal %s: skipping corrupted line %d (%s)",
                self.path, bad_lineno, err,
            )
        return applied, None, offset, h

    def _apply(self, event: dict) -> None:
        etype = event["type"]
        if etype == "EPOCH":
            # fencing control line (engine/replication.py): records the
            # leadership term under which the following events were
            # written — no store effect, but recovery/promotion read the
            # high-water term from it
            self.last_epoch = max(self.last_epoch, int(event.get("epoch", 0)))
            return
        if etype == "GANG":
            # gang control line (engine/gang.py): group reserve/rollback
            # audit stamp — no store effect; last op per group wins
            group = str(event.get("group", ""))
            if group:
                entry = {"op": str(event.get("op", ""))}
                members = event.get("members")
                if members is not None:
                    entry["members"] = [str(m) for m in members]
                elif group in self.gang_ops and "members" in self.gang_ops[group]:
                    entry["members"] = self.gang_ops[group]["members"]
                self.gang_ops[group] = entry
            return
        if etype == "PREEMPT":
            # preemption control line (policy/preempt.py): victim-eviction
            # bracket — no store effect on replay; last op per id wins.
            # The begin line carries the serialized victims so recovery
            # can restore an uncommitted eviction to zero victims
            # (rollback_uncommitted_preempts).
            pid = str(event.get("id", ""))
            if pid:
                entry = {"op": str(event.get("op", ""))}
                prev = self.preempt_ops.get(pid) or {}
                for field in ("victims", "victimObjects"):
                    if event.get(field) is not None:
                        entry[field] = list(event[field])
                    elif field in prev:
                        entry[field] = prev[field]
                self.preempt_ops[pid] = entry
            return
        if (
            isinstance(etype, str)
            and etype.isupper()
            and etype not in _KNOWN_LINE_TYPES
            and "object" not in event
        ):
            # uppercase type, no object payload: the control-line shape,
            # but a type this reader does not know — a newer writer's
            # line, not bit rot. Refuse by name (the line may carry its
            # own ``minReader`` stamp; otherwise the demand is unknown).
            from ..version import local_proto_version

            need = event.get("minReader", "unknown")
            ours = "%d.%d" % local_proto_version()
            raise JournalFormatError(
                f"unknown control line type {etype!r} requires reader "
                f">= {need} (this reader speaks {ours}); refusing replay"
            )
        kind = event["kind"]
        obj = object_from_dict({**event["object"], "kind": kind})
        store = self.store
        if etype == "DELETED":
            try:
                if kind == "Pod":
                    store.delete_pod(obj.namespace, obj.name)
                elif kind == "Namespace":
                    store.delete_namespace(obj.name)
                elif kind == "Throttle":
                    store.delete_throttle(obj.namespace, obj.name)
                else:
                    store.delete_cluster_throttle(obj.name)
            except KeyError:
                pass
            return
        # ADDED/MODIFIED → upsert (replay must be idempotent-ish: a
        # compacted snapshot starts from ADDED lines)
        try:
            if kind == "Pod":
                store.create_pod(obj)
            elif kind == "Namespace":
                store.create_namespace(obj)
            elif kind == "Throttle":
                store.create_throttle(obj)
            else:
                store.create_cluster_throttle(obj)
        except ValueError:
            if kind == "Pod":
                store.update_pod(obj)
            elif kind == "Namespace":
                store.update_namespace(obj)
            elif kind == "Throttle":
                store.update_throttle(obj)
            else:
                store.update_cluster_throttle(obj)

    # -- live append ----------------------------------------------------------

    @staticmethod
    def _encode(event: Event) -> str:
        return json.dumps(
            {
                "type": event.type.value,
                "kind": event.kind,
                "object": object_to_dict(event.obj),
            }
        )

    def on_batch(self, events) -> None:
        """GROUP COMMIT (store batch-listener hook): journal a whole ingest
        batch as ONE buffered write + one flush (+ at most one fsync when
        ``fsync_batches`` is set) instead of a write+flush syscall pair per
        event.

        Crash contract (tools/crashtest.py site ``crash.journal.group_commit``):
        the batch's lines are concatenated in event order and handed to the
        file in one write, so a crash anywhere inside the commit leaves a
        strict PREFIX of the batch on disk — every complete line replays,
        and only the FINAL surviving record can be torn (truncated by
        recovery as the normal torn-tail artifact). No interior corruption
        is possible because nothing is appended after the cut.

        Per-line fault modes (``journal.append`` torn/error and the
        per-append ``crash.journal.*`` kill sites) keep their single-event
        meaning inside a batch: the buffer accumulated so far is flushed
        before a kill fires, so the on-disk artifact matches the
        event-by-event timeline."""
        # HA kill site: the whole batch mutated the store, but none of its
        # lines exist yet — the entire batch is unjournaled AND
        # unreplicated (tools/hatest.py asserts the standby promotes from
        # the surviving prefix with zero divergence)
        maybe_crash(self.faults, "ha.journal.batch")
        pieces: list = []
        lines_added = 0
        snapshotter = None
        with self._lock:
            if self._file is None:
                return
            if self.fencing is not None and self.fencing.is_stale():
                # fenced: a stale leader's batch must not extend the log
                self.stale_epoch_rejected += len(events)
                return
            for event in events:
                line = self._encode(event)
                fault = (
                    self.faults.check("journal.append")
                    if self.faults is not None
                    else None
                )
                if self.faults is not None:
                    crash = self.faults.check("crash.journal.append")
                    if crash is not None and crash.mode == "kill":
                        # die BEFORE this event's line exists — earlier batch
                        # lines already reached the store, so they reach the
                        # file first (the per-event timeline's artifact)
                        self._write_pieces_locked(pieces)
                        crash.kill()
                    crash_torn = self.faults.check("crash.journal.torn")
                    if crash_torn is not None and crash_torn.mode == "kill":
                        pieces.append(line[: max(1, len(line) // 2)])
                        self._write_pieces_locked(pieces)
                        crash_torn.kill()
                if fault is not None and fault.mode == "error":
                    self.write_errors += 1
                    continue
                if fault is not None and fault.mode == "torn":
                    # half the line, no newline: the NEXT buffered line
                    # concatenates onto it — one corrupt interior line,
                    # exactly the single-event torn artifact
                    pieces.append(line[: max(1, len(line) // 2)])
                    self.torn_writes += 1
                    lines_added += 1
                    continue
                pieces.append(line + "\n")
                lines_added += 1
            if pieces:
                crash_gc = (
                    self.faults.check("crash.journal.group_commit")
                    if self.faults is not None
                    else None
                )
                if crash_gc is not None and crash_gc.mode == "kill":
                    # die MID-COMMIT: half the batch buffer reaches the file
                    # (cutting through a line), then SIGKILL — recovery must
                    # see a clean prefix with one torn tail, zero divergence
                    data = "".join(pieces)
                    self._file.write(data[: max(1, len(data) // 2)])
                    self._file.flush()
                    crash_gc.kill()
                self._write_pieces_locked(pieces)
                if self.fsync_batches:
                    try:
                        os.fsync(self._file.fileno())
                    except OSError:  # pragma: no cover — fsync race on close
                        pass
            self._lines += lines_added
            compacted = False
            if self._lines >= self.compact_after:
                try:
                    self._compact_locked()
                    compacted = True
                except OSError:
                    self.compact_failures += 1
                    self._lines = 0
                    logger.warning(
                        "journal %s: compaction failed; keeping the "
                        "uncompacted log and retrying later",
                        self.path, exc_info=True,
                    )
            if self._snapshotter is not None:
                if self.snapshot_every > 0:
                    self._lines_since_snapshot += lines_added
                # a compaction invalidates every snapshot's journal anchor:
                # cut a fresh one regardless of the line budget so standby
                # bootstraps always find a resolvable anchor
                if compacted or (
                    self.snapshot_every > 0
                    and self._lines_since_snapshot >= self.snapshot_every
                ):
                    self._lines_since_snapshot = 0
                    snapshotter = self._snapshotter
        if snapshotter is not None:
            # outside the journal lock, inside the store's batch dispatch —
            # same placement as the single-event trigger
            snapshotter.snapshot_on_journal_trigger()

    def _write_pieces_locked(self, pieces) -> None:
        """One buffered write + flush of the accumulated batch lines, with
        the running (bytes, sha256) position advanced to match. Caller
        holds the journal lock."""
        assert_held(self._lock, "StoreJournal._write_pieces_locked")
        if not pieces:
            return
        data = "".join(pieces)
        raw = data.encode("utf-8")
        self._file.write(data)
        self._file.flush()
        self._sha.update(raw)
        self._bytes += len(raw)
        del pieces[:]

    def _on_event(self, event: Event) -> None:
        if self.store.in_batch_dispatch:
            return  # already group-committed by on_batch
        line = self._encode(event)
        fault = self.faults.check("journal.append") if self.faults is not None else None
        # crash points OUTSIDE the lock (SIGKILL never returns, but keeping
        # lock holds minimal keeps the site placement honest): before the
        # line hits the file at all, and the torn-then-die artifact
        maybe_crash(self.faults, "crash.journal.append")
        if event.kind in ("Throttle", "ClusterThrottle") and event.type is EventType.MODIFIED:
            # HA kill site: a status write (possibly a FLIP) reached the
            # store but its journal line never lands — the standby must
            # re-derive the flip from replicated pod/spec truth
            maybe_crash(self.faults, "ha.status.commit")
        crash_torn = (
            self.faults.check("crash.journal.torn")
            if self.faults is not None
            else None
        )
        snapshotter = None
        with self._lock:
            if self._file is None:
                return
            if self.fencing is not None and self.fencing.is_stale():
                self.stale_epoch_rejected += 1
                return
            if crash_torn is not None and crash_torn.mode == "kill":
                # the canonical crash-mid-write artifact: half the line,
                # no newline, then the process dies. Recovery must treat
                # this as a normal torn tail (truncate, stay healthy).
                self._file.write(line[: max(1, len(line) // 2)])
                self._file.flush()
                crash_torn.kill()
            if fault is not None and fault.mode == "error":
                # simulated failed write: the event never reaches the log
                # (the gap is what replay-convergence soaks must tolerate)
                self.write_errors += 1
                return
            if fault is not None and fault.mode == "torn":
                # half the line, no newline: the NEXT append concatenates
                # onto the fragment, producing one corrupt interior line —
                # the exact artifact a crash between write() and the
                # newline leaves behind
                frag = line[: max(1, len(line) // 2)].encode("utf-8")
                self._file.write(frag.decode("utf-8"))
                self._file.flush()
                self._sha.update(frag)
                self._bytes += len(frag)
                self.torn_writes += 1
                self._lines += 1
                return
            data = (line + "\n").encode("utf-8")
            self._file.write(line + "\n")
            self._file.flush()
            self._sha.update(data)
            self._bytes += len(data)
            self._lines += 1
            compacted = False
            if self._lines >= self.compact_after:
                try:
                    self._compact_locked()
                    compacted = True
                except OSError:
                    # a failed compaction (disk full, fsync error) must not
                    # propagate into the store's dispatch — the old log is
                    # intact and still growing; retry a full window later
                    self.compact_failures += 1
                    self._lines = 0
                    logger.warning(
                        "journal %s: compaction failed; keeping the "
                        "uncompacted log and retrying later",
                        self.path, exc_info=True,
                    )
            if self._snapshotter is not None:
                if self.snapshot_every > 0:
                    self._lines_since_snapshot += 1
                # see on_batch: a compaction must be followed by a fresh
                # snapshot or every bootstrap anchor dangles
                if compacted or (
                    self.snapshot_every > 0
                    and self._lines_since_snapshot >= self.snapshot_every
                ):
                    self._lines_since_snapshot = 0
                    snapshotter = self._snapshotter
        if snapshotter is not None:
            # journal-size snapshot trigger, OUTSIDE the journal lock (the
            # snapshot writer re-reads the journal position itself). We are
            # still inside the store's dispatch, so the store lock is held
            # (reentrant) and the cut is consistent with the event stream.
            snapshotter.snapshot_on_journal_trigger()

    def _compact_locked(self) -> None:
        """Rewrite the journal as a snapshot of the CURRENT store contents
        (ADDED lines, namespaces first), atomically. Caller holds the
        journal lock (asserted under KT_LOCK_ASSERT=1)."""
        assert_held(self._lock, "StoreJournal._compact_locked")
        epoch = self.last_epoch
        if self.fencing is not None:
            epoch = max(epoch, self.fencing.current())
        objs = []
        for ns in self.store.list_namespaces():
            objs.append(("Namespace", ns))
        for thr in self.store.list_throttles():
            objs.append(("Throttle", thr))
        for thr in self.store.list_cluster_throttles():
            objs.append(("ClusterThrottle", thr))
        for pod in self.store.list_pods():
            objs.append(("Pod", pod))
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".journal"
        )
        new_sha = hashlib.sha256()
        new_bytes = 0
        lines = len(objs)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                if epoch > 0:
                    # compaction must not erase the fencing high-water: a
                    # genesis replay of the compacted log still learns the
                    # leadership term the objects were written under
                    data = (
                        json.dumps({"type": "EPOCH", "epoch": epoch}) + "\n"
                    ).encode("utf-8")
                    f.write(data.decode("utf-8"))
                    new_sha.update(data)
                    new_bytes += len(data)
                    lines += 1
                for group, entry in sorted(self.gang_ops.items()):
                    # compaction must not erase an in-flight gang reserve
                    # either (protocol checker: control types survive the
                    # re-emit): a begin-without-commit tail is how recovery
                    # learns a mid-reserve crash needs rolling back —
                    # committed/rolled-back groups carry no future meaning
                    # and are dropped with the rest of the history
                    if entry.get("op") != "begin":
                        continue
                    record = {"type": "GANG", "op": "begin", "group": group}
                    if "members" in entry:
                        record["members"] = list(entry["members"])
                    data = (json.dumps(record) + "\n").encode("utf-8")
                    f.write(data.decode("utf-8"))
                    new_sha.update(data)
                    new_bytes += len(data)
                    lines += 1
                for pid, entry in sorted(self.preempt_ops.items()):
                    # in-flight preemptions survive compaction the same way
                    # (protocol checker: control types survive the
                    # re-emit): a begin-without-commit marker — WITH its
                    # victim payload — is how recovery learns a mid-
                    # eviction crash must restore the victims; finished
                    # preemptions carry no future meaning and drop
                    if entry.get("op") != "begin":
                        continue
                    record = {"type": "PREEMPT", "op": "begin", "id": pid}
                    for field in ("victims", "victimObjects"):
                        if field in entry:
                            record[field] = list(entry[field])
                    data = (json.dumps(record) + "\n").encode("utf-8")
                    f.write(data.decode("utf-8"))
                    new_sha.update(data)
                    new_bytes += len(data)
                    lines += 1
                for kind, obj in objs:
                    data = (
                        json.dumps(
                            {"type": "ADDED", "kind": kind, "object": object_to_dict(obj)}
                        )
                        + "\n"
                    ).encode("utf-8")
                    f.write(data.decode("utf-8"))
                    new_sha.update(data)
                    new_bytes += len(data)
                f.flush()
                if self.faults is not None:
                    self.faults.maybe_raise(
                        "journal.fsync",
                        default=lambda: OSError("injected fsync failure"),
                    )
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        # the instant a crash invalidates every snapshot's recorded journal
        # offset (recovery must fall back to genesis replay of this file)
        maybe_crash(self.faults, "crash.journal.compact")
        self._file.close()
        self._file = open(self.path, "a", encoding="utf-8")
        self._lines = lines
        self._sha = new_sha
        self._bytes = new_bytes
        logger.info("journal %s compacted to %d objects", self.path, len(objs))

    def compact(self) -> None:
        """Force a compaction now (operational hook + the chaos soak's
        heal-the-log step): the journal becomes a clean snapshot of the
        live store, erasing any torn/corrupt interior lines."""
        if self.fencing is not None and self.fencing.is_stale():
            # a fenced (stale) leader rewriting its journal is still a
            # durable write after leadership loss — refuse, like every
            # other guarded write path (protocol checker)
            self.stale_epoch_rejected += 1
            logger.warning("journal %s: compaction refused (fenced)", self.path)
            return
        # store lock FIRST — the same order as the dispatch path
        # (store._dispatch_locked -> _on_event -> journal lock). Taking
        # only the journal lock here and letting _compact_locked's
        # store.list_* acquire the store lock underneath was an ABBA
        # inversion against concurrent writers (found by KT_LOCK_ASSERT),
        # and it could also lose a concurrent event: one appended to the
        # old file after the snapshot was cut would vanish at rotation.
        with self.store._lock:  # noqa: SLF001 — same-package access
            snapshotter = None
            with self._lock:
                if self._file is not None:
                    self._compact_locked()
                    snapshotter = self._snapshotter
            if snapshotter is not None:
                # the rewrite invalidated every snapshot's journal anchor;
                # cut a fresh one (journal lock released, store lock held —
                # the same stance as the dispatch-path trigger)
                snapshotter.snapshot_on_journal_trigger()

    # -- position / snapshot trigger ---------------------------------------

    def position(self) -> Tuple[int, str]:
        """``(byte offset, sha256 hexdigest)`` of the journal content up to
        now — the tail-replay anchor a snapshot records at cut time."""
        with self._lock:
            return self._bytes, self._sha.hexdigest()

    def append_gang(self, op: str, group_key: str, members=None) -> None:
        """Append a GANG control line (engine/gang.py): ``op`` is
        ``begin`` / ``commit`` / ``rollback``. No store effect; replays
        into :attr:`gang_ops` so recovery can treat a begin-without-commit
        tail as a mid-reserve crash. Stamps are advisory audit/defense
        lines — the all-or-nothing invariant itself is held by the gang
        lock around snapshot gathers (GangLedger) — so a fenced or closed
        journal silently drops them like any other refused append."""
        record = {"type": "GANG", "op": str(op), "group": str(group_key)}
        if members is not None:
            record["members"] = list(members)
        with self._lock:
            entry = {"op": str(op)}
            if members is not None:
                entry["members"] = list(members)
            elif group_key in self.gang_ops and "members" in self.gang_ops[group_key]:
                entry["members"] = self.gang_ops[group_key]["members"]
            self.gang_ops[str(group_key)] = entry
            if self._file is None:
                return
            if self.fencing is not None and self.fencing.is_stale():
                self.stale_epoch_rejected += 1
                return
            data = (json.dumps(record) + "\n").encode("utf-8")
            self._file.write(data.decode("utf-8"))
            self._file.flush()
            self._sha.update(data)
            self._bytes += len(data)
            self._lines += 1

    def append_preempt(
        self, op: str, preempt_id: str, victims=None, objects=None
    ) -> None:
        """Append a PREEMPT control line (policy/preempt.py): ``op`` is
        ``begin`` / ``commit`` / ``rollback``. The begin line carries the
        victim keys AND their serialized objects — unlike GANG stamps
        (advisory; the invariant is lock-held), this payload IS the crash
        contract: a begin-without-commit tail tells recovery to re-create
        exactly these objects (``rollback_uncommitted_preempts``), rolling
        an interrupted eviction back to zero victims. No store effect on
        replay; a fenced or closed journal drops the stamp like any other
        refused append (the eviction then has no rollback guarantee, but a
        fenced replica must not evict at all — the scheduler is gone)."""
        record = {"type": "PREEMPT", "op": str(op), "id": str(preempt_id)}
        if victims is not None:
            record["victims"] = list(victims)
        if objects is not None:
            record["victimObjects"] = list(objects)
        with self._lock:
            entry = {"op": str(op)}
            prev = self.preempt_ops.get(str(preempt_id)) or {}
            for field in ("victims", "victimObjects"):
                if record.get(field) is not None:
                    entry[field] = list(record[field])
                elif field in prev:
                    entry[field] = prev[field]
            self.preempt_ops[str(preempt_id)] = entry
            if self._file is None:
                return
            if self.fencing is not None and self.fencing.is_stale():
                self.stale_epoch_rejected += 1
                return
            data = (json.dumps(record) + "\n").encode("utf-8")
            self._file.write(data.decode("utf-8"))
            self._file.flush()
            self._sha.update(data)
            self._bytes += len(data)
            self._lines += 1

    def open_preempts(self) -> dict:
        """Begin-without-commit preemptions (id → entry with victims +
        objects), read under the journal lock — the snapshot payload
        carries these so a tail-mode recovery whose anchor sits PAST the
        begin line still knows which eviction to roll back."""
        with self._lock:
            return {
                pid: {k: (list(v) if isinstance(v, list) else v) for k, v in e.items()}
                for pid, e in self.preempt_ops.items()
                if e.get("op") == "begin"
            }

    def set_epoch(self, epoch: int) -> None:
        """Append a fencing EPOCH control line (engine/replication.py):
        stamps the leadership term into the event stream so replay,
        recovery, and streaming standbys all learn the high-water term
        from the journal alone. No store effect; replays as a no-op."""
        epoch = int(epoch)
        with self._lock:
            if epoch <= self.last_epoch:
                return  # terms only move forward; duplicates add no info
            self.last_epoch = epoch
            if self._file is None:
                return
            if self.fencing is not None and self.fencing.is_stale():
                # a fenced journal must not extend the log with ANY line,
                # control lines included (protocol checker: every durable
                # write dominated by a fencing check)
                self.stale_epoch_rejected += 1
                return
            data = (json.dumps({"type": "EPOCH", "epoch": epoch}) + "\n").encode(
                "utf-8"
            )
            self._file.write(data.decode("utf-8"))
            self._file.flush()
            self._sha.update(data)
            self._bytes += len(data)
            self._lines += 1

    def replication_chunk(
        self, start_offset: int, max_bytes: int = 4 << 20,
        want_start_sha: bool = False,
    ) -> Optional[Tuple[bytes, int, str, int, Optional[str]]]:
        """Tail bytes for a streaming standby: ``(data, end_offset,
        end_sha_hex, position, start_sha_hex)`` covering ``[start_offset,
        min(position, start_offset+max_bytes))``. Serving only up to the
        ACCOUNTED position (never the raw file end) guarantees complete
        lines — a torn crash artifact past the position is never shipped.
        Returns None when ``start_offset`` lies beyond the position (the
        journal was compacted/rewritten under the standby).
        ``start_sha_hex`` (the prefix hash at ``start_offset``, for the
        source's continuity verification) is None unless
        ``want_start_sha``. Everything — position, bytes, and both prefix
        hashes — is read under the journal lock so a concurrent compaction
        cannot swap the file between any two of the reads."""
        with self._lock:
            position = self._bytes
            if start_offset > position:
                return None
            start_sha: Optional[str] = None
            if want_start_sha:
                if start_offset == position:
                    start_sha = self._sha.hexdigest()
                else:
                    h = hash_prefix(self.path, start_offset)
                    if h is None:
                        return None
                    start_sha = h.hexdigest()
            end = min(position, start_offset + max_bytes)
            if start_offset == end:
                return b"", position, self._sha.hexdigest(), position, start_sha
            if not os.path.exists(self.path):
                return None
            with open(self.path, "rb") as f:
                f.seek(start_offset)
                data = f.read(end - start_offset)
            if len(data) != end - start_offset:
                return None  # file shorter than accounted (rewritten)
            if end == position:
                end_sha = self._sha.hexdigest()
            else:
                h = hash_prefix(self.path, end)
                if h is None:
                    return None
                end_sha = h.hexdigest()
            return data, end, end_sha, position, start_sha

    def set_snapshotter(self, snapshotter, every_lines: int) -> None:
        """Arm the journal-size snapshot trigger: every ``every_lines``
        appended lines, ``snapshotter.snapshot_on_journal_trigger()`` runs
        (outside the journal lock, inside the store's dispatch)."""
        with self._lock:
            self._snapshotter = snapshotter
            self.snapshot_every = int(every_lines)
            self._lines_since_snapshot = 0

    def health_state(self) -> Tuple[str, dict]:
        """Health-component contract (health.py): degraded while any
        corruption/write-loss counter is nonzero — the journal still works,
        but an operator should know recovery was lossy. A truncated torn
        FINAL line (``torn_tails``) is the normal crash artifact and does
        NOT degrade; it is surfaced in the detail only."""
        detail = {
            "replaySkipped": self.replay_skipped,
            "tornTails": self.torn_tails,
            "writeErrors": self.write_errors,
            "compactFailures": self.compact_failures,
            "staleEpochRejected": self.stale_epoch_rejected,
            "epoch": self.last_epoch,
            "formatRefused": self.format_refused,
        }
        if self.format_refused:
            # replay stopped at a newer writer's control line: the store
            # behind this journal is an incomplete prefix — serving from
            # it would hand out verdicts the missing tail may contradict.
            # Down, with the version demand named for the operator.
            detail["formatRefusedReason"] = self.format_refused_reason
            return "down", detail
        if self.stale_epoch_rejected:
            # a fenced journal is not merely lossy — this replica must not
            # serve at all (a standby owns the keyspace now)
            return "down", detail
        degraded = self.replay_skipped or self.write_errors or self.compact_failures
        return ("degraded" if degraded else "ok"), detail

    def close(self) -> None:
        for kind in Store.KINDS:
            self.store.remove_event_handler(kind, self._on_event)
        self.store.remove_batch_listener(self)
        with self._lock:
            if self._file is not None:
                self._file.flush()
                try:
                    # graceful shutdown fsyncs the log: a clean SIGTERM exit
                    # must leave nothing in OS buffers a power cut could eat
                    os.fsync(self._file.fileno())
                except OSError:  # pragma: no cover — fsync of a closed fd race
                    pass
                self._file.close()
                self._file = None


def rollback_uncommitted_preempts(
    store: Store, journal: StoreJournal, extra_ops: Optional[dict] = None
) -> Tuple[int, int]:
    """Roll every begin-without-commit preemption back to ZERO evictions:
    re-create each victim whose DELETED line landed (from the begin
    line's serialized objects) and stamp ``rollback``. The creates run
    through the live store, so they re-journal as ADDED lines and the log
    stays self-reproducing. ``extra_ops`` merges snapshot-carried open
    preemptions under the journal's own (the journal wins per id — it is
    strictly newer). Returns ``(preempts rolled back, victims
    restored)``; also accumulated on the journal's counters for recovery
    reports. Idempotent: a rolled-back id's last op is ``rollback`` and
    is skipped on any later pass."""
    ops = dict(extra_ops or {})
    ops.update(journal.preempt_ops)
    rolled = restored = 0
    for pid in sorted(ops):
        entry = ops[pid]
        if entry.get("op") != "begin":
            continue
        for d in entry.get("victimObjects") or []:
            try:
                obj = object_from_dict({**d, "kind": "Pod"})
                store.create_pod(obj)
                restored += 1
            except ValueError:
                pass  # still present: its DELETED never reached the log
        journal.append_preempt("rollback", pid)
        rolled += 1
        logger.warning(
            "journal %s: preemption %s crashed mid-eviction; rolled back "
            "to zero evictions", journal.path, pid,
        )
    journal.preempts_rolled_back += rolled
    journal.preempt_victims_restored += restored
    return rolled, restored


def attach(
    store: Store,
    path: str,
    compact_after: int = 100_000,
    faults=None,
    start_offset: int = 0,
    resume_hash=None,
) -> StoreJournal:
    """Replay ``path`` into the store, then journal every subsequent event
    to it. Must run BEFORE other handlers are registered so replayed events
    don't double-dispatch.

    ``start_offset``/``resume_hash`` are the tail-replay form recovery uses
    after restoring a snapshot: replay only the bytes past ``start_offset``
    (the caller has verified, via :func:`hash_prefix`, that the prefix
    matches the snapshot's recorded hash, and hands the prefix hash object
    over so the running content hash stays continuous)."""
    journal = StoreJournal(store, path, compact_after=compact_after, faults=faults)
    n, truncate_at, end_bytes, end_sha = journal._replay(
        start_offset=start_offset, resume_hash=resume_hash
    )
    journal.replayed_events = n
    if n:
        logger.info(
            "journal %s: replayed %d events (%d corrupted line(s) skipped)",
            path, n, journal.replay_skipped,
        )
    if truncate_at is not None:
        with open(path, "r+b") as f:
            f.truncate(truncate_at)
    elif end_bytes > start_offset and os.path.exists(path):
        # a final line that PARSED but lacks its newline (crash after the
        # payload byte, before the terminator): keep the event, repair the
        # terminator — otherwise the next append would concatenate onto it
        # and corrupt both
        with open(path, "rb") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                with open(path, "ab") as g:
                    g.write(b"\n")
                end_sha.update(b"\n")
                end_bytes += 1
    # under the lock although pre-publication: these are declared guarded,
    # and the runtime guard (KT_LOCK_ASSERT=1) checks rebinds
    with journal._lock:
        journal._file = open(path, "a", encoding="utf-8")
        journal._lines = n
        journal._bytes = end_bytes
        journal._sha = end_sha
    for kind in Store.KINDS:
        store.add_event_handler(kind, journal._on_event, replay=False)
    # batched mutations (micro-batched ingest, batched status drains) group-
    # commit through on_batch; the per-event handler skips those dispatches
    store.add_batch_listener(journal)
    if start_offset == 0:
        # full replay ⇒ preempt_ops is complete: roll uncommitted
        # preemptions back to zero evictions here, so EVERY full-replay
        # consumer (genesis recovery, the crash harness's pure-replay
        # oracle, a restarting standby) lands on the same contract without
        # separate wiring. Tail replays defer to RecoveryManager.
        # restore_preemptions, which merges the snapshot's open-preempt
        # payload (the begin line may predate the anchor).
        rollback_uncommitted_preempts(store, journal)
    return journal
