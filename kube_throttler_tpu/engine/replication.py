"""Active/standby HA: fenced leadership epochs + journal-tail streaming.

The reference inherits HA from the embedded kube-scheduler's client-go
LeaderElector (deploy/config.yaml ``leaderElection``): replicas block on a
Lease and the apiserver is the shared state of record, so a failover is
just "the next replica starts serving the same apiserver". The standalone
daemon's state of record is its OWN store + journal (engine/journal.py),
so HA needs two more pieces, built here:

- **Fencing epochs** (:class:`FencingEpoch`): every leadership term gets a
  monotonically increasing integer, persisted in ``<data-dir>/epoch``,
  stamped into every journal batch (``EPOCH`` control lines), snapshot
  header, and outbound status write (``X-Kube-Throttler-Epoch``). Writers
  that learn they are stale — leadership lost, a write rejected by a
  fenced peer — flip the gate and every guarded write path (journal
  append, snapshot cut, remote status PUT) refuses from then on. A
  paused-then-resumed old leader therefore cannot corrupt state it no
  longer owns: its local appends are dropped and counted, and the
  mockserver/transport reject its wire writes (no split brain).

- **Warm standby** (:class:`StandbyReplicator`): bootstraps from the
  leader's newest snapshot, then continuously streams the journal tail
  over HTTP (:class:`ReplicationSource` serves ``/v1/replication/*`` —
  the wire form of the journal's ``attach(start_offset, resume_hash)``
  contract: byte offsets + prefix sha256 continuity). Streamed events are
  applied into the standby's own store — its attached journal re-journals
  them, its index/device planes follow via the normal handler fan-out —
  so at takeover the standby only fast-forwards the remaining tail, runs
  the recovery plane reconcile, bumps the epoch, and serves.

Chunk protocol (``GET /v1/replication/journal?offset=N&hash=H``):

- the source serves exactly ``[offset, accounted_position)`` — the bytes
  the journal's running ``(bytes, sha256)`` position covers, so a chunk
  always ends on a complete line (torn crash artifacts live BEYOND the
  accounted position and are never shipped);
- ``hash`` is the sha256 hexdigest of the journal prefix up to ``offset``
  as the standby last knew it; a mismatch (the leader compacted the
  journal underneath the stream) answers 409 and the standby discards
  its resume point and re-bootstraps from the leader's newest snapshot
  rather than applying bytes from a rewritten file;
- the response carries ``X-KT-End-Sha`` (prefix hash at the chunk end) so
  the standby's resume pair stays verified without re-hashing, plus
  ``X-KT-Epoch`` and ``X-KT-Position`` for fencing and lag accounting.

Crash site ``ha.replication.send`` (faults/plan.py) SIGKILLs the leader
after flushing HALF a chunk body: the standby sees a short read, discards
the partial, and re-fetches from its last verified offset — the harness
(tools/hatest.py) proves zero divergence for that artifact.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from http.client import HTTPConnection, HTTPException
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from ..api.serialization import object_from_dict
from ..utils.lockorder import guard_attrs, make_lock
from ..version import local_proto_version
from .journal import JournalFormatError, StoreJournal
from .snapshot import SnapshotError, find_snapshots, load_snapshot
from .store import Store

logger = logging.getLogger(__name__)

EPOCH_FILE = "epoch"
EPOCH_HEADER = "X-Kube-Throttler-Epoch"
# replication wire version stamp (version.py): every /v1/replication/*
# response carries the leader's protocol version so a skewed standby can
# refuse an incompatible major BY NAME instead of misparsing the stream
PROTO_HEADER = "X-KT-Proto-Version"


class ReplicationDiverged(Exception):
    """The standby's resume point no longer matches the leader's journal
    (compaction/rewrite under the stream) — applying further bytes would
    silently fork state; the standby must re-bootstrap instead."""


@guard_attrs
class FencingEpoch:
    """One process's view of the leadership epoch: the highest epoch it
    has observed, whether ITS writes are still fresh, and (optionally)
    durable persistence in ``<data-dir>/epoch``.

    ``bump()`` is the takeover step: highest-known + 1, persisted BEFORE
    any write carries it, so a crash right after promotion still recovers
    a strictly larger epoch than the dead leader's. ``fence()`` is the
    demotion step: once stale, every guarded writer (journal, snapshot,
    remote status committer) refuses and counts."""

    GUARDED_BY = {
        "_epoch": "self._lock",
        "_stale": "self._lock",
        "_claimed": "self._lock",
    }

    def __init__(self, data_dir: Optional[str] = None, epoch: int = 0):
        self._lock = make_lock("ha.epoch")
        self._path = os.path.join(data_dir, EPOCH_FILE) if data_dir else None
        self._epoch = int(epoch)
        self._stale = False
        # True once bump() has run: this process claimed a term of its own.
        # Only a claimant is deposed by a higher observed epoch — a standby
        # legitimately observes every new leader term while streaming and
        # must NOT fence itself out of its own journal.
        self._claimed = False
        if self._path is not None and os.path.exists(self._path):
            try:
                with open(self._path) as f:
                    self._epoch = max(self._epoch, int(f.read().strip() or 0))
            except (OSError, ValueError):
                logger.warning("unreadable epoch file %s; starting at %d",
                               self._path, self._epoch)

    def current(self) -> int:
        with self._lock:
            return self._epoch

    def is_stale(self) -> bool:
        with self._lock:
            return self._stale

    def observe(self, epoch: int) -> None:
        """Learn an epoch from the environment (snapshot header, journal
        EPOCH line, replication stream). Raises the known high-water; if a
        STRICTLY higher epoch appears while we hold a claimed term (bump()
        ran) and are not yet stale, someone else has taken over — fence
        ourselves. A process that never claimed (a streaming standby) just
        tracks the high-water: new leader terms are its normal diet."""
        epoch = int(epoch)
        with self._lock:
            if epoch > self._epoch:
                fence_now = self._claimed and not self._stale
                self._epoch = epoch
            else:
                return
        if fence_now:
            self.fence(f"observed higher epoch {epoch}")

    def bump(self) -> int:
        """Start a new leadership term: epoch := highest-known + 1,
        persisted durably, staleness cleared. Returns the new epoch."""
        with self._lock:
            self._epoch += 1
            self._stale = False
            self._claimed = True
            epoch, path = self._epoch, self._path
        if path is not None:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                f.write(str(epoch))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        logger.info("fencing epoch bumped to %d", epoch)
        return epoch

    def fence(self, reason: str) -> None:
        """Mark this process's epoch stale — all guarded writes refuse
        from here on. Idempotent; logs once."""
        with self._lock:
            if self._stale:
                return
            self._stale = True
        logger.warning("FENCED (epoch %d is stale): %s", self.current(), reason)


# --------------------------------------------------------------------------
# leader side: the replication source + HTTP plumbing
# --------------------------------------------------------------------------


class ReplicationSource:
    """Leader-side read API over the data directory: newest snapshot blob
    + journal tail chunks with prefix-hash continuity. Stateless reads —
    safe from any HTTP handler thread."""

    MAX_CHUNK = 4 << 20  # bytes per journal response

    def __init__(
        self,
        data_dir: str,
        journal: StoreJournal,
        epoch: FencingEpoch,
        faults=None,
    ):
        self.data_dir = data_dir
        self.journal = journal
        self.epoch = epoch
        self.faults = faults
        # single-writer stats (probes/tests read them)
        self.chunks_served = 0
        self.snapshots_served = 0

    def status(self) -> Dict[str, Any]:
        offset, sha = self.journal.position()
        snaps = find_snapshots(self.data_dir)
        return {
            "epoch": self.epoch.current(),
            "journalOffset": offset,
            "journalSha256": sha,
            "snapshotSeq": snaps[0][0] if snaps else None,
        }

    def snapshot_blob(self) -> Optional[Tuple[bytes, int]]:
        """Raw bytes + seq of the newest VALID snapshot (checksum-gated:
        a torn one must not bootstrap a standby), or None when the leader
        has not cut one yet (the standby streams from offset 0 instead)."""
        for seq, path in find_snapshots(self.data_dir):
            try:
                load_snapshot(path)  # header/length/sha256 gate
            except SnapshotError as e:
                logger.warning("replication: skipping invalid snapshot %s (%s)",
                               path, e)
                continue
            with open(path, "rb") as f:
                self.snapshots_served += 1
                return f.read(), seq
        return None

    def journal_chunk(
        self, offset: int, sha_hex: str = "", want_start_sha: bool = False
    ) -> Dict[str, Any]:
        """One tail chunk past ``offset``; verifies ``sha_hex`` (prefix
        hash at ``offset``) when given. Returns {data, endOffset, endSha,
        position, epoch, startSha?}; raises :class:`ReplicationDiverged`
        on any continuity failure. The journal computes the start/end
        prefix hashes under its own lock, so a compaction racing this read
        cannot produce a hash over a rewritten file."""
        chunk = self.journal.replication_chunk(
            offset,
            max_bytes=self.MAX_CHUNK,
            want_start_sha=bool(sha_hex) or want_start_sha,
        )
        if chunk is None:
            raise ReplicationDiverged(
                f"offset {offset} beyond journal position (compacted?)"
            )
        data, end_offset, end_sha, position, start_sha = chunk
        if sha_hex and sha_hex != start_sha:
            raise ReplicationDiverged(
                f"prefix hash mismatch at offset {offset} — journal "
                "rewritten since the standby attached"
            )
        out = {
            "data": data,
            "endOffset": end_offset,
            "endSha": end_sha,
            "position": position,
            "epoch": self.epoch.current(),
        }
        if want_start_sha:
            out["startSha"] = start_sha
        self.chunks_served += 1
        return out


def handle_replication_get(handler, source: ReplicationSource, raw_path: str) -> bool:
    """Serve ``GET /v1/replication/{status,snapshot,journal}`` on a
    BaseHTTPRequestHandler. Returns False when ``raw_path`` is not a
    replication route (the caller falls through to its own routing).

    Crash site ``ha.replication.send``: flush HALF the journal chunk body,
    then SIGKILL — the torn-stream artifact the standby must survive."""
    split = urlsplit(raw_path)
    path = split.path
    if not path.startswith("/v1/replication/"):
        return False

    proto = "%d.%d" % local_proto_version()

    def send_json(code: int, doc: dict) -> None:
        body = json.dumps(doc).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.send_header(PROTO_HEADER, proto)
        handler.end_headers()
        handler.wfile.write(body)

    def send_raw(body: bytes, headers: Dict[str, str], torn: bool = False) -> None:
        handler.send_response(200)
        handler.send_header("Content-Type", "application/octet-stream")
        handler.send_header("Content-Length", str(len(body)))
        handler.send_header(PROTO_HEADER, proto)
        for k, v in headers.items():
            handler.send_header(k, v)
        handler.end_headers()
        if torn:
            # half the body on the wire, then die: the standby's read
            # comes up short (IncompleteRead) and must discard the chunk
            handler.wfile.write(body[: max(1, len(body) // 2)])
            handler.wfile.flush()
            return
        handler.wfile.write(body)

    try:
        if path == "/v1/replication/status":
            send_json(200, source.status())
        elif path == "/v1/replication/snapshot":
            blob = source.snapshot_blob()
            if blob is None:
                send_json(404, {"message": "no snapshot yet; stream from 0"})
            else:
                data, seq = blob
                send_raw(
                    data,
                    {
                        EPOCH_HEADER: str(source.epoch.current()),
                        "X-KT-Snapshot-Seq": str(seq),
                    },
                )
        elif path == "/v1/replication/journal":
            query = parse_qs(split.query)
            offset = int((query.get("offset") or ["0"])[0] or "0")
            sha_hex = (query.get("hash") or [""])[0]
            want_start = (query.get("rehash") or ["0"])[0] == "1"
            try:
                chunk = source.journal_chunk(
                    offset, sha_hex, want_start_sha=want_start
                )
            except ReplicationDiverged as e:
                send_json(409, {"message": str(e), "reason": "Diverged"})
                return True
            headers = {
                EPOCH_HEADER: str(chunk["epoch"]),
                "X-KT-End-Offset": str(chunk["endOffset"]),
                "X-KT-End-Sha": chunk["endSha"],
                "X-KT-Position": str(chunk["position"]),
            }
            if "startSha" in chunk:
                headers["X-KT-Start-Sha"] = chunk["startSha"]
            torn = False
            if source.faults is not None and chunk["data"]:
                fault = source.faults.check("ha.replication.send")
                if fault is not None and fault.mode == "kill":
                    torn = True
                    send_raw(chunk["data"], headers, torn=True)
                    fault.kill()
            if not torn:
                send_raw(chunk["data"], headers)
        else:
            send_json(404, {"message": f"no replication route {path}"})
    except (BrokenPipeError, ConnectionResetError, OSError):
        pass  # standby went away mid-response; it will re-poll
    return True


class ReplicationServer:
    """Minimal standalone HTTP server over a :class:`ReplicationSource` —
    what the chaos harness's leader child runs (the daemon serves the same
    routes from server.py)."""

    def __init__(self, source: ReplicationSource, host: str = "127.0.0.1",
                 port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer_source = source

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                if not handle_replication_get(self, outer_source, self.path):
                    body = b'{"message": "replication endpoint only"}'
                    self.send_response(404)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="replication", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)


# --------------------------------------------------------------------------
# standby side
# --------------------------------------------------------------------------


class StandbyReplicator:
    """Warm standby: bootstrap from the leader's newest snapshot, then
    poll the journal tail and apply every event into the LOCAL store. The
    standby's own attached journal re-journals what lands, so its data
    directory independently satisfies the crash-recovery invariant ("the
    journal alone reproduces the store") at every instant — promotion is
    a local recovery, not a data copy.

    Single consumer thread; probe attributes (lag, counters, epoch) are
    single-writer values read lock-free by health/metrics probes (same
    stance as the journal's robustness counters)."""

    def __init__(
        self,
        store: Store,
        journal: StoreJournal,
        leader_url: str,
        epoch: Optional[FencingEpoch] = None,
        poll_interval: float = 0.2,
        request_timeout: float = 5.0,
    ):
        self.store = store
        self.journal = journal
        self.epoch = epoch
        split = urlsplit(leader_url)
        self._host = split.hostname or "127.0.0.1"
        self._port = split.port or 80
        self.leader_url = leader_url
        self.poll_interval = poll_interval
        self.request_timeout = request_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # resume pair: consumed leader-journal offset + verified prefix sha
        self._offset = 0
        self._sha_hex = ""
        self._needs_rehash = False
        self.bootstrap_snapshot: Optional[dict] = None
        # single-writer probe stats
        self.leader_position = 0
        self.leader_epoch = 0
        self.events_applied = 0
        self.bytes_applied = 0
        self.lines_skipped = 0
        self.apply_errors = 0
        self.polls = 0
        self.rebootstraps = 0
        self.last_contact_monotonic: Optional[float] = None
        self.diverged = False
        self.bootstrapped = False
        # rolling-upgrade format refusal: the leader served a snapshot
        # version, protocol major, or control line this build cannot read.
        # Deterministic — retrying fetches the same bytes — so bootstrap
        # fails fast (no retry-until-deadline) and health names the demand.
        self.format_refused = 0
        self.format_refused_reason: Optional[str] = None

    # -- wire ---------------------------------------------------------------

    def _get(self, path: str) -> Tuple[int, bytes, Dict[str, str]]:
        conn = HTTPConnection(self._host, self._port, timeout=self.request_timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data, {k: v for k, v in resp.getheaders()}
        except HTTPException as e:
            # a torn chunk (leader died mid-send, Content-Length declared
            # but the connection closed short) surfaces from resp.read()
            # as IncompleteRead — an HTTPException, NOT an OSError.
            # Normalize so every caller's retry path (bootstrap, _run,
            # catch_up) treats it like any other transport failure.
            raise OSError(f"replication fetch failed: {e!r}") from e
        finally:
            conn.close()

    def _proto_refusal(self, headers: Dict[str, str]) -> Optional[str]:
        """Non-None when the leader's stamped protocol major (PROTO_HEADER)
        is incompatible with ours. A missing or malformed stamp is treated
        as the pre-versioning 1.x baseline — never a refusal (a rolling
        upgrade must interoperate with the build that predates the
        stamp)."""
        raw = headers.get(PROTO_HEADER)
        if not raw:
            return None
        try:
            major = int(str(raw).split(".", 1)[0])
        except ValueError:
            return None
        ours = local_proto_version()
        if major != ours[0]:
            return (
                f"leader speaks replication protocol {raw}; this build "
                f"speaks {ours[0]}.{ours[1]} (incompatible major)"
            )
        return None

    # -- bootstrap ----------------------------------------------------------

    def bootstrap(self, deadline_s: float = 30.0) -> bool:
        """Fetch the leader's newest snapshot (404 → genesis stream) and
        apply it into the local store; seeds the resume pair from the
        snapshot's journal anchor. Retries transport errors AND transient
        non-200 answers (a restarting leader's 500 is as temporary as a
        refused socket) until the deadline passes. Never raises — returns
        True when bootstrapped, False on deadline/stop, so callers have
        exactly one failure path."""
        deadline = time.monotonic() + deadline_s
        while not self._stop.is_set():
            try:
                status, data, headers = self._get("/v1/replication/snapshot")
            except OSError:
                if time.monotonic() >= deadline:
                    return False
                self._stop.wait(0.1)
                continue
            self.last_contact_monotonic = time.monotonic()
            refusal = self._proto_refusal(headers)
            if refusal:
                self.format_refused += 1
                self.format_refused_reason = refusal
                logger.error("standby bootstrap REFUSED: %s", refusal)
                return False
            if status == 404:
                self._offset, self._sha_hex = 0, ""
            elif status == 200:
                from .snapshot import parse_snapshot_bytes

                try:
                    payload = parse_snapshot_bytes(data)
                except SnapshotError as e:
                    # version/format refusal (rolling-upgrade skew): the
                    # leader's snapshot is NEWER than this reader. This is
                    # deterministic — every retry fetches the same bytes —
                    # so retrying until the deadline would just burn it
                    # and then report a generic timeout. Fail fast instead,
                    # with the version named for the operator; health
                    # reports down until this build is upgraded.
                    self.format_refused += 1
                    self.format_refused_reason = str(e)
                    logger.error(
                        "standby bootstrap REFUSED (no retry): %s", e
                    )
                    return False
                self._apply_snapshot(payload)
                self.bootstrap_snapshot = payload
                jinfo = payload.get("journal") or {}
                self._offset = int(jinfo.get("offset") or 0)
                self._sha_hex = str(jinfo.get("sha256") or "")
                snap_epoch = int(payload.get("epoch") or 0)
                if snap_epoch:
                    # stamp OUR journal too: the snapshot's term predates
                    # the tail we stream, so a restarted standby must not
                    # re-learn epoch 0 from a log missing the marker
                    if self.epoch is not None:
                        self.epoch.observe(snap_epoch)
                    self.journal.set_epoch(snap_epoch)
            else:
                logger.warning(
                    "snapshot fetch: HTTP %d %r; retrying",
                    status, data[:200],
                )
                if time.monotonic() >= deadline:
                    return False
                self._stop.wait(0.1)
                continue
            ep = headers.get(EPOCH_HEADER)
            if ep:
                self.leader_epoch = int(ep)
            # drain the tail once NOW, so "bootstrapped" means caught up
            # to the leader's position at this instant — a leader killed
            # right after bootstrap must not take the whole journal with
            # it just because the first background poll never ran
            try:
                while True:
                    self.poll_once()
                    if self._offset >= self.leader_position:
                        break
            except OSError:
                pass  # leader vanished mid-drain: keep what landed
            except ReplicationDiverged:
                # the anchor went stale under us: the leader compacted
                # after cutting the snapshot we just applied. Compaction
                # triggers a fresh snapshot on the leader, so re-fetching
                # yields one with a resolvable anchor — loop back
                self.diverged = False
                if time.monotonic() >= deadline:
                    return False
                self._stop.wait(0.1)
                continue
            self.bootstrapped = True
            logger.info(
                "standby bootstrapped from %s (offset=%d, epoch=%s)",
                self.leader_url, self._offset, self.leader_epoch,
            )
            return True
        return False

    def _apply_snapshot(self, payload: dict) -> None:
        from .store import key_of

        want: Dict[str, set] = {}
        ops: List[Tuple[str, str, object]] = []
        for d in payload.get("objects", []):
            kind = d.get("kind")
            obj = object_from_dict(d)
            want.setdefault(kind, set()).add(key_of(kind, obj))
            ops.append(("upsert", kind, obj))
        # snapshot v2 ships pods as a columnar block instead of manifest
        # dicts; materialize through the shared reader so the standby's
        # apply path (events → its own journal) stays unchanged
        block = payload.get("podColumns")
        if block:
            from .columnar import pods_from_columns

            for pod in pods_from_columns(block):
                want.setdefault("Pod", set()).add(key_of("Pod", pod))
                ops.append(("upsert", "Pod", pod))
        # a RESTARTED standby recovers its previous replicated state first;
        # anything it holds that the leader's snapshot no longer carries
        # was deleted while we were down — drop it BEFORE the upserts, or
        # stale extras would survive every future comparison. Dependents
        # first (pods before namespaces).
        stale: List[Tuple[str, str, object]] = []
        for kind, lister in (
            ("Pod", self.store.list_pods),
            ("Throttle", self.store.list_throttles),
            ("ClusterThrottle", self.store.list_cluster_throttles),
            ("Namespace", self.store.list_namespaces),
        ):
            have = want.get(kind, set())
            for obj in lister():
                if key_of(kind, obj) not in have:
                    stale.append(("delete", kind, obj))
        ops = stale + ops
        if ops:
            results = self.store.apply_events(ops)
            self.apply_errors += sum(
                1 for r in results if isinstance(r, Exception)
            )
            self.events_applied += len(ops)
        self.store.advance_resource_version_to(int(payload.get("rv", 0)))

    # -- tail streaming ------------------------------------------------------

    def poll_once(self) -> int:
        """One tail fetch + apply; returns events applied. Raises OSError
        on transport failure (caller decides retry policy) and
        :class:`ReplicationDiverged` on a 409 continuity failure."""
        q = f"offset={self._offset}"
        if self._sha_hex:
            q += f"&hash={self._sha_hex}"
        if self._needs_rehash:
            q += "&rehash=1"
        status, data, headers = self._get(f"/v1/replication/journal?{q}")
        self.polls += 1
        self.last_contact_monotonic = time.monotonic()
        refusal = self._proto_refusal(headers)
        if refusal:
            # the leader was upgraded across a protocol major mid-stream:
            # stop consuming BEFORE the offset advances. OSError keeps the
            # run loop's quiet paced retry (no hot loop); health names the
            # incompatibility via format_refused_reason.
            if self.format_refused_reason != refusal:
                logger.error("journal tail REFUSED: %s", refusal)
            self.format_refused += 1
            self.format_refused_reason = refusal
            raise OSError(f"replication refused: {refusal}")
        if status == 409:
            self.diverged = True
            raise ReplicationDiverged(data.decode(errors="replace")[:200])
        if status != 200:
            raise OSError(f"journal fetch failed: HTTP {status}")
        # a torn send (leader died mid-chunk) never reaches here: read()
        # raises IncompleteRead inside _get, normalized to OSError there
        if self._needs_rehash and "X-KT-Start-Sha" in headers:
            self._sha_hex = headers["X-KT-Start-Sha"]
            self._needs_rehash = False
        ep = headers.get(EPOCH_HEADER)
        if ep:
            self.leader_epoch = int(ep)
            if self.epoch is not None:
                self.epoch.observe(self.leader_epoch)
        self.leader_position = int(headers.get("X-KT-Position", "0") or 0)
        if not data:
            return 0
        # the chunk ends at the leader's accounted position — complete
        # lines, except when a torn-mode fault left an unterminated
        # fragment at the accounted tail: consume only whole lines and
        # re-fetch the fragment once its terminator lands
        valid_len = data.rfind(b"\n") + 1
        consumed = data[:valid_len]
        applied = self._apply_lines(consumed)
        self._offset += valid_len
        self.bytes_applied += valid_len
        if valid_len == len(data):
            self._sha_hex = headers.get("X-KT-End-Sha", "")
        else:
            # offset now sits mid-chunk; the verified hash no longer
            # matches — ask the source to re-hash our prefix next poll
            self._sha_hex = ""
            self._needs_rehash = True
        return applied

    def _apply_lines(self, data: bytes) -> int:
        ops: List[Tuple[str, str, object]] = []
        epochs: List[int] = []
        gangs: List[Tuple[str, str, Optional[list]]] = []
        preempts: List[dict] = []
        for raw in data.split(b"\n"):
            line = raw.strip()
            if not line:
                continue
            try:
                event = json.loads(line.decode("utf-8"))
                if event.get("type") == "EPOCH":
                    epochs.append(int(event.get("epoch", 0)))
                    continue
                if event.get("type") == "GANG":
                    # gang control line (protocol checker): forward into
                    # OUR journal so a promoted standby still knows which
                    # groups have a begin-without-commit tail to roll back
                    # — dropping it here counted the line as corruption
                    # and silently lost the mid-reserve crash marker
                    gangs.append(
                        (
                            str(event.get("op", "")),
                            str(event.get("group", "")),
                            event.get("members"),
                        )
                    )
                    continue
                if event.get("type") == "PREEMPT":
                    # preemption control line (protocol checker): forward
                    # into OUR journal so a promoted standby still knows
                    # which mid-eviction preemptions to roll back to zero
                    # victims — dropping it would count as corruption and
                    # silently lose the crash-rollback payload
                    preempts.append(event)
                    continue
                etype = event.get("type")
                if (
                    isinstance(etype, str)
                    and etype.isupper()
                    and etype not in ("ADDED", "MODIFIED", "DELETED")
                    and "object" not in event
                ):
                    # unknown-but-versioned control line from a NEWER
                    # leader build (journal.JournalFormatError stance):
                    # refuse by name BEFORE any of this chunk applies or
                    # the offset advances — counting it as corruption
                    # would silently drop semantics we do not understand.
                    need = event.get("minReader", "unknown")
                    ours = "%d.%d" % local_proto_version()
                    refusal = (
                        f"unknown control line type {etype!r} requires "
                        f"reader >= {need} (this reader speaks {ours})"
                    )
                    self.format_refused += 1
                    self.format_refused_reason = refusal
                    raise JournalFormatError(refusal)
                kind = event["kind"]
                obj = object_from_dict({**event["object"], "kind": kind})
                if event["type"] == "DELETED":
                    ops.append(("delete", kind, obj))
                else:
                    ops.append(("upsert", kind, obj))
            except (ValueError, KeyError, UnicodeDecodeError):
                # mirror journal-replay semantics: interior corruption is
                # skipped and counted, never fatal
                self.lines_skipped += 1
        if ops:
            results = self.store.apply_events(ops)
            self.apply_errors += sum(
                1 for r in results if isinstance(r, Exception)
            )
            self.events_applied += len(ops)
        for e in epochs:
            # propagate the leader's epoch marker into OUR journal so a
            # restart of this standby still knows the high-water term
            if self.epoch is not None:
                self.epoch.observe(e)
            self.journal.set_epoch(e)
        for op, group, members in gangs:
            if group:
                self.journal.append_gang(op, group, members)
        for event in preempts:
            pid = str(event.get("id", ""))
            if pid:
                self.journal.append_preempt(
                    str(event.get("op", "")),
                    pid,
                    victims=event.get("victims"),
                    objects=event.get("victimObjects"),
                )
        return len(ops)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="standby-replicator", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except ReplicationDiverged as e:
                # the leader rewrote the journal under the stream (any
                # compaction does this): the resume pair is worthless, so
                # discard it and re-bootstrap from the newest snapshot —
                # exactly what ReplicationDiverged's contract demands. The
                # standby reports down (diverged) until the re-bootstrap
                # lands; on a dead/unreachable leader the bootstrap times
                # out and the next poll's 409 brings us back here.
                logger.warning("replication diverged: %s — re-bootstrapping "
                               "from the leader's newest snapshot", e)
                self._offset, self._sha_hex = 0, ""
                self._needs_rehash = False
                if self.bootstrap(deadline_s=30.0):
                    self.diverged = False
                    self.rebootstraps += 1
            except JournalFormatError:
                # format refusal: already counted and named in
                # format_refused_reason (health reports down). Keep the
                # paced poll — a leader rollback or our own upgrade is the
                # only thing that clears it; no hot loop, no log storm.
                pass
            except OSError:
                # leader unreachable (crashed, restarting, network): keep
                # polling — the lease decides when WE take over, not the
                # socket
                pass
            except Exception:  # noqa: BLE001 — route the death, keep polling
                # the PR 6 silent-replicator-death class: an unexpected
                # exception (malformed header, apply bug) must not kill
                # the thread while health keeps reporting a live standby —
                # count it where health_state/probes can see it and retry
                self.apply_errors += 1
                logger.exception("standby replicator poll failed; retrying")
            self._stop.wait(self.poll_interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def catch_up(self, attempts: int = 3, delay: float = 0.05) -> int:
        """Promotion fast-forward: drain whatever tail the (probably dead)
        leader can still serve. Transport errors end the attempt quietly —
        the surviving prefix IS the state to promote."""
        total = 0
        for _ in range(attempts):
            try:
                applied = self.poll_once()
            except (OSError, ReplicationDiverged):
                break
            total += applied
            if self._offset >= self.leader_position:
                break
            time.sleep(delay)
        return total

    # -- probes --------------------------------------------------------------

    def lag_bytes(self) -> int:
        return max(0, self.leader_position - self._offset)

    def consumed_offset(self) -> int:
        return self._offset

    def health_state(self) -> Tuple[str, dict]:
        age = (
            round(time.monotonic() - self.last_contact_monotonic, 3)
            if self.last_contact_monotonic is not None
            else None
        )
        detail = {
            "role": "standby",
            "leader": self.leader_url,
            "bootstrapped": self.bootstrapped,
            "lagBytes": self.lag_bytes(),
            "eventsApplied": self.events_applied,
            "linesSkipped": self.lines_skipped,
            "lastContactAgeSeconds": age,
            "leaderEpoch": self.leader_epoch,
            "rebootstraps": self.rebootstraps,
            "formatRefused": self.format_refused,
        }
        if self.format_refused_reason:
            # version skew, not an outage: the leader serves a format this
            # build cannot read. Down with the demand NAMED, so the
            # operator reads "upgrade me", not "network flake".
            return "down", {
                **detail,
                "error": f"format refused: {self.format_refused_reason}",
            }
        if self.diverged:
            return "down", {**detail, "error": "replication diverged"}
        if not self.bootstrapped:
            return "down", {**detail, "error": "not bootstrapped"}
        return "ok", detail


class ReplicaGate:
    """Staleness gate for the stateless read-replica admission tier.

    A read replica serves ``pre_filter``/``pre_filter_batch`` from its
    replicated mirror; every verdict is therefore as old as the last
    journal-tail confirmation. This gate enforces the staleness bound
    (replica verdict lag ≤ the flip SLO, ``max_lag_s``): a request that
    arrives while the replica cannot prove it has heard from the leader
    within the bound is REFUSED (the server answers 503 and the client
    retries against the owner) instead of served from state that may
    predate a flip.

    Lag is measured as seconds since the replicator's last successful
    tail poll (``last_contact_monotonic``): a successful poll drains the
    leader's accounted tail, so fresh contact means the mirror is within
    one poll interval of the leader's position. Divergence and
    pre-bootstrap states count as infinite lag. Counters are
    single-writer-per-request and read racily by metrics — the same
    stance as the replicator's own probe stats."""

    def __init__(self, replicator: "StandbyReplicator", max_lag_s: float = 5.0):
        self.replicator = replicator
        self.max_lag_s = float(max_lag_s)
        # admit() refuses on `lag > max_lag_s`, and every float comparison
        # against NaN is False — a NaN bound would therefore serve
        # arbitrarily stale verdicts forever (fail-OPEN, silently). A
        # non-positive bound is the opposite dead state. +inf is allowed:
        # it is the explicit "never refuse on staleness" operator choice.
        if self.max_lag_s != self.max_lag_s or self.max_lag_s <= 0:
            raise ValueError(
                f"replica max lag must be a positive number of seconds "
                f"(got {max_lag_s!r})"
            )
        self._monotonic = time.monotonic  # test injection point
        self.served_total = 0
        self.refused_total = 0
        self.lag_events_total = 0

    def current_lag(self) -> float:
        """Seconds since the replica last confirmed the leader's tail;
        +inf before bootstrap or while diverged."""
        r = self.replicator
        if r.diverged or not r.bootstrapped or r.last_contact_monotonic is None:
            return float("inf")
        return max(0.0, self._monotonic() - r.last_contact_monotonic)

    def admit(self) -> bool:
        """Gate one serving request: True ⇒ serve, False ⇒ refuse
        (stale). Counts either way."""
        if self.current_lag() > self.max_lag_s:
            self.refused_total += 1
            self.lag_events_total += 1
            return False
        self.served_total += 1
        return True

    def health_state(self) -> Tuple[str, dict]:
        lag = self.current_lag()
        detail = {
            "role": "replica",
            "maxLagSeconds": self.max_lag_s,
            "lagSeconds": (round(lag, 3) if lag != float("inf") else None),
            "served": self.served_total,
            "refused": self.refused_total,
        }
        if lag > self.max_lag_s:
            return "down", {**detail, "error": "staleness bound exceeded"}
        return "ok", detail


# --------------------------------------------------------------------------
# the facade the server/CLI/metrics read
# --------------------------------------------------------------------------


# --------------------------------------------------------------------------
# resharding: the chunk protocol re-pointed at an in-memory handoff slice,
# plus range-scoped fencing (the FencingEpoch discipline per keyspace range)
# --------------------------------------------------------------------------


class SliceChunkSource:
    """The StandbyReplicator journal-chunk contract re-pointed at an
    in-memory handoff slice: serve ``[offset, len(blob))`` windows with
    prefix-sha256 continuity, so a live-resharding source shard streams
    its keyspace slice over the framed-pickle IPC with exactly the
    torn-stream detection the replication wire already has. A chunk whose
    claimed prefix hash mismatches raises :class:`ReplicationDiverged` —
    the coordinator's abort-back-to-source trigger."""

    def __init__(self, blob: bytes, max_chunk: int = 1 << 20):
        self.blob = blob
        self.max_chunk = int(max_chunk)
        self.chunks_served = 0

    def chunk(self, offset: int, sha_hex: str = "") -> Dict[str, Any]:
        offset = int(offset)
        if offset > len(self.blob):
            raise ReplicationDiverged(
                f"offset {offset} beyond slice length {len(self.blob)}"
            )
        if sha_hex:
            want = hashlib.sha256(self.blob[:offset]).hexdigest()
            if sha_hex != want:
                raise ReplicationDiverged(
                    f"slice prefix hash mismatch at offset {offset}"
                )
        data = self.blob[offset : offset + self.max_chunk]
        end = offset + len(data)
        self.chunks_served += 1
        return {
            "data": data,
            "endOffset": end,
            "endSha": hashlib.sha256(self.blob[:end]).hexdigest(),
            "position": len(self.blob),
            # protocol stamp (version.py): the sink refuses a major it
            # cannot read instead of misparsing the slice payload
            "proto": list(local_proto_version()),
        }


class SliceChunkSink:
    """Destination-side assembler for a :class:`SliceChunkSource` stream:
    verifies every chunk's offset continuity and end-prefix hash before
    appending; a torn or reordered chunk raises
    :class:`ReplicationDiverged` and the partial buffer is discarded by
    the caller (never applied). ``done`` flips when the verified buffer
    reaches the source's position."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self.position: Optional[int] = None

    def offset(self) -> int:
        return len(self._buf)

    def sha_hex(self) -> str:
        return hashlib.sha256(bytes(self._buf)).hexdigest()

    @property
    def done(self) -> bool:
        return self.position is not None and len(self._buf) >= self.position

    def feed(self, chunk: Dict[str, Any]) -> int:
        proto = chunk.get("proto")
        if proto:
            # version stamp (SliceChunkSource): an incompatible major is
            # the coordinator's abort-back-to-source trigger — authority
            # stays with the source, nothing half-parsed is applied. An
            # unstamped chunk is the pre-versioning baseline (accepted).
            try:
                major = int(proto[0])
            except (TypeError, ValueError, IndexError, KeyError):
                major = None
            if major is not None and major != local_proto_version()[0]:
                ours = "%d.%d" % local_proto_version()
                raise ReplicationDiverged(
                    f"slice stream speaks protocol {proto}; this sink "
                    f"speaks {ours} (incompatible major)"
                )
        data = chunk.get("data") or b""
        end = int(chunk.get("endOffset", 0))
        if end != len(self._buf) + len(data):
            raise ReplicationDiverged(
                f"chunk end {end} does not extend verified prefix "
                f"{len(self._buf)}+{len(data)}"
            )
        candidate = bytes(self._buf) + bytes(data)
        if hashlib.sha256(candidate).hexdigest() != chunk.get("endSha"):
            raise ReplicationDiverged("slice chunk hash mismatch (torn stream)")
        self._buf = bytearray(candidate)
        self.position = int(chunk.get("position", end))
        return len(data)

    def payload(self) -> bytes:
        if not self.done:
            raise ReplicationDiverged("slice stream incomplete")
        return bytes(self._buf)


@guard_attrs
class RangeFence:
    """Range-scoped fencing: the :class:`FencingEpoch` discipline applied
    per keyspace range during a live reshard. Once a handoff's ranges are
    fenced at an epoch, the source's write path refuses every
    authoritative (throttle-keyspace) write whose route hash lands in a
    fenced range — a racing event routed before the cutover cannot mutate
    state the destination now owns. Fences are lifted by the cutover's
    retire (the slice left with the range) or by an abort/TTL-reap
    (authority returns to the source)."""

    GUARDED_BY = {
        "_fences": "self._lock",
        "writes_refused": "self._lock",
    }

    def __init__(self) -> None:
        self._lock = make_lock("reshard.rangefence")
        # handoff id -> (epoch, ((lo, hi), ...))
        self._fences: Dict[str, Tuple[int, Tuple[Tuple[int, int], ...]]] = {}
        self.writes_refused = 0

    def fence(self, handoff: str, ranges, epoch: int) -> None:
        with self._lock:
            self._fences[handoff] = (
                int(epoch),
                tuple((int(lo), int(hi)) for lo, hi in ranges),
            )

    def lift(self, handoff: str) -> bool:
        with self._lock:
            return self._fences.pop(handoff, None) is not None

    def fenced_handoffs(self) -> List[str]:
        with self._lock:
            return sorted(self._fences)

    def covers(self, h: int) -> bool:
        """True when ``h`` lies in any fenced range — the write refusal
        predicate the source's event path consults."""
        with self._lock:
            for _epoch, ranges in self._fences.values():
                for lo, hi in ranges:
                    if lo <= h < hi:
                        return True
            return False

    def refuse(self, n: int = 1) -> None:
        with self._lock:
            self.writes_refused += n

    def refused(self) -> int:
        with self._lock:
            return self.writes_refused


class HaCoordinator:
    """Role + epoch + replication wiring for one replica. The HTTP server
    reads ``role`` for /readyz, serves ``source`` when present; metrics
    read the lag/rejection aggregates; the CLI drives :meth:`promote`."""

    def __init__(
        self,
        epoch: FencingEpoch,
        role: str = "standby",
        source: Optional[ReplicationSource] = None,
        replicator: Optional[StandbyReplicator] = None,
        journal: Optional[StoreJournal] = None,
        snapshotter=None,
    ):
        self.epoch = epoch
        self.role = role
        self.source = source
        self.replicator = replicator
        self.journal = journal
        self.snapshotter = snapshotter
        self.failover_duration_s: Optional[float] = None
        self.promotions = 0

    def become_leader(self) -> int:
        """Leader startup (no failover): bump + stamp the journal."""
        epoch = self.epoch.bump()
        if self.journal is not None:
            self.journal.set_epoch(epoch)
        self.role = "leader"
        return epoch

    def promote(self) -> int:
        """Standby → leader: fast-forward the remaining tail, stop
        replicating, bump the epoch past every observed term, stamp the
        journal. The caller then builds the serving plugin (cache-sync
        replay rebuilds index/planes), runs the recovery-style reconcile,
        and re-enqueues every key so flips the dead leader never committed
        are recomputed and published through the two-lane pipeline."""
        t0 = time.monotonic()
        if self.replicator is not None:
            self.replicator.catch_up()
            self.replicator.stop()
        epoch = self.epoch.bump()
        if self.journal is not None:
            self.journal.set_epoch(epoch)
        self.role = "leader"
        self.promotions += 1
        self.failover_duration_s = time.monotonic() - t0
        logger.info(
            "promoted to leader (epoch %d) in %.3fs",
            epoch, self.failover_duration_s,
        )
        return epoch

    def promote_reconcile(self, plugin) -> int:
        """Post-promotion flip re-publication: enqueue EVERY live key on
        both controllers so the first reconcile sweep recomputes statuses
        from replicated truth — any flip the dead leader had computed but
        not durably published is re-derived and goes out flips-first
        through the two-lane pipeline. Returns the number of keys."""
        n = 0
        for ctr, informer in (
            (plugin.throttle_ctr, plugin.informers.throttles()),
            (plugin.cluster_throttle_ctr, plugin.informers.cluster_throttles()),
        ):
            keys = list(informer.snapshot_objects().keys())
            ctr.enqueue_all(keys)
            n += len(keys)
        return n

    def stale_epoch_rejections(self) -> int:
        total = 0
        if self.journal is not None:
            total += getattr(self.journal, "stale_epoch_rejected", 0)
        if self.snapshotter is not None:
            total += getattr(self.snapshotter, "stale_epoch_rejected", 0)
        return total

    def health_state(self) -> Tuple[str, dict]:
        detail: Dict[str, Any] = {
            "role": self.role,
            "epoch": self.epoch.current(),
            "fenced": self.epoch.is_stale(),
            "staleEpochRejections": self.stale_epoch_rejections(),
        }
        if self.failover_duration_s is not None:
            detail["failoverDurationSeconds"] = round(self.failover_duration_s, 4)
        if self.epoch.is_stale():
            return "down", detail
        if self.role == "standby" and self.replicator is not None:
            state, rdetail = self.replicator.health_state()
            return state, {**detail, **rdetail}
        return "ok", detail
