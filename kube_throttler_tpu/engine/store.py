"""Deterministic in-memory object store speaking the watch protocol.

Plays the role of kube-apiserver + client-go informer caches in one object:

- every mutation bumps a global monotonically-increasing resourceVersion;
- ``update_*_status`` enforces optimistic concurrency like the status
  subresource (conflict → ``ConflictError``, caller re-reads and retries,
  matching UpdateStatus error handling at throttle_controller.go:170-173);
- event handlers (add/update/delete) fire synchronously on the mutating
  thread — informer handlers in the reference are required to be fast and
  only enqueue workqueue keys, which is exactly how the controllers here use
  them. Reconcile work itself is decoupled through the workqueue, so the
  observable interleaving (watch event → enqueue → async reconcile → status
  write → next event) matches the reference's.

Store contents are immutable-by-convention: mutators replace whole objects
(`dataclasses.replace` style); readers must not mutate returned objects.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..api.pod import Namespace, Pod
from ..api.types import ClusterThrottle, Throttle
from ..utils.lockorder import assert_held, make_rlock
from .columnar import ColumnarEventFrame, PodArena

KObject = Union[Pod, Namespace, Throttle, ClusterThrottle]


class _ColumnarPodMap:
    """Dict-shaped facade over a :class:`PodArena`: the store's mutation
    code keeps its exact ``self._objects["Pod"]`` surface (contains /
    get / setitem / pop / values), but writes absorb into columns and
    reads materialize full objects lazily — the arena IS the store for
    pods. Only touched under the store lock, like the dicts it
    replaces."""

    __slots__ = ("arena",)

    def __init__(self, arena: PodArena) -> None:
        self.arena = arena

    def __contains__(self, key: str) -> bool:
        return key in self.arena

    def __len__(self) -> int:
        return len(self.arena)

    def __setitem__(self, key: str, pod: Pod) -> None:
        self.arena.absorb(key, pod)

    def get(self, key: str, default=None):
        pod = self.arena.materialize_key(key)
        return pod if pod is not None else default

    def pop(self, key: str, default=None):
        pod = self.arena.materialize_key(key)
        if pod is None:
            return default
        self.arena.free(key)
        return pod

    def keys(self):
        return self.arena.keys()

    def values(self):
        # generator (not a view): both consumers — handler replay and
        # _list's list() — iterate once; a 1M-pod store must not
        # materialize a second full object list just to iterate
        for key in list(self.arena.keys()):
            pod = self.arena.materialize_key(key)
            if pod is not None:
                yield pod


def columnar_default() -> bool:
    """Columnar pods are the default; ``KT_STORE_COLUMNAR=0`` keeps the
    frozen-dict reference path alive (the equivalence-sweep oracle)."""
    return os.environ.get("KT_STORE_COLUMNAR", "1") != "0"


class ConflictError(Exception):
    """Optimistic-concurrency conflict on a status update."""


class NotFoundError(KeyError):
    pass


class EventType(Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass(frozen=True)
class Event:
    type: EventType
    kind: str  # "Pod" | "Namespace" | "Throttle" | "ClusterThrottle"
    obj: KObject
    old_obj: Optional[KObject] = None
    # the resourceVersion assigned by the mutation that produced this event.
    # Under BATCHED dispatch (apply_events / the batched status writes)
    # handlers run after the whole batch has mutated, so reading
    # ``store.latest_resource_version`` inside a handler would report the
    # batch's LAST version for every event — consumers that stamp wire
    # events (the mockserver's watch log) must read this field instead.
    rv: Optional[int] = None


Handler = Callable[[Event], None]


def key_of(kind: str, obj: KObject) -> str:
    """Canonical store/informer cache key for an object of ``kind``."""
    if kind in ("Pod", "Throttle"):
        return f"{obj.namespace}/{obj.name}"
    return obj.name  # Namespace, ClusterThrottle (cluster-scoped)


_key_of = key_of


class Store:
    """Thread-safe store for the four kinds the throttler watches."""

    KINDS = ("Pod", "Namespace", "Throttle", "ClusterThrottle")

    # everything below mutates only under the store lock; dispatch also
    # runs inside it (lock order store -> handler-internal, see _create)
    GUARDED_BY = {
        "_rv": "self._lock",
        "_objects": "self._lock",
        "_versions": "self._lock",
        "_handlers": "self._lock",
        "_batch_listeners": "self._lock",
        "_in_batch_dispatch": "self._lock",
    }

    # statuses written per lock hold by the batched status writers: one
    # hold per drain (the pre-chunking behavior) kept event ingest parked
    # behind a ~250-key write for tens of ms at full scale, which is
    # exactly the flip-publication tail. Chunking bounds any single hold
    # while keeping the per-drain amortization (~chunk× fewer acquires).
    STATUS_WRITE_CHUNK = 64

    def __init__(self, columnar: Optional[bool] = None) -> None:
        self._lock = make_rlock("store")
        self._rv = 0
        # pods live in the columnar arena (interned struct-of-arrays,
        # lazily materialized at the API edge) unless the frozen-dict
        # reference mode is forced — see engine/columnar.py
        self.columnar = columnar_default() if columnar is None else bool(columnar)
        self.pod_arena: Optional[PodArena] = PodArena() if self.columnar else None
        self._objects: Dict[str, Dict[str, KObject]] = {k: {} for k in self.KINDS}
        if self.pod_arena is not None:
            self._objects["Pod"] = _ColumnarPodMap(self.pod_arena)
        self._versions: Dict[str, Dict[str, int]] = {k: {} for k in self.KINDS}
        self._handlers: Dict[str, List[Handler]] = {k: [] for k in self.KINDS}
        # batch-aware subscribers (journal, device mirror, informers, batch
        # watches): each gets ONE ``on_batch(events)`` call per batched
        # mutation with the whole ordered event list, instead of N per-event
        # handler calls — the micro-batch ingest amortization point
        self._batch_listeners: List = []
        # True while apply_events / the batched status write dispatches the
        # batch's events to PER-EVENT handlers; batch-aware components'
        # per-event handlers early-return on it (they already processed the
        # batch in on_batch). Only ever read under the store lock.
        self._in_batch_dispatch = False

    # -- atomic sections ---------------------------------------------------

    @contextmanager
    def atomic(self):
        """Hold the store lock across a multi-read (or read-modify) section.

        Every mutation dispatches its events to listeners UNDER this
        (reentrant) lock, so a section inside ``atomic()`` observes a
        frozen store AND is totally ordered against every listener
        callback — the property resync snapshots need: no event routed
        concurrently can land in a shard queue between the snapshot's
        reads and its enqueue (sharding/front.py resync_shard)."""
        with self._lock:
            yield self

    # -- watch ------------------------------------------------------------

    def add_event_handler(self, kind: str, handler: Handler, replay: bool = True) -> None:
        """Register a handler; with ``replay`` it receives synthetic ADDED
        events for existing objects first (informer cache-sync semantics,
        plugin.go:114-130)."""
        with self._lock:
            self._handlers[kind].append(handler)
            # replay INSIDE the lock (normal dispatch already runs under it):
            # otherwise a concurrent DELETED could reach the handler before
            # the stale replay ADDED, resurrecting a deleted object
            if replay:
                for obj in self._objects[kind].values():
                    handler(Event(EventType.ADDED, kind, obj))

    def remove_event_handler(self, kind: str, handler: Handler) -> None:
        """Unregister a handler (watch-stream stop)."""
        with self._lock:
            try:
                self._handlers[kind].remove(handler)
            except ValueError:
                pass

    def _dispatch_locked(self, event: Event) -> None:
        """Caller holds the store lock (see the NOTE below — dispatch runs
        inside it by design; asserted under KT_LOCK_ASSERT=1)."""
        assert_held(self._lock, "Store._dispatch_locked")
        for handler in list(self._handlers[event.kind]):
            handler(event)

    # -- batch-aware subscription (micro-batched ingest) -------------------

    def add_batch_listener(self, listener) -> None:
        """Register a batch-aware subscriber: ``listener.on_batch(events)``
        runs ONCE per batched mutation (``apply_events`` or the batched
        status writes), under the store lock, after every mutation in the
        batch has landed and BEFORE per-event handlers dispatch. A listener
        whose per-event handlers are subsumed by its batch processing must
        early-return from them while :attr:`in_batch_dispatch` is set."""
        with self._lock:
            self._batch_listeners.append(listener)

    def remove_batch_listener(self, listener) -> None:
        with self._lock:
            try:
                self._batch_listeners.remove(listener)
            except ValueError:
                pass

    @property
    def in_batch_dispatch(self) -> bool:
        """True while per-event handlers are being replayed for a batch a
        batch listener already processed. Handlers are only ever called
        under the store lock (asserted under KT_LOCK_ASSERT=1), so the
        read is coherent."""
        return self._in_batch_dispatch_locked()

    def _in_batch_dispatch_locked(self) -> bool:
        assert_held(self._lock, "Store.in_batch_dispatch")
        return self._in_batch_dispatch

    def _dispatch_batch_locked(self, events: List[Event]) -> None:
        """Batch dispatch: batch listeners first (one call each, whole
        ordered list), then the regular per-event handlers with
        ``in_batch_dispatch`` raised so batch-subsumed handlers skip."""
        assert_held(self._lock, "Store._dispatch_batch_locked")
        if not events:
            return
        frame = None
        for listener in list(self._batch_listeners):
            on_frame = getattr(listener, "on_frame", None)
            if on_frame is not None:
                # columnar batch payload (engine/columnar.py): built once
                # per batch, only when some listener asked for it — flat
                # verb/kind/key/rv/slot columns instead of object events
                if frame is None:
                    frame = ColumnarEventFrame(events, _key_of, self.pod_arena)
                on_frame(frame, events)
            else:
                listener.on_batch(events)
        self._in_batch_dispatch = True
        try:
            for event in events:
                self._dispatch_locked(event)
        finally:
            self._in_batch_dispatch = False

    def apply_events(self, ops: Sequence[Tuple[str, str, object]]) -> List[object]:
        """Apply N mutations under ONE lock acquisition — the micro-batched
        ingest entry point (engine/ingest.py drains its queue into this).

        ``ops`` is an ordered sequence of ``(verb, kind, payload)``:

        - ``("create", kind, obj)`` / ``("update", kind, obj)`` — the exact
          single-op semantics (create raises on exists, update on missing);
        - ``("upsert", kind, obj)`` — create-else-update (the watch-replay
          shape reflectors and journals apply);
        - ``("delete", kind, key)`` — delete by store key (also accepts the
          object for convenience).

        Returns one entry per op: the dispatched :class:`Event` on success
        or the raised exception (per-op failures never abort the batch —
        the events before AND after a bad op still land, so a batch is
        observably a sequence of independent mutations).

        Equivalence contract: for any partition of an op stream into
        batches, the final store contents, assigned resourceVersions, and
        the per-event handler event sequence are identical to applying the
        ops one at a time. What batching changes is only WHEN handlers run
        (after the whole batch mutated, not interleaved per op) and that
        batch listeners get one amortized call per batch."""
        results: List[object] = []
        events: List[Event] = []
        with self._lock:
            for op in ops:
                try:
                    event = self._apply_op_locked(*op)
                except Exception as e:  # noqa: BLE001 — reported per op
                    results.append(e)
                    continue
                events.append(event)
                results.append(event)
            self._dispatch_batch_locked(events)
        return results

    def _apply_op_locked(self, verb: str, kind: str, payload) -> Event:
        assert_held(self._lock, "Store._apply_op_locked")
        if verb == "delete":
            key = payload if isinstance(payload, str) else _key_of(kind, payload)
            return self._delete_locked(kind, key)
        if verb == "create":
            return self._create_locked(kind, payload)
        if verb == "update":
            return self._update_locked(kind, payload)
        if verb == "upsert":
            try:
                return self._create_locked(kind, payload)
            except ValueError:
                return self._update_locked(kind, payload)
        raise ValueError(f"unknown ingest verb {verb!r}")

    # -- generic mutations ------------------------------------------------

    # NOTE: dispatch happens INSIDE the store lock. Releasing before dispatch
    # would let two concurrent mutations of the same key deliver their
    # MODIFIED events in reverse resourceVersion order, leaving mirrors (the
    # device state) stale until the next unrelated event. Handlers are
    # informer-contract cheap (row updates + enqueues) and must never hold
    # their own lock while mutating the store from another thread (lock order
    # is store → handler-internal, established here).

    def _create_locked(self, kind: str, obj: KObject) -> Event:
        assert_held(self._lock, "Store._create_locked")
        key = _key_of(kind, obj)
        if key in self._objects[kind]:
            raise ValueError(f"{kind} {key!r} already exists")
        self._rv += 1
        self._objects[kind][key] = obj
        self._versions[kind][key] = self._rv
        return Event(EventType.ADDED, kind, obj, rv=self._rv)

    def _update_locked(self, kind: str, obj: KObject) -> Event:
        assert_held(self._lock, "Store._update_locked")
        key = _key_of(kind, obj)
        old = self._objects[kind].get(key)
        if old is None:
            raise NotFoundError(f"{kind} {key!r} not found")
        self._rv += 1
        self._objects[kind][key] = obj
        self._versions[kind][key] = self._rv
        return Event(EventType.MODIFIED, kind, obj, old_obj=old, rv=self._rv)

    def _delete_locked(self, kind: str, key: str) -> Event:
        assert_held(self._lock, "Store._delete_locked")
        old = self._objects[kind].pop(key, None)
        if old is None:
            raise NotFoundError(f"{kind} {key!r} not found")
        self._versions[kind].pop(key, None)
        self._rv += 1
        return Event(EventType.DELETED, kind, old, rv=self._rv)

    def _create(self, kind: str, obj: KObject) -> KObject:
        with self._lock:
            self._dispatch_locked(self._create_locked(kind, obj))
        return obj

    def _update(self, kind: str, obj: KObject) -> KObject:
        with self._lock:
            self._dispatch_locked(self._update_locked(kind, obj))
        return obj

    def _delete(self, kind: str, key: str) -> KObject:
        with self._lock:
            event = self._delete_locked(kind, key)
            self._dispatch_locked(event)
        return event.obj

    def materialize_pod(self, pod_key: str) -> Optional[Pod]:
        """Resolver handed to the selector indexes (SelectorIndex.pod_resolver)
        so they stop retaining per-pod objects: rare consumers (general-tier
        selector evaluation, matched_pods) materialize on demand. Takes
        ONLY the arena's leaf lock — callers hold index/devicestate locks,
        and nesting the store lock inside those would invert the
        store→index order."""
        if self.pod_arena is not None:
            return self.pod_arena.materialize_key(pod_key)
        with self._lock:
            return self._objects["Pod"].get(pod_key)

    def _get(self, kind: str, key: str) -> KObject:
        with self._lock:
            obj = self._objects[kind].get(key)
        if obj is None:
            raise NotFoundError(f"{kind} {key!r} not found")
        return obj

    def _list(self, kind: str) -> List[KObject]:
        with self._lock:
            return list(self._objects[kind].values())

    # -- typed convenience ------------------------------------------------

    def create_pod(self, pod: Pod) -> Pod:
        return self._create("Pod", pod)

    def update_pod(self, pod: Pod) -> Pod:
        return self._update("Pod", pod)

    def delete_pod(self, namespace: str, name: str) -> Pod:
        return self._delete("Pod", f"{namespace}/{name}")

    def get_pod(self, namespace: str, name: str) -> Pod:
        return self._get("Pod", f"{namespace}/{name}")

    def list_pods(self, namespace: Optional[str] = None) -> List[Pod]:
        pods = self._list("Pod")
        if namespace is None:
            return pods
        return [p for p in pods if p.namespace == namespace]

    def create_namespace(self, ns: Namespace) -> Namespace:
        return self._create("Namespace", ns)

    def update_namespace(self, ns: Namespace) -> Namespace:
        return self._update("Namespace", ns)

    def delete_namespace(self, name: str) -> Namespace:
        return self._delete("Namespace", name)

    def get_namespace(self, name: str) -> Optional[Namespace]:
        try:
            return self._get("Namespace", name)
        except NotFoundError:
            return None

    def list_namespaces(self) -> List[Namespace]:
        return self._list("Namespace")

    def create_throttle(self, thr: Throttle) -> Throttle:
        return self._create("Throttle", thr)

    def update_throttle(self, thr: Throttle) -> Throttle:
        return self._update("Throttle", thr)

    def delete_throttle(self, namespace: str, name: str) -> Throttle:
        return self._delete("Throttle", f"{namespace}/{name}")

    def get_throttle(self, namespace: str, name: str) -> Throttle:
        return self._get("Throttle", f"{namespace}/{name}")

    def list_throttles(self, namespace: Optional[str] = None) -> List[Throttle]:
        thrs = self._list("Throttle")
        if namespace is None:
            return thrs
        return [t for t in thrs if t.namespace == namespace]

    def create_cluster_throttle(self, thr: ClusterThrottle) -> ClusterThrottle:
        return self._create("ClusterThrottle", thr)

    def update_cluster_throttle(self, thr: ClusterThrottle) -> ClusterThrottle:
        return self._update("ClusterThrottle", thr)

    def delete_cluster_throttle(self, name: str) -> ClusterThrottle:
        return self._delete("ClusterThrottle", name)

    def get_cluster_throttle(self, name: str) -> ClusterThrottle:
        return self._get("ClusterThrottle", name)

    def list_cluster_throttles(self) -> List[ClusterThrottle]:
        return self._list("ClusterThrottle")

    # -- atomic read-modify-write (Patch verbs) ----------------------------

    def mutate(self, kind: str, key: str, fn: Callable[[KObject], KObject]) -> KObject:
        """Apply ``fn(current) -> updated`` atomically under the store lock —
        the server-side-apply analog a JSON merge patch needs: without it,
        two concurrent get→merge→update round trips silently lose one
        write. For Throttle/ClusterThrottle the stored status is preserved
        (status-subresource semantics). ``fn`` must be pure and fast; it
        runs under the store lock."""
        with self._lock:
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key!r} not found")
            updated = fn(current)
            if kind in ("Throttle", "ClusterThrottle"):
                updated = updated.with_status(current.status)
            return self._update(kind, updated)

    # -- main-resource update with status-subresource semantics ------------

    def update_throttle_spec(self, thr: Throttle) -> Throttle:
        """Replace the object but keep the STORED status (the apiserver
        ignores status changes on main-resource writes when the status
        subresource is enabled — throttle_types.go:158 marker). Atomic via
        :meth:`mutate`, so a concurrent ``update_throttle_status`` can never
        be reverted by a stale read."""
        return self.mutate("Throttle", thr.key, lambda _cur: thr)

    def update_cluster_throttle_spec(self, thr: ClusterThrottle) -> ClusterThrottle:
        return self.mutate("ClusterThrottle", thr.name, lambda _cur: thr)

    # -- status subresource (optimistic concurrency) ----------------------

    def update_throttle_status(self, thr: Throttle, expected_version: Optional[int] = None) -> Throttle:
        """UpdateStatus: replace only the status of the stored object. With
        ``expected_version``, conflicts raise (the caller re-reads, like a
        client-go retry-on-conflict loop)."""
        key = thr.key
        with self._lock:
            current = self._objects["Throttle"].get(key)
            if current is None:
                raise NotFoundError(f"Throttle {key!r} not found")
            if expected_version is not None and self._versions["Throttle"][key] != expected_version:
                raise ConflictError(f"Throttle {key!r} version changed")
            updated = current.with_status(thr.status)
            self._rv += 1
            self._objects["Throttle"][key] = updated
            self._versions["Throttle"][key] = self._rv
            self._dispatch_locked(
                Event(EventType.MODIFIED, "Throttle", updated, old_obj=current, rv=self._rv)
            )
        return updated

    def _update_statuses_locked(self, kind: str, thrs) -> Dict[str, object]:
        """Batched UpdateStatus under ONE lock hold: at reconcile-drain
        saturation, per-key writes made every status contend with the
        event-ingest threads for this lock ~hundreds of times per drain;
        one hold writes the whole drain's worth. Dispatch is BATCHED
        (``_dispatch_batch_locked``): batch listeners — the journal's group
        commit, the device mirror's one-hold echo pass — get the drain's
        events in one call; per-event handlers still see every event in
        resourceVersion order. Returns {key: updated object | Exception} —
        per-key failures don't fail the batch."""
        out: Dict[str, object] = {}
        thrs = list(thrs)
        chunk = max(1, int(self.STATUS_WRITE_CHUNK))
        for s in range(0, len(thrs), chunk):
            events: List[Event] = []
            with self._lock:
                for thr in thrs[s : s + chunk]:
                    key = _key_of(kind, thr)
                    try:
                        current = self._objects[kind].get(key)
                        if current is None:
                            raise NotFoundError(f"{kind} {key!r} not found")
                        updated = current.with_status(thr.status)
                        self._rv += 1
                        self._objects[kind][key] = updated
                        self._versions[kind][key] = self._rv
                        events.append(
                            Event(
                                EventType.MODIFIED, kind, updated,
                                old_obj=current, rv=self._rv,
                            )
                        )
                        out[key] = updated
                    except Exception as e:  # noqa: BLE001 — reported per key
                        out[key] = e
                self._dispatch_batch_locked(events)
        return out

    def update_throttle_statuses(self, thrs) -> Dict[str, object]:
        """Batch form of update_throttle_status (no optimistic-concurrency
        arg: the reconcile loop re-reads on requeue anyway)."""
        return self._update_statuses_locked("Throttle", thrs)

    def update_cluster_throttle_statuses(self, thrs) -> Dict[str, object]:
        return self._update_statuses_locked("ClusterThrottle", thrs)

    def update_cluster_throttle_status(
        self, thr: ClusterThrottle, expected_version: Optional[int] = None
    ) -> ClusterThrottle:
        key = thr.name
        with self._lock:
            current = self._objects["ClusterThrottle"].get(key)
            if current is None:
                raise NotFoundError(f"ClusterThrottle {key!r} not found")
            if expected_version is not None and self._versions["ClusterThrottle"][key] != expected_version:
                raise ConflictError(f"ClusterThrottle {key!r} version changed")
            updated = current.with_status(thr.status)
            self._rv += 1
            self._objects["ClusterThrottle"][key] = updated
            self._versions["ClusterThrottle"][key] = self._rv
            self._dispatch_locked(
                Event(
                    EventType.MODIFIED, "ClusterThrottle", updated,
                    old_obj=current, rv=self._rv,
                )
            )
        return updated

    def resource_version(self, kind: str, key: str) -> int:
        with self._lock:
            return self._versions[kind][key]

    def advance_resource_version_to(self, rv: int) -> None:
        """Raise the global resourceVersion floor (never lowers it).
        Snapshot restore uses this so recovered state keeps pre-crash RV
        continuity — post-recovery writes must never reuse a version an
        old client already observed."""
        with self._lock:
            if rv > self._rv:
                self._rv = int(rv)

    @property
    def latest_resource_version(self) -> int:
        """The highest resourceVersion assigned so far (the list RV a
        wire-protocol LIST response reports). Inside an event handler this is
        exactly the dispatching event's RV — dispatch runs under the store
        lock right after the bump."""
        with self._lock:
            return self._rv
