"""The gang (pod-group) ledger: all-or-nothing multi-pod reserve/rollback
layered on the per-pod reservation ledger (engine/reservations.py).

A tightly-coupled multi-host job must start all ranks or none (PAPERS.md,
Rank-Aware MPI scheduling): admitting half a gang pins capacity that can
never run while starving jobs that could. The ledger provides the group
half of that contract:

- ``reserve_group`` adds EVERY member's reservation to the underlying
  per-kind ``ReservedResourceAmounts`` caches (which keep their own
  key-lock → global-lock order — the gang lock nests OUTSIDE them, so the
  per-pod paths are untouched), or rolls back the members already added
  when any add fails. The whole loop runs under the gang lock, which is
  the crash-atomicity hinge: the snapshot gather (engine/snapshot.py)
  captures the gang records AND the reservation caches under this same
  lock, so a snapshot can never observe a half-formed gang — recovered
  state is always fully-reserved or fully-rolled-back
  (tools/crashtest.py site ``crash.gang.partial_reserve`` proves it).
- ``reserve``/``rollback``/``commit`` are stamped into the journal as
  ``GANG`` control lines (engine/journal.py): no store effect, but
  recovery reads the begin-without-commit tail as a rollback order for
  any member reservation that somehow survived (defense in depth behind
  the lock-level atomicity), and operators get a durable audit trail of
  group admission.
- group TTLs ride PR 4's charge-then-rebase machinery: every member
  reservation carries the gang TTL, the group record keeps the deadline,
  ``snapshot_state`` serializes REMAINING seconds and ``restore_state``
  charges the dead time then rebases — a half-dead scheduler's gang can
  never pin capacity across a crash.

Member lifecycle after a successful reserve: the scheduler binds each
rank; the store's Pod events drive the record (``on_pod_event`` — a bound
member is *admitted*; a deleted pre-admission member rolls the WHOLE
group back, all-or-nothing both ways), and the controllers'
unreserve-on-observe handshake notifies ``note_unreserved`` as each
member's reservation is released into ``status.used``. When every member
is admitted the record retires (``groups_admitted_total``).

``sequential_gang_check`` is the per-pod ORACLE the batched feasibility
kernel (ops/gang_check.py) is property-tested against: admit members one
at a time through the reference 4-step check, counting earlier members as
reserved.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from datetime import datetime, timedelta
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..api.pod import Pod, accel_class_of
from ..api.types import CheckThrottleStatus, ResourceAmount, resource_amount_of_pod
from ..faults.plan import maybe_crash
from ..utils.clock import Clock, RealClock
from ..utils.lockorder import guard_attrs, make_rlock
from ..utils.tracing import vlog
from .reservations import TTL, _ttl_seconds
from .store import EventType

logger = logging.getLogger(__name__)

# member_keys shape: pod_key → {kind: [throttle_key, ...]}
MemberKeys = Dict[str, Dict[str, List[str]]]


@dataclass
class GangRecord:
    """One fully-reserved group awaiting admission of all its ranks."""

    group_key: str
    members: MemberKeys
    deadline: Optional[datetime] = None
    admitted: Set[str] = field(default_factory=set)
    # pods kept for rollback (remove needs only keys, but the device
    # mirror replay wants the amounts; keyed like members)
    pod_amounts: Dict[str, ResourceAmount] = field(default_factory=dict)


@guard_attrs
class GangLedger:
    """Group ledger over the per-kind reservation caches.

    Lock order: gang lock → reservation key/global locks (via the caches)
    and gang lock → devicestate main lock (via ``on_change``) and gang
    lock → journal lock (via the GANG stamps). The store lock, when
    involved, is always OUTSIDE the gang lock (store event dispatch →
    ``on_pod_event``; snapshot gather → ``lock``)."""

    GUARDED_BY = {
        "_groups": "self._lock",
        "_member_index": "self._lock",
    }

    def __init__(
        self,
        caches: Dict[str, object],  # {kind: ReservedResourceAmounts}
        clock: Optional[Clock] = None,
        on_change: Optional[Callable[[str, str], None]] = None,
        journal=None,
        faults=None,
        default_ttl: TTL = None,
    ):
        # RLock: on_pod_event → _rollback_locked nests fine, and the
        # snapshot gather may re-enter through cache callbacks
        self._lock = make_rlock("gang.ledger")
        self._caches = dict(caches)
        self._clock = clock or RealClock()
        self._on_change = on_change
        self.journal = journal
        self.faults = faults
        self.default_ttl = default_ttl
        self._groups: Dict[str, GangRecord] = {}
        self._member_index: Dict[str, str] = {}  # pod_key → group_key
        # single-writer counters (metrics/tests read these)
        self.groups_reserved_total = 0
        self.groups_admitted_total = 0
        self.groups_rolled_back_total = 0
        self.groups_expired_total = 0

    @property
    def lock(self):
        """The gang lock, exposed for the snapshot gather: holding it
        around the reservation-cache capture is what makes snapshots
        gang-atomic (module docstring)."""
        return self._lock

    def _notify(self, kind: str, throttle_key: str) -> None:
        if self._on_change is not None:
            self._on_change(kind, throttle_key)

    def _stamp(self, op: str, group_key: str, members: Optional[Sequence[str]] = None) -> None:
        if self.journal is not None:
            self.journal.append_gang(op, group_key, members=members)

    # -- reserve / rollback -------------------------------------------------

    def reserve_group(
        self,
        group_key: str,
        pods: Sequence[Pod],
        member_keys: MemberKeys,
        ttl: TTL = None,
    ) -> bool:
        """Atomically reserve every member on every matched throttle of
        both kinds. True on success; already-pending groups are idempotent
        True (a scheduler retry must not double-reserve). On any member
        failure the members already added are removed and the journal gets
        a rollback stamp — all-or-nothing, crash included (module
        docstring)."""
        ttl = ttl if ttl is not None else self.default_ttl
        ttl_s = _ttl_seconds(ttl)
        now = self._clock.now()
        with self._lock:
            self._purge_expired_locked(now)
            if group_key in self._groups:
                return True
            self._stamp("begin", group_key, members=sorted(p.key for p in pods))
            added: List[Tuple[str, str, str]] = []  # (kind, throttle_key, pod_key)
            record = GangRecord(
                group_key=group_key,
                members={p.key: dict(member_keys.get(p.key, {})) for p in pods},
                deadline=(
                    now + timedelta(seconds=ttl_s) if ttl_s is not None else None
                ),
            )
            try:
                for pod in pods:
                    record.pod_amounts[pod.key] = resource_amount_of_pod(pod)
                    for kind, keys in member_keys.get(pod.key, {}).items():
                        cache = self._caches[kind]
                        for key in keys:
                            # the mid-gang SIGKILL instant the crash matrix
                            # drives: some members reserved, the rest not
                            maybe_crash(self.faults, "crash.gang.partial_reserve")
                            if self.faults is not None:
                                self.faults.maybe_raise("gang.reserve.partial")
                            cache.add_pod(key, pod, ttl=ttl)
                            added.append((kind, key, pod.key))
                            self._notify(kind, key)
            except Exception:
                for kind, key, pod_key in reversed(added):
                    self._caches[kind].remove_pod_key(key, pod_key)
                    self._notify(kind, key)
                self._stamp("rollback", group_key)
                self.groups_rolled_back_total += 1
                logger.warning(
                    "gang %s: member reserve failed; rolled back %d "
                    "reservation(s)", group_key, len(added), exc_info=True,
                )
                return False
            self._groups[group_key] = record
            for pod_key in record.members:
                self._member_index[pod_key] = group_key
            self._stamp("commit", group_key)
            self.groups_reserved_total += 1
            vlog(3, "gang %s reserved: %d member(s)", group_key, len(pods))
            return True

    def rollback_group(self, group_key: str, reason: str = "rollback") -> bool:
        """Release every not-yet-admitted member reservation and retire the
        record. Admitted members' reservations are left to the normal
        unreserve-on-observe handshake (removing them early would reopen
        the double-count window the handshake closes)."""
        with self._lock:
            return self._rollback_locked(group_key, reason)

    def _rollback_locked(self, group_key: str, reason: str) -> bool:
        record = self._groups.pop(group_key, None)
        if record is None:
            return False
        for pod_key, kinds in record.members.items():
            self._member_index.pop(pod_key, None)
            if pod_key in record.admitted:
                continue
            for kind, keys in kinds.items():
                cache = self._caches[kind]
                for key in keys:
                    if cache.remove_pod_key(key, pod_key):
                        self._notify(kind, key)
        self._stamp("rollback", group_key)
        self.groups_rolled_back_total += 1
        vlog(3, "gang %s rolled back (%s)", group_key, reason)
        return True

    def drop_groups(self, group_keys) -> int:
        """Forget group records WITHOUT releasing member reservations —
        the live-resharding retire/abort path: the moved throttle keys'
        cache entries are dropped (or kept, on the surviving owner) by the
        same handoff step, so a rollback-style release here would
        double-free capacity the other shard now accounts. Returns the
        number of records removed."""
        dropped = 0
        with self._lock:
            for gk in list(group_keys):
                record = self._groups.pop(gk, None)
                if record is None:
                    continue
                dropped += 1
                for pod_key in record.members:
                    if self._member_index.get(pod_key) == gk:
                        del self._member_index[pod_key]
        return dropped

    # -- member lifecycle ---------------------------------------------------

    def on_pod_event(self, event) -> None:
        """Store Pod-event hook (registered by the plugin; runs under the
        store lock — order store → gang). A bound member is admitted; a
        deleted pre-admission member rolls the whole group back."""
        pod = event.obj
        with self._lock:
            group_key = self._member_index.get(pod.key)
            if group_key is None:
                return
            record = self._groups.get(group_key)
            if record is None:  # stale index entry
                self._member_index.pop(pod.key, None)
                return
            if event.type == EventType.DELETED:
                if pod.key in record.admitted:
                    # an admitted rank died: its reservations already
                    # released; the group record just forgets it
                    record.members.pop(pod.key, None)
                    record.admitted.discard(pod.key)
                    self._member_index.pop(pod.key, None)
                    self._maybe_complete_locked(group_key, record)
                else:
                    # a rank vanished before the gang started: the group
                    # can never run — free everything (all-or-nothing on
                    # the way out too)
                    self._rollback_locked(group_key, "member deleted")
                return
            if pod.is_scheduled() and pod.key not in record.admitted:
                record.admitted.add(pod.key)
                self._maybe_complete_locked(group_key, record)

    def note_unreserved(self, kind: str, throttle_key: str, pod_key: str) -> None:
        """Controller unreserve-on-observe hook: the member's reservation
        on ``throttle_key`` was just released into ``status.used`` — prune
        it from the record (a later rollback must not re-remove a live
        pod's worth of capacity) and count the member admitted."""
        with self._lock:
            group_key = self._member_index.get(pod_key)
            if group_key is None:
                return
            record = self._groups.get(group_key)
            if record is None:
                return
            keys = record.members.get(pod_key, {}).get(kind)
            if keys is not None and throttle_key in keys:
                keys.remove(throttle_key)
            if pod_key not in record.admitted:
                record.admitted.add(pod_key)
                self._maybe_complete_locked(group_key, record)

    def _maybe_complete_locked(self, group_key: str, record: GangRecord) -> None:
        if record.members and record.admitted >= set(record.members):
            self._groups.pop(group_key, None)
            for pod_key in record.members:
                self._member_index.pop(pod_key, None)
            self.groups_admitted_total += 1
            vlog(3, "gang %s fully admitted", group_key)

    # -- TTL expiry ---------------------------------------------------------

    def _purge_expired_locked(self, now: datetime) -> None:
        expired = [
            gk
            for gk, rec in self._groups.items()
            if rec.deadline is not None and rec.deadline <= now
        ]
        for gk in expired:
            self.groups_expired_total += 1
            self._rollback_locked(gk, "ttl expired")

    def purge_expired(self) -> None:
        with self._lock:
            self._purge_expired_locked(self._clock.now())

    # -- probes -------------------------------------------------------------

    def pending_groups(self) -> int:
        with self._lock:
            self._purge_expired_locked(self._clock.now())
            return len(self._groups)

    def group_record(self, group_key: str) -> Optional[GangRecord]:
        with self._lock:
            return self._groups.get(group_key)

    def is_member(self, pod_key: str) -> bool:
        with self._lock:
            return pod_key in self._member_index

    # -- snapshot / restore (engine/snapshot.py, engine/recovery.py) --------

    def snapshot_state(self, now: Optional[datetime] = None) -> Dict[str, dict]:
        """Serializable group records; TTLs as REMAINING seconds (the
        charge-then-rebase contract, engine/reservations.py). The snapshot
        gather calls this under ``self.lock`` (held around the reservation
        capture too), so the records and the member reservations describe
        one instant."""
        now = now or self._clock.now()
        with self._lock:
            out: Dict[str, dict] = {}
            for gk, rec in self._groups.items():
                if rec.deadline is not None and rec.deadline <= now:
                    continue  # a snapshot must never carry a dead gang
                out[gk] = {
                    "members": {
                        pk: {kind: list(keys) for kind, keys in kinds.items()}
                        for pk, kinds in rec.members.items()
                    },
                    "admitted": sorted(rec.admitted),
                    "ttlRemainingSeconds": (
                        (rec.deadline - now).total_seconds()
                        if rec.deadline is not None
                        else None
                    ),
                }
            return out

    def restore_state(
        self,
        state: Dict[str, dict],
        now: Optional[datetime] = None,
        elapsed_s: float = 0.0,
    ) -> Tuple[int, int]:
        """Rebuild group records from a snapshot payload. Each remaining
        TTL is charged the dead time then rebased on this clock; a group
        whose budget is spent is DROPPED — and its not-yet-admitted member
        reservations are removed from the caches (they were restored by
        ``restore_reservations`` moments earlier; a dead gang must not pin
        capacity). Returns ``(restored, dropped_expired)``."""
        now = now or self._clock.now()
        elapsed_s = max(0.0, float(elapsed_s))
        restored = dropped = 0
        with self._lock:
            for gk, entry in (state or {}).items():
                members: MemberKeys = {
                    pk: {kind: list(keys) for kind, keys in kinds.items()}
                    for pk, kinds in (entry.get("members") or {}).items()
                }
                remaining = entry.get("ttlRemainingSeconds")
                deadline = None
                if remaining is not None:
                    remaining = float(remaining) - elapsed_s
                    if remaining <= 0.0:
                        dropped += 1
                        self.groups_expired_total += 1
                        admitted = set(entry.get("admitted") or [])
                        for pk, kinds in members.items():
                            if pk in admitted:
                                continue
                            for kind, keys in kinds.items():
                                cache = self._caches[kind]
                                for key in keys:
                                    if cache.remove_pod_key(key, pk):
                                        self._notify(kind, key)
                        continue
                    deadline = now + timedelta(seconds=remaining)
                record = GangRecord(
                    group_key=gk,
                    members=members,
                    deadline=deadline,
                    admitted=set(entry.get("admitted") or []),
                )
                self._groups[gk] = record
                for pk in members:
                    self._member_index[pk] = gk
                restored += 1
        return restored, dropped

    def rollback_uncommitted(self, gang_ops: Dict[str, dict]) -> int:
        """Recovery's pass over the journal's GANG control lines
        (engine/journal.py ``gang_ops``): a group whose LAST stamped op is
        ``begin`` crashed mid-reserve, and one whose last op is
        ``rollback`` was released after the snapshot cut (reservation
        removals are not journaled, so the snapshot may still carry it) —
        either way, remove every member reservation of it that survived
        into the restored caches and drop any restored record. For the
        ``begin`` case this is defense in depth behind the gang lock's
        snapshot atomicity; for ``rollback`` it is the replay that brings
        the restored ledger forward to the journal's truth. Returns groups
        rolled back."""
        rolled = 0
        with self._lock:
            for gk, entry in (gang_ops or {}).items():
                if entry.get("op") not in ("begin", "rollback"):
                    continue
                record = self._groups.get(gk)
                if record is not None:
                    self._rollback_locked(gk, "journal begin without commit")
                    rolled += 1
                    continue
                members = entry.get("members") or []
                removed_any = False
                for pod_key in members:
                    for kind, cache in self._caches.items():
                        for key in list(cache.throttle_keys()):
                            if cache.remove_pod_key(key, pod_key):
                                self._notify(kind, key)
                                removed_any = True
                if removed_any:
                    self._stamp("rollback", gk)
                    self.groups_rolled_back_total += 1
                    rolled += 1
        return rolled


def sequential_gang_check(
    pods: Sequence[Pod],
    kind_controllers: Sequence[Tuple[str, object, bool]],
) -> Tuple[bool, Dict[str, List[str]]]:
    """The per-pod ORACLE batched gang feasibility must equal: admit the
    members ONE AT A TIME through the reference 4-step check, counting
    every earlier member as reserved on its matched throttles — exactly
    what a sequence of per-pod PreFilter+Reserve cycles would compute.
    ``kind_controllers`` is ``[(kind, controller, is_throttled_on_equal)]``
    (the controller supplies ``affected_throttles`` and its reservation
    ``cache``). Returns ``(feasible, {pod_key: [blocking "kind status
    throttle_key" strings]})``; side-effect-free (earlier members are
    accumulated in a local overlay, never the live caches)."""
    extra: Dict[Tuple[str, str], ResourceAmount] = {}
    blocked: Dict[str, List[str]] = {}
    feasible = True
    for pod in pods:
        accel = accel_class_of(pod)
        pod_blocks: List[str] = []
        matched: List[Tuple[str, object]] = []  # (kind, throttle) to charge
        for kind, ctr, on_equal in kind_controllers:
            for thr in ctr.affected_throttles(pod):
                matched.append((kind, thr))
                reserved, _ = ctr.cache.reserved_resource_amount(thr.key)
                overlay = extra.get((kind, thr.key))
                if overlay is not None:
                    reserved = reserved.add(overlay)
                status = thr.check_throttled_for(
                    pod, reserved, on_equal, accel_class=accel
                )
                if status != CheckThrottleStatus.NOT_THROTTLED:
                    pod_blocks.append(f"{kind}[{status}]={thr.key}")
        if pod_blocks:
            blocked[pod.key] = pod_blocks
            feasible = False
            continue  # keep collecting per-pod reasons; don't charge it
        amount = resource_amount_of_pod(pod)
        for kind, thr in matched:
            key = (kind, thr.key)
            extra[key] = (extra.get(key) or ResourceAmount()).add(amount)
    return feasible, blocked
