"""Crash recovery: snapshot restore + journal tail replay + reconcile.

The restore pipeline (standalone ``--data-dir`` mode, run before any store
handler exists):

1. **Pick a snapshot.** Walk ``snapshot-*.ktsnap`` newest-first; a file
   that fails the header/checksum/version gate (engine/snapshot.py) is
   counted in ``snapshots_rejected`` and the walk falls back to the next
   older one. Orphan ``*.tmp`` files (crash mid-snapshot-write) are swept.
2. **Tail or genesis.** A snapshot records the journal's ``(offset,
   sha256)`` at cut time. If the live journal's prefix up to that offset
   still hashes identically, the journal is a strict superset of the
   snapshot: apply the snapshot's objects and replay ONLY the tail
   (``journal_mode="tail"``). If the prefix no longer matches (the journal
   was compacted after the cut) the journal alone is the newest complete
   state: ignore the snapshot's objects and replay from genesis
   (``"genesis"`` — also the no-snapshot path). If the journal file is
   missing while a snapshot exists, the snapshot IS the state
   (``"snapshot-only"``): apply it and immediately compact the fresh
   journal so the log alone is complete again — the invariant after every
   recovery is *the journal by itself reproduces the store*.
   Either replay applies the journal's torn-tail rules: a torn FINAL line
   is truncated silently (normal crash artifact), interior corruption is
   skipped and counted (engine/journal.py).
3. **Reservations.** ``restore_reservations`` rebases snapshot TTLs
   against the restoring clock (never resurrecting expired entries) and
   replays restored keys into the device mirror's reserved rows.
4. **Reconcile.** After the plugin exists (SelectorIndex + devicestate
   planes rebuilt from the informer cache-sync replay), ``reconcile``
   compares the rebuilt published planes against the first informer-relist
   view of the statuses. Any mismatch is a divergence: counted, exported
   (kube_throttler_recovery_divergence_total), and REPAIRED by enqueueing
   the key — the controller recomputes the status from specs/pods and the
   write-echo refreshes the plane. The crash harness asserts this counter
   is zero for every seeded SIGKILL artifact.

The report lands in ``/readyz`` (health component ``recovery``) and the
recovery metric families (metrics.register_recovery_metrics).
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from ..utils.clock import Clock, RealClock
from .journal import StoreJournal, attach, hash_prefix
from .snapshot import SnapshotError, find_snapshots, load_snapshot
from .store import Store

logger = logging.getLogger(__name__)

JOURNAL_FILE = "store.journal"


@dataclass
class RecoveryReport:
    """What recovery did, for /readyz + metrics + the crash harness."""

    data_dir: str = ""
    snapshot_path: Optional[str] = None
    snapshot_seq: Optional[int] = None
    snapshot_taken_at: Optional[str] = None
    snapshot_objects: int = 0
    snapshots_rejected: int = 0
    tmp_files_swept: int = 0
    journal_mode: str = "none"  # "tail" | "genesis" | "snapshot-only" | "none"
    journal_lines_replayed: int = 0
    journal_interior_skipped: int = 0
    journal_torn_tails: int = 0
    reservations_restored: int = 0
    reservations_expired_dropped: int = 0
    gangs_restored: int = 0
    gangs_expired_dropped: int = 0
    gangs_rolled_back: int = 0  # journal begin-without-commit rollbacks
    preempts_rolled_back: int = 0  # uncommitted preemptions rolled back
    preempt_victims_restored: int = 0  # victim pods re-created by rollback
    epoch: int = 0  # highest fencing epoch found (snapshot header + journal)
    divergences: int = 0
    repaired_keys: List[str] = field(default_factory=list)
    snapshot_drift_keys: int = 0  # keys whose flags legitimately progressed
    duration_s: float = 0.0


class RecoveryManager:
    """One recovery run for one data directory. Single-threaded startup
    object — construct, ``recover_store``, then (after the plugin exists)
    ``restore_reservations`` + ``reconcile``; keep it around for the
    ``health_state`` probe."""

    def __init__(
        self,
        data_dir: str,
        clock: Optional[Clock] = None,
        faults=None,
        compact_after: int = 100_000,
    ):
        self.data_dir = data_dir
        self.clock = clock or RealClock()
        self.faults = faults
        self.compact_after = compact_after
        self.journal_path = os.path.join(data_dir, JOURNAL_FILE)
        self.report = RecoveryReport(data_dir=data_dir)
        self.snapshot: Optional[dict] = None  # payload actually used

    # -- step 1+2: snapshot + journal ---------------------------------------

    def _sweep_tmp_files(self) -> None:
        try:
            entries = os.listdir(self.data_dir)
        except OSError:
            return
        for name in entries:
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.data_dir, name))
                    self.report.tmp_files_swept += 1
                except OSError:  # pragma: no cover — racing cleaner
                    pass

    def _pick_snapshot(self) -> Optional[dict]:
        for _seq, path in find_snapshots(self.data_dir):
            try:
                payload = load_snapshot(path)
            except SnapshotError as e:
                self.report.snapshots_rejected += 1
                logger.warning("recovery: rejecting snapshot %s (%s)", path, e)
                continue
            self.report.snapshot_path = path
            self.report.snapshot_seq = payload.get("seq")
            self.report.snapshot_taken_at = payload.get("takenAt")
            return payload
        return None

    def _apply_snapshot_objects(self, store: Store, payload: dict) -> None:
        from ..api.serialization import object_from_dict

        # objects are serialized namespaces-first (snapshot._gather), so a
        # straight walk satisfies creation-order dependencies
        for d in payload.get("objects", []):
            obj = object_from_dict(d)
            kind = d.get("kind")
            if kind == "Namespace":
                store.create_namespace(obj)
            elif kind == "Throttle":
                store.create_throttle(obj)
            elif kind == "ClusterThrottle":
                store.create_cluster_throttle(obj)
            elif kind == "Pod":
                store.create_pod(obj)
            self.report.snapshot_objects += 1
        # v2 columnar pod block (pods absent from "objects"); v1 snapshots
        # (pre-bump fixtures and mixed-version restarts) recover through
        # the objects walk above — both paths land in the same store state
        block = payload.get("podColumns")
        if block:
            from .columnar import pods_from_columns

            for pod in pods_from_columns(block):
                store.create_pod(pod)
                self.report.snapshot_objects += 1
        store.advance_resource_version_to(int(payload.get("rv", 0)))

    def recover_store(self, store: Store) -> StoreJournal:
        """Restore ``store`` (freshly constructed, empty, no handlers) from
        the newest usable snapshot + journal tail, falling back per the
        module docstring. Returns the attached live journal."""
        t0 = time.monotonic()
        self._sweep_tmp_files()
        payload = self._pick_snapshot()
        mode = "genesis"
        start_offset, resume_hash = 0, None
        if payload is not None:
            jinfo = payload.get("journal")
            journal_exists = os.path.exists(self.journal_path)
            if jinfo is not None and journal_exists:
                h = hash_prefix(self.journal_path, int(jinfo.get("offset", -1)))
                if h is not None and h.hexdigest() == jinfo.get("sha256"):
                    mode = "tail"
                    start_offset, resume_hash = int(jinfo["offset"]), h
                else:
                    # journal compacted (or rewritten) after the cut: it is
                    # the newest complete state by itself — snapshot objects
                    # would resurrect things the compaction dropped
                    mode = "genesis"
            elif not journal_exists:
                mode = "snapshot-only"
            # jinfo None but journal exists → genesis (snapshot was cut
            # without a journal bound; the journal is the fuller record)
        if mode in ("tail", "snapshot-only"):
            self.snapshot = payload
            self._apply_snapshot_objects(store, payload)
        elif payload is not None:
            # snapshot skipped for objects, but reservations/published
            # planes still come from it (they are not in the journal)
            self.snapshot = payload
        journal = attach(
            store,
            self.journal_path,
            compact_after=self.compact_after,
            faults=self.faults,
            start_offset=start_offset,
            resume_hash=resume_hash,
        )
        if mode == "snapshot-only":
            # re-establish the invariant "the journal alone reproduces the
            # store": the fresh log would otherwise start empty and a later
            # genesis fallback would lose the snapshot's objects
            journal.compact()
        # uncommitted-preemption rollback (zero evictions, the GANG
        # contract's store-state mirror): full replays already rolled back
        # inside attach(); merging the snapshot's open-preempt payload
        # covers tail/snapshot-only modes whose anchor sits past the
        # PREEMPT begin line. Idempotent per id (rollback-stamped ids skip).
        from .journal import rollback_uncommitted_preempts

        extra_preempts = (payload or {}).get("preempts") or {}
        rollback_uncommitted_preempts(store, journal, extra_ops=extra_preempts)
        self.report.preempts_rolled_back = journal.preempts_rolled_back
        self.report.preempt_victims_restored = journal.preempt_victims_restored
        self.report.journal_mode = mode
        self.report.journal_lines_replayed = journal.replayed_events
        self.report.journal_interior_skipped = journal.replay_skipped
        self.report.journal_torn_tails = journal.torn_tails
        # the fencing high-water this data directory knows about: a
        # promoting standby (or restarting leader) must bump PAST it
        self.report.epoch = max(
            int((payload or {}).get("epoch") or 0), journal.last_epoch
        )
        self.report.duration_s = time.monotonic() - t0
        logger.info(
            "recovery: mode=%s snapshot=%s objects=%d journal_lines=%d "
            "interior_skipped=%d torn_tails=%d rejected=%d (%.3fs)",
            mode, self.report.snapshot_path, self.report.snapshot_objects,
            self.report.journal_lines_replayed,
            self.report.journal_interior_skipped, self.report.journal_torn_tails,
            self.report.snapshots_rejected, self.report.duration_s,
        )
        return journal

    # -- step 3: reservations ----------------------------------------------

    def restore_reservations(
        self,
        caches: Mapping[str, object],
        on_change: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        """Rebase + restore the snapshot's reservation ledgers into
        ``caches`` ({kind: ReservedResourceAmounts}). The dead time between
        the snapshot cut and now is charged against every TTL (the
        scheduler that held a reservation did not survive the crash), then
        the remainder is rebased onto the restoring clock — so neither
        wall-time progression while dead nor clock skew between runs can
        resurrect an expired reservation. ``on_change(kind, throttle_key)``
        replays each touched key into the device mirror (the CLI passes
        device_manager.on_reservation_change)."""
        if self.snapshot is None:
            return
        state = self.snapshot.get("reservations") or {}
        now = self.clock.now()
        elapsed_s = 0.0
        taken_at = self.snapshot.get("takenAt")
        if taken_at:
            from datetime import datetime

            try:
                taken = datetime.fromisoformat(taken_at)
                if taken.tzinfo is None and now.tzinfo is not None:
                    taken = taken.replace(tzinfo=now.tzinfo)
                elapsed_s = max(0.0, (now - taken).total_seconds())
            except (ValueError, TypeError):  # pragma: no cover — we wrote it
                pass
        for kind, cache in caches.items():
            restored, dropped, touched = cache.restore_state(
                state.get(kind) or {}, now=now, elapsed_s=elapsed_s
            )
            self.report.reservations_restored += restored
            self.report.reservations_expired_dropped += dropped
            if on_change is not None:
                for throttle_key in touched:
                    on_change(kind, throttle_key)
        if self.report.reservations_restored or self.report.reservations_expired_dropped:
            logger.info(
                "recovery: %d reservation(s) restored with rebased TTLs, "
                "%d expired one(s) dropped",
                self.report.reservations_restored,
                self.report.reservations_expired_dropped,
            )

    def restore_gangs(self, ledger, journal: Optional[StoreJournal] = None) -> None:
        """Rebuild the gang ledger (engine/gang.py) from the snapshot's
        group records — group TTLs get the same charge-then-rebase
        treatment as reservations, and an expired group's surviving member
        reservations are removed (all-or-nothing across the crash). Then
        the journal's GANG control-line tail is applied: a group whose
        last stamp is ``begin`` (no commit) crashed mid-reserve and is
        rolled back — the defense-in-depth half behind the gang lock's
        snapshot atomicity. Call AFTER ``restore_reservations``."""
        state = (self.snapshot or {}).get("gangs") or {}
        now = self.clock.now()
        elapsed_s = 0.0
        taken_at = (self.snapshot or {}).get("takenAt")
        if taken_at:
            from datetime import datetime

            try:
                taken = datetime.fromisoformat(taken_at)
                if taken.tzinfo is None and now.tzinfo is not None:
                    taken = taken.replace(tzinfo=now.tzinfo)
                elapsed_s = max(0.0, (now - taken).total_seconds())
            except (ValueError, TypeError):  # pragma: no cover — we wrote it
                pass
        restored, dropped = ledger.restore_state(state, now=now, elapsed_s=elapsed_s)
        self.report.gangs_restored += restored
        self.report.gangs_expired_dropped += dropped
        if journal is not None:
            self.report.gangs_rolled_back += ledger.rollback_uncommitted(
                journal.gang_ops
            )
        if restored or dropped or self.report.gangs_rolled_back:
            logger.info(
                "recovery: %d gang(s) restored, %d expired dropped, %d "
                "uncommitted rolled back",
                restored, dropped, self.report.gangs_rolled_back,
            )

    # -- step 4: reconcile ---------------------------------------------------

    @staticmethod
    def _flags_of_status(thr) -> dict:
        flags = thr.status.throttled
        return {
            "pod": bool(flags.resource_counts_pod),
            "requests": {
                str(k): bool(v) for k, v in (flags.resource_requests or {}).items()
            },
        }

    def reconcile(
        self,
        informers,
        device_manager=None,
        enqueue: Optional[Mapping[str, Callable[[str], None]]] = None,
    ) -> int:
        """First-relist reconcile: the rebuilt published ``st_*`` planes
        must agree with the statuses the informer caches carry — any
        mismatch is a recovery divergence: counted, logged, and repaired by
        enqueueing the key for a fresh reconcile. Also counts (detail only,
        not a divergence) keys whose flags progressed past the snapshot —
        the journal tail legitimately outruns the snapshot's planes.
        Returns the divergence count."""
        kinds = {
            "throttle": informers.throttles(),
            "clusterthrottle": informers.cluster_throttles(),
        }
        planes = (
            device_manager.published_flags() if device_manager is not None else None
        )
        snap_published = (self.snapshot or {}).get("published") or {}
        divergences = 0
        for kind, informer in kinds.items():
            relisted = informer.snapshot_objects()
            expected = {
                key: self._flags_of_status(thr) for key, thr in relisted.items()
            }
            if planes is not None:
                plane = planes.get(kind, {})
                for key, want in expected.items():
                    got = plane.get(key)
                    if got != want:
                        divergences += 1
                        logger.warning(
                            "recovery divergence: %s %s plane=%r status=%r — "
                            "re-enqueueing for repair", kind, key, got, want,
                        )
                        self.report.repaired_keys.append(f"{kind}/{key}")
                        if enqueue is not None and kind in enqueue:
                            enqueue[kind](key)
            snap_kind = snap_published.get(kind) or {}
            self.report.snapshot_drift_keys += sum(
                1
                for key, flags in snap_kind.items()
                if key in expected and expected[key] != flags
            )
        self.report.divergences += divergences
        return divergences

    # -- probes -------------------------------------------------------------

    def snapshot_age_seconds(self) -> Optional[float]:
        """Age of the snapshot recovery restored from (None when recovery
        ran without one)."""
        if self.report.snapshot_taken_at is None:
            return None
        from datetime import datetime

        try:
            taken = datetime.fromisoformat(self.report.snapshot_taken_at)
        except ValueError:  # pragma: no cover — snapshot wrote isoformat
            return None
        now = self.clock.now()
        if taken.tzinfo is None and now.tzinfo is not None:
            taken = taken.replace(tzinfo=now.tzinfo)
        return max(0.0, (now - taken).total_seconds())

    def health_state(self) -> Tuple[str, dict]:
        """Health component (health.py): degraded when recovery had to
        reject a corrupt snapshot or found plane divergences — it still
        serves (older snapshot / genesis replay / repair enqueued), but the
        operator should know the crash left marks."""
        r = self.report
        age = self.snapshot_age_seconds()
        detail = {
            "mode": r.journal_mode,
            "snapshotSeq": r.snapshot_seq,
            "snapshotAgeSeconds": round(age, 3) if age is not None else None,
            "snapshotsRejected": r.snapshots_rejected,
            "journalLinesReplayed": r.journal_lines_replayed,
            "journalInteriorSkipped": r.journal_interior_skipped,
            "journalTornTails": r.journal_torn_tails,
            "reservationsRestored": r.reservations_restored,
            "reservationsExpiredDropped": r.reservations_expired_dropped,
            "gangsRestored": r.gangs_restored,
            "gangsExpiredDropped": r.gangs_expired_dropped,
            "gangsRolledBack": r.gangs_rolled_back,
            "preemptsRolledBack": r.preempts_rolled_back,
            "preemptVictimsRestored": r.preempt_victims_restored,
            "reconcileDivergences": r.divergences,
            "durationSeconds": round(r.duration_s, 4),
        }
        degraded = bool(r.snapshots_rejected or r.divergences)
        return ("degraded" if degraded else "ok"), detail
