"""Embedded scheduler loop — the analog of the reference's in-binary
kube-scheduler (cmd/kube_scheduler.go:90-106 registers the plugin into the
upstream scheduler app; integration tests then run `scheduler.Setup +
go scheduler.Run` in-process, integration_suite_test.go:87-138).

The framework is standalone, so this module supplies the scheduling loop
the plugin plugs into:

- a pending queue (active / backoff / unschedulable, mirroring the
  scheduler's three-queue structure);
- ``schedule_one``: pop → PreFilter → pick node → Reserve → bind
  (write ``spec.nodeName`` back through the store) → Unreserve on failure;
- event-driven requeue per the plugin's ``EventsToRegister`` hints
  (plugin.go:263-279): Throttle/ClusterThrottle/Pod/Node changes move
  unschedulable pods back to the active queue, subject to per-pod
  exponential backoff (the reference integration suite pokes a Node to
  force exactly this wakeup, util_pod_test.go:206-225);
- ``FailedScheduling`` Warning events with the plugin's reason string, the
  same observable the reference's tests assert on (util_pod_test.go:156-180).

Binding sets only ``spec.nodeName`` (phase stays Pending) — that is the
reference's ``shouldCountIn`` trigger (scheduled ∧ not finished,
pod_util.go:300-306), so a bound pod immediately counts into
``status.used`` at the next reconcile and its reservation is released by
the unreserve-on-observe handshake.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from .api.pod import Pod, PodGroup, pod_group_of, priority_of
from .quantity import parse_quantity
from .resourcelist import add as rl_add, pod_request_resource_list, sub as rl_sub
from .utils.lockorder import assert_held, guard_attrs, make_condition, make_lock
from .utils.tracing import vlog
from .engine.store import Event, EventType, Store
from .plugin.plugin import KubeThrottler

logger = logging.getLogger(__name__)


@dataclass
class Node:
    """Node model: pod-count capacity (the integration fixture is one node
    with max-pods 300 — hack/integration/kind.conf) plus optional
    allocatable resources. With ``allocatable`` set, binding also requires
    the pod's effective requests to fit the remaining capacity per declared
    dimension (the NodeResourcesFit analog of the embedded kube-scheduler
    the reference relies on); a NONZERO request for an undeclared resource
    never fits (zero requests are skipped, as NodeResourcesFit does).
    ``allocatable=None`` keeps the resource-blind behavior."""

    name: str
    max_pods: int = 300
    allocatable: Optional[Dict[str, str]] = None


@dataclass
class _QueuedPod:
    key: str
    attempts: int = 0
    not_before: float = 0.0  # monotonic gate for backoff
    # preemption-ordered admission (docs/gang_admission.md): when capacity
    # opens, eligible candidates are drained highest priority first, ties
    # oldest first — not in arbitrary queue order
    priority: int = 0
    enqueued_at: float = 0.0  # monotonic first-enqueue time (the age tiebreak)


@guard_attrs
class Scheduler:
    """Single-threaded scheduling loop over the store's pending pods.

    Synchronous driving (tests/examples): ``run_until_idle()``.
    Daemon mode: ``start()`` spawns the loop thread; ``stop()`` joins it.
    """

    FAILED_SCHEDULING = "FailedScheduling"

    # queue state and node-occupancy ledgers move only under the single
    # scheduler lock (always taken through the `_cv` condition over it)
    GUARDED_BY = {
        "_active": "self._cv",
        "_unschedulable": "self._cv",
        "_queued_keys": "self._cv",
        "_wake_gen": "self._cv",
        "_bound_per_node": "self._cv",
        "_alloc_used": "self._cv",
    }

    def __init__(
        self,
        plugin: KubeThrottler,
        store: Store,
        nodes: Optional[List[Node]] = None,
        backoff_base: float = 0.05,
        backoff_max: float = 5.0,
    ) -> None:
        self.plugin = plugin
        self.store = store
        self.nodes = list(nodes) if nodes else [Node("node-1")]
        self._bound_per_node: Dict[str, int] = {n.name: 0 for n in self.nodes}
        # resource accounting (only maintained/consulted for nodes that
        # declare allocatable — the default path stays Fraction-free)
        self._alloc_cap = {
            n.name: (
                {r: parse_quantity(v) for r, v in n.allocatable.items()}
                if n.allocatable is not None
                else None
            )
            for n in self.nodes
        }
        self._alloc_used: Dict[str, Dict] = {n.name: {} for n in self.nodes}
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max

        self._lock = make_lock("scheduler")
        self._cv = make_condition(self._lock)
        self._active: List[_QueuedPod] = []
        self._unschedulable: Dict[str, _QueuedPod] = {}
        self._queued_keys: set = set()
        # bumped on every requeue hint; a cycle that started before the bump
        # re-queues to active instead of parking (closes the window where a
        # wake lands while its pod is popped but not yet parked)
        self._wake_gen = 0
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

        target = plugin.args.target_scheduler_name
        self._target = target

        # node occupancy is driven ENTIRELY by pod events (replay covers
        # pre-existing pods): a pod occupies a node slot while bound and not
        # finished; deletes and terminal phases free the slot. schedule_one
        # does NOT increment directly — its bind write's MODIFIED event does,
        # synchronously on the same thread, so there is no double count.
        store.add_event_handler("Pod", self._on_pod_event, replay=True)
        # EventsToRegister: throttle/clusterthrottle/namespace changes retry
        # unschedulable pods (plugin.go:263-279; Node changes would too, but
        # nodes live outside the store — poke_nodes() covers that hint)
        for kind in ("Throttle", "ClusterThrottle", "Namespace"):
            store.add_event_handler(kind, self._on_cluster_event, replay=False)

    # -- queue management --------------------------------------------------

    def _track_usage_locked(self, node_name: Optional[str], pod: Optional[Pod], sign: int) -> None:
        """Adjust a node's used-resources ledger — no-op for resource-blind
        nodes, keeping the hot event path free of Fraction work."""
        assert_held(self._lock, "Scheduler._track_usage_locked")
        if pod is None or node_name is None or self._alloc_cap.get(node_name) is None:
            return
        (rl_add if sign > 0 else rl_sub)(
            self._alloc_used[node_name], pod_request_resource_list(pod)
        )

    def _is_schedulable_target(self, pod: Pod) -> bool:
        # reads only immutable-after-init config (self._target) + the pod —
        # deliberately callable with or without the scheduler lock
        return (
            pod.spec.scheduler_name == self._target
            and not pod.is_scheduled()
            and pod.is_not_finished()
        )

    def _occupies_node_locked(self, pod: Optional[Pod]) -> Optional[str]:
        """Node name this pod holds a slot on, or None."""
        assert_held(self._lock, "Scheduler._occupies_node_locked")
        if pod is None or not pod.is_scheduled() or not pod.is_not_finished():
            return None
        return pod.spec.node_name if pod.spec.node_name in self._bound_per_node else None

    def _on_pod_event(self, event: Event) -> None:
        pod = event.obj
        if event.type == EventType.DELETED:
            with self._cv:
                freed = self._occupies_node_locked(pod)
                if freed is not None:
                    self._bound_per_node[freed] -= 1
                    self._track_usage_locked(freed, pod, -1)
                self._queued_keys.discard(pod.key)
                self._unschedulable.pop(pod.key, None)
                self._active = [q for q in self._active if q.key != pod.key]
            # a delete frees node capacity and throttle usage — it is a
            # requeue hint like any other Pod event (EventsToRegister)
            self._wake_unschedulable()
            return
        if event.type == EventType.ADDED:
            with self._cv:
                held = self._occupies_node_locked(pod)
                if held is not None:
                    self._bound_per_node[held] += 1
                    self._track_usage_locked(held, pod, +1)
                elif self._is_schedulable_target(pod) and pod.key not in self._queued_keys:
                    self._queued_keys.add(pod.key)
                    self._active.append(
                        _QueuedPod(
                            pod.key,
                            priority=priority_of(pod),
                            enqueued_at=time.monotonic(),
                        )
                    )
                    self._cv.notify_all()
            # a new pod is a requeue hint too (EventsToRegister lists Pod
            # events): a parked gang member may only need this arrival to
            # complete its group
            self._wake_unschedulable()
            return
        # MODIFIED: adjust occupancy for bind/unbind/termination transitions
        # AND in-place request edits (same node, different requests), then
        # treat the change as a requeue hint for unschedulable pods
        new_priority = priority_of(pod)
        priority_changed = (
            event.old_obj is None or priority_of(event.old_obj) != new_priority
        )
        with self._cv:
            before = self._occupies_node_locked(event.old_obj)
            after = self._occupies_node_locked(pod)
            if before != after:
                if before is not None:
                    self._bound_per_node[before] -= 1
                if after is not None:
                    self._bound_per_node[after] += 1
            self._track_usage_locked(before, event.old_obj, -1)
            self._track_usage_locked(after, pod, +1)
            if priority_changed and pod.key in self._queued_keys:
                # stale-priority requeue fix: a priority-annotation update
                # re-orders already-queued work — candidate selection reads
                # the queued entry's priority, so rewrite it in place (the
                # workqueue hi lane re-orders the same way)
                for q in self._active:
                    if q.key == pod.key:
                        q.priority = new_priority
                        break
                parked = self._unschedulable.get(pod.key)
                if parked is not None:
                    parked.priority = new_priority
        self._wake_unschedulable()

    def _on_cluster_event(self, event: Event) -> None:
        self._wake_unschedulable()

    def _wake_unschedulable(self) -> None:
        with self._cv:
            # bump even when nothing is parked: a mid-cycle pod checks this
            # generation before parking itself
            self._wake_gen += 1
            if not self._unschedulable:
                return
            for q in self._unschedulable.values():
                self._active.append(q)
            self._unschedulable.clear()
            self._cv.notify_all()

    def poke_nodes(self) -> None:
        """The Node-change requeue hint (the reference tests' WakeupBackoffPod
        node-poke, util_pod_test.go:206-225)."""
        self._wake_unschedulable()

    def _backoff_for(self, attempts: int) -> float:
        return min(self._backoff_base * (2 ** max(attempts - 1, 0)), self._backoff_max)

    # -- the scheduling cycle ---------------------------------------------

    def _fits_resources_locked(self, node: Node, req) -> bool:
        """NodeResourcesFit: every requested dimension must be declared in
        the node's allocatable and leave headroom. Resource-blind when the
        node declares no allocatable."""
        assert_held(self._lock, "Scheduler._fits_resources_locked")
        cap = self._alloc_cap[node.name]
        if cap is None:
            return True
        used = self._alloc_used[node.name]
        for resource, q in req.items():
            if q == 0:
                continue  # NodeResourcesFit skips zero requests
            limit = cap.get(resource)
            if limit is None or used.get(resource, 0) + q > limit:
                return False
        return True

    def _pick_node(self, pod: Pod) -> Optional[Node]:
        req = pod_request_resource_list(pod)
        with self._cv:
            for node in self.nodes:
                if self._bound_per_node[node.name] < node.max_pods and self._fits_resources_locked(
                    node, req
                ):
                    return node
        return None

    def schedule_one(self, now: Optional[float] = None) -> Optional[str]:
        """Run one scheduling cycle. Returns the bound pod's key (a gang
        cycle returns the triggering member's key after binding the whole
        group), or None if nothing was schedulable (queue empty or all
        gated by backoff).

        Candidate selection is preemption-ordered: among backoff-eligible
        queued pods, highest priority first, ties oldest-first — so when
        capacity opens the drain order is (priority, age), not whatever
        order the queue happened to accumulate."""
        now = time.monotonic() if now is None else now
        with self._cv:
            idx = None
            best = None
            for i, q in enumerate(self._active):
                if q.not_before > now:
                    continue
                rank = (-q.priority, q.enqueued_at)
                if best is None or rank < best:
                    best = rank
                    idx = i
            if idx is None:
                return None
            queued = self._active.pop(idx)
            gen = self._wake_gen
        try:
            pod = self.store.get_pod(*queued.key.split("/", 1))
        except KeyError:
            with self._cv:
                self._queued_keys.discard(queued.key)
            return None
        if not self._is_schedulable_target(pod):
            with self._cv:
                self._queued_keys.discard(queued.key)
            return None

        queued.attempts += 1
        group = pod_group_of(pod)
        if group is not None:
            return self._schedule_gang(queued, pod, group, now, gen)
        status = self.plugin.pre_filter(pod)
        if not status.is_success():
            self._record_failed_scheduling(pod, status.message())
            self._park(queued, now, gen)
            return None

        node = self._pick_node(pod)
        if node is None:
            self._record_failed_scheduling(pod, "0/%d nodes are available" % len(self.nodes))
            self._park(queued, now, gen)
            return None

        reserve_status = self.plugin.reserve(pod, node.name)
        if not reserve_status.is_success():
            self.plugin.unreserve(pod, node.name)
            self._park(queued, now, gen)
            return None

        try:
            # atomic bind: set ONLY spec.nodeName on the store's current
            # object (the bind-subresource analog) — a whole-object write of
            # the pod read at cycle start would revert any patch that landed
            # mid-cycle. Occupancy increments via the write's MODIFIED event.
            self.store.mutate(
                "Pod",
                pod.key,
                lambda cur: replace(cur, spec=replace(cur.spec, node_name=node.name)),
            )
        except Exception:
            logger.exception("bind failed for %s", pod.key)
            self.plugin.unreserve(pod, node.name)
            self._park(queued, now, gen)
            return None

        with self._cv:
            self._queued_keys.discard(queued.key)
        vlog(3, "scheduled %s -> %s", pod.key, node.name)
        return pod.key

    # -- gang scheduling ---------------------------------------------------

    def _gang_members(self, group: PodGroup, namespace: str) -> List[Pod]:
        """Pending schedulable members of ``group`` in ``namespace``,
        name-sorted for a deterministic admission set."""
        members = [
            p
            for p in self.store.list_pods(namespace)
            if self._is_schedulable_target(p)
            and (g := pod_group_of(p)) is not None
            and g.key == group.key
        ]
        members.sort(key=lambda p: p.name)
        return members

    def _pick_nodes_for(self, pods: List[Pod]) -> Optional[List[Node]]:
        """Greedy all-members placement with TENTATIVE occupancy: either
        every member gets a node (respecting max-pods and declared
        allocatable against the members placed before it) or the whole
        placement fails — the node-capacity half of all-or-nothing.

        Rank-aware contiguity (policy.rankAwarePlacement, on by default —
        the MPI-locality hint of docs/policy.md): the node list IS the
        topology order (racks/hosts enumerate adjacently), so each rank
        prefers its predecessor's node, then the nearest index, among the
        FEASIBLE nodes only. Feasibility is unchanged — a gang that fit
        under first-fit still fits here; what changes is that a multi-host
        gang stops fragmenting across distant nodes when a near one has
        room. Rank 0 (and the scoring-off path) keeps the original
        lowest-index first-fit."""
        rank_aware = self._placement_rank_aware()
        node_idx = {n.name: i for i, n in enumerate(self.nodes)}
        with self._cv:
            counts = dict(self._bound_per_node)
            used = {
                name: dict(self._alloc_used[name])
                for name, cap in self._alloc_cap.items()
                if cap is not None
            }
            out: List[Node] = []
            prev_idx: Optional[int] = None
            for pod in pods:
                req = pod_request_resource_list(pod)
                chosen = None
                best_score = None
                for node in self.nodes:
                    if counts[node.name] >= node.max_pods:
                        continue
                    cap = self._alloc_cap[node.name]
                    if cap is not None:
                        u = used[node.name]
                        if any(
                            q != 0
                            and (cap.get(r) is None or u.get(r, 0) + q > cap[r])
                            for r, q in req.items()
                        ):
                            continue
                    if not rank_aware or prev_idx is None:
                        chosen = node
                        break  # original first-fit
                    idx = node_idx[node.name]
                    score = (0 if idx == prev_idx else 1, abs(idx - prev_idx), idx)
                    if best_score is None or score < best_score:
                        best_score = score
                        chosen = node
                if chosen is None:
                    return None
                counts[chosen.name] += 1
                if self._alloc_cap[chosen.name] is not None:
                    rl_add(used[chosen.name], req)
                out.append(chosen)
                prev_idx = node_idx[chosen.name]
            return out

    def _placement_rank_aware(self) -> bool:
        """The active policy's rankAwarePlacement knob (docs/policy.md) —
        True when the plugin carries no policy engine (the default spec's
        value)."""
        policy = getattr(self.plugin, "policy", None)
        if policy is None:
            return True
        try:
            return bool(policy.active().rank_aware_placement)
        except Exception:  # pragma: no cover — a policy bug must not stop binds
            return True

    def _schedule_gang(
        self, queued: _QueuedPod, pod: Pod, group: PodGroup, now: float, gen: int
    ) -> Optional[str]:
        """One gang admission cycle, triggered by ANY member's pop:
        gather the pending members → group PreFilter (one batched
        feasibility dispatch) → place every rank → atomic group Reserve →
        bind all ranks. Any failure before the binds parks the triggering
        member with the whole group unreserved (all-or-nothing)."""
        members = self._gang_members(group, pod.namespace)
        if len(members) < group.size:
            self._record_failed_scheduling(
                pod,
                f"gang {group.key}: waiting for members "
                f"({len(members)}/{group.size} present)",
            )
            self._park(queued, now, gen)
            return None
        members = members[: group.size]

        status = self.plugin.pre_filter_gang(group.key, members)
        if not status.is_success():
            self._record_failed_scheduling(pod, status.message())
            if status.is_unschedulable():
                # gang-aware preemption (docs/policy.md): a capacity
                # rejection may be resolvable by evicting lower-priority
                # running work. Eviction is delete-then-requeue — the
                # DELETED events free node slots and used sums, the freed-
                # capacity flips publish through the priority lane, and
                # the deletes themselves are requeue hints — so this cycle
                # just parks; the wake generation check below keeps the
                # group active when victims were actually evicted.
                self.plugin.maybe_preempt_gang(group.key, members)
            self._park(queued, now, gen)
            return None

        nodes = self._pick_nodes_for(members)
        if nodes is None:
            self._record_failed_scheduling(
                pod,
                "0/%d nodes can place all %d ranks of gang %s"
                % (len(self.nodes), group.size, group.key),
            )
            self._park(queued, now, gen)
            return None

        reserve_status = self.plugin.reserve_gang(group.key, members)
        if not reserve_status.is_success():
            self.plugin.unreserve_gang(group.key)
            self._park(queued, now, gen)
            return None

        for member, node in zip(members, nodes):
            try:
                self.store.mutate(
                    "Pod",
                    member.key,
                    lambda cur, n=node.name: replace(
                        cur, spec=replace(cur.spec, node_name=n)
                    ),
                )
            except Exception:
                logger.exception(
                    "gang %s: bind failed for %s; releasing the group reserve",
                    group.key, member.key,
                )
                # already-bound ranks are admitted (their reservations ride
                # the normal unreserve-on-observe handshake); the rest of
                # the group's reserve is released together
                self.plugin.unreserve_gang(group.key)
                self._park(queued, now, gen)
                return None

        with self._cv:
            for member in members:
                self._queued_keys.discard(member.key)
                self._unschedulable.pop(member.key, None)
            member_keys = {m.key for m in members}
            self._active = [q for q in self._active if q.key not in member_keys]
        vlog(3, "gang %s scheduled: %d rank(s)", group.key, len(members))
        return pod.key

    def _park(self, queued: _QueuedPod, now: float, gen: Optional[int] = None) -> None:
        # a sync drain passes now=inf to bypass backoff gates; anchor the
        # backoff to the real clock so the pod isn't gated forever once a
        # real-time loop takes over
        base = now if math.isfinite(now) else time.monotonic()
        queued.not_before = base + self._backoff_for(queued.attempts)
        with self._cv:
            if gen is not None and gen != self._wake_gen:
                # a requeue hint fired while this pod was mid-cycle; parking
                # now would miss it — keep the pod active (backoff-gated)
                self._active.append(queued)
                self._cv.notify_all()
                return
            self._unschedulable[queued.key] = queued

    def _record_failed_scheduling(self, pod: Pod, message: str) -> None:
        if self.plugin.event_recorder is not None:
            self.plugin.event_recorder.eventf(
                pod.key, "Warning", self.FAILED_SCHEDULING, "Scheduling", message
            )

    # -- driving -----------------------------------------------------------

    def run_until_idle(self, max_cycles: int = 10_000, settle: bool = True) -> int:
        """Synchronously drain the queue: reconcile controllers and schedule
        until neither makes progress. Backoff gates are ignored (tests drive
        wall-clock-free). Returns the number of pods bound."""
        bound = 0
        for _ in range(max_cycles):
            progressed = False
            if self.plugin.run_pending_once():
                progressed = True
            # far-future "now" neutralizes backoff gating for sync draining
            key = self.schedule_one(now=float("inf")) if settle else self.schedule_one()
            if key is not None:
                bound += 1
                progressed = True
            if not progressed:
                with self._cv:
                    if not self._active:
                        break
                    # only backoff-parked actives remain and settle is off
                    if not settle:
                        break
        return bound

    def pending_count(self) -> int:
        with self._cv:
            return len(self._active) + len(self._unschedulable)

    def start(self, poll_interval: float = 0.01, flush_interval: float = 5.0) -> None:
        """``flush_interval``: unschedulable pods are periodically re-queued
        even without a triggering event (kube-scheduler's
        flushUnschedulablePodsLeftover analog) — the safety net under the
        event-driven wakeups; backoff gates still apply after a flush.

        The loop sleeps on the condition variable until the next backoff
        gate or the flush deadline — an idle scheduler makes no wakeups
        (new work notifies the condition). ``poll_interval`` is retained
        for signature compatibility; it no longer drives a poll."""
        del poll_interval  # superseded by event-driven waits
        if self._thread is not None:
            return
        self._stop_event.clear()

        def loop() -> None:
            last_flush = time.monotonic()
            while not self._stop_event.is_set():
                # loop-level routing (threads checker): a scheduling bug
                # must not silently stop the scheduler loop for good
                try:
                    key = self.schedule_one()
                    now = time.monotonic()
                    if now - last_flush >= flush_interval:
                        last_flush = now
                        self._wake_unschedulable()
                        continue
                    if key is None:
                        with self._cv:
                            if self._stop_event.is_set():
                                return
                            gates = [q.not_before for q in self._active]
                            if any(g <= now for g in gates):
                                continue  # work arrived between cycle and here
                            next_gate = min((g for g in gates if g > now), default=None)
                            deadline = last_flush + flush_interval
                            wake_at = deadline if next_gate is None else min(next_gate, deadline)
                            self._cv.wait(timeout=max(wake_at - now, 0.0))
                except Exception:  # noqa: BLE001 — keep scheduling
                    logger.exception("scheduler loop error")
                    self._stop_event.wait(0.1)

        self._thread = threading.Thread(target=loop, name="scheduler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_event.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
