"""Time-varying threshold resolution on device.

``calculate_threshold`` (reference throttle_types.go:65-106) picks, at time
``now``, the first-active override per dimension; if ANY override is active
the merged result REPLACES the whole spec threshold (dims absent from the
merge become absent). Overrides whose RFC3339 strings fail to parse are
skipped (messages are host-side static data — they depend only on the spec).

Encoded as a padded override schedule: [T,O] begin/end nanosecond bounds
(±int64 sentinels for open ends / parse errors) plus per-override threshold
tensors. Resolution is a pure function of ``now_ns`` — the 100k×10k
overrides bench config recomputes every throttle's effective threshold in
one kernel launch, no host loop.

First-wins semantics vectorize as a cumsum one-hot over the override axis:
``cand ∧ (running count == 1)`` marks exactly the FIRST True slot, matching
the Go loop's iteration order (throttle_types.go:76-95); a masked sum then
extracts that slot's value with elementwise + reduce ops only (no
argmax/gather — slow int64 paths on TPU).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from typing import Mapping

from ..api.types import RFC3339ParseError, ThrottleSpecBase
from ..quantity import to_milli
from .schema import DimRegistry

NS_MIN = np.int64(np.iinfo(np.int64).min)
NS_MAX = np.int64(np.iinfo(np.int64).max)

_EPOCH = None


def _datetime_to_ns(dt) -> np.int64:
    """Exact integer nanoseconds since epoch, clamped to int64.

    ``int(dt.timestamp() * 1e9)`` both overflows for far-future dates (year
    9999 'never expires' values are valid RFC3339) and mis-rounds ~97% of
    microsecond fractions through the float round-trip; integer timedelta
    arithmetic does neither.
    """
    global _EPOCH
    if _EPOCH is None:
        from datetime import datetime, timezone

        _EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)
    delta = dt - _EPOCH
    ns = (delta.days * 86_400 + delta.seconds) * 10**9 + delta.microseconds * 1000
    return np.int64(max(int(NS_MIN), min(int(NS_MAX), ns)))


@jax.tree_util.register_pytree_node_class
@dataclass
class OverrideSchedule:
    """Padded [T,O] override schedule + [T]/[T,R] spec threshold tensors."""

    ov_valid: jnp.ndarray  # bool[T,O] — exists ∧ parses
    ov_begin: jnp.ndarray  # int64[T,O] ns since epoch (NS_MIN if open)
    ov_end: jnp.ndarray  # int64[T,O] ns (NS_MAX if open)
    ov_cnt: jnp.ndarray  # int64[T,O]
    ov_cnt_present: jnp.ndarray  # bool[T,O]
    ov_req: jnp.ndarray  # int64[T,O,R]
    ov_req_present: jnp.ndarray  # bool[T,O,R]
    spec_cnt: jnp.ndarray  # int64[T]
    spec_cnt_present: jnp.ndarray  # bool[T]
    spec_req: jnp.ndarray  # int64[T,R]
    spec_req_present: jnp.ndarray  # bool[T,R]

    def tree_flatten(self):
        return (
            (
                self.ov_valid,
                self.ov_begin,
                self.ov_end,
                self.ov_cnt,
                self.ov_cnt_present,
                self.ov_req,
                self.ov_req_present,
                self.spec_cnt,
                self.spec_cnt_present,
                self.spec_req,
                self.spec_req_present,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def encode_override_schedule(
    specs: Sequence[ThrottleSpecBase],
    dims: DimRegistry,
    throttle_capacity: Optional[int] = None,
    override_capacity: Optional[int] = None,
) -> OverrideSchedule:
    for spec in specs:
        if spec is None:  # unoccupied device column (padded capacity)
            continue
        for name in (spec.threshold.resource_requests or {}):
            dims.index_of(name)
        for o in spec.temporary_threshold_overrides:
            for name in (o.threshold.resource_requests or {}):
                dims.index_of(name)

    T = throttle_capacity if throttle_capacity is not None else max(len(specs), 1)
    max_overrides = max(
        (len(s.temporary_threshold_overrides) for s in specs if s is not None),
        default=0,
    )
    O = override_capacity if override_capacity is not None else max(max_overrides, 1)
    if max_overrides > O:
        raise ValueError(
            f"override_capacity={O} cannot hold {max_overrides} overrides; "
            "grow the capacity and re-encode (silent truncation would drop "
            "active overrides)"
        )
    R = dims.capacity

    ov_valid = np.zeros((T, O), dtype=bool)
    ov_begin = np.full((T, O), NS_MIN, dtype=np.int64)
    ov_end = np.full((T, O), NS_MAX, dtype=np.int64)
    ov_cnt = np.zeros((T, O), dtype=np.int64)
    ov_cnt_present = np.zeros((T, O), dtype=bool)
    ov_req = np.zeros((T, O, R), dtype=np.int64)
    ov_req_present = np.zeros((T, O, R), dtype=bool)
    spec_cnt = np.zeros(T, dtype=np.int64)
    spec_cnt_present = np.zeros(T, dtype=bool)
    spec_req = np.zeros((T, R), dtype=np.int64)
    spec_req_present = np.zeros((T, R), dtype=bool)

    for i, spec in enumerate(specs):
        if spec is None:
            continue
        if spec.threshold.resource_counts is not None:
            spec_cnt[i] = spec.threshold.resource_counts
            spec_cnt_present[i] = True
        for name, q in (spec.threshold.resource_requests or {}).items():
            j = dims.index_of(name)
            spec_req[i, j] = to_milli(q)
            spec_req_present[i, j] = True
        for k, o in enumerate(spec.temporary_threshold_overrides):
            try:
                begin_t = o.begin_time()
                end_t = o.end_time()
            except RFC3339ParseError:
                continue  # skipped, exactly like the Go loop (messages are host data)
            ov_valid[i, k] = True
            if begin_t is not None:
                ov_begin[i, k] = _datetime_to_ns(begin_t)
            if end_t is not None:
                ov_end[i, k] = _datetime_to_ns(end_t)
            if o.threshold.resource_counts is not None:
                ov_cnt[i, k] = o.threshold.resource_counts
                ov_cnt_present[i, k] = True
            for name, q in (o.threshold.resource_requests or {}).items():
                j = dims.index_of(name)
                ov_req[i, k, j] = to_milli(q)
                ov_req_present[i, k, j] = True

    return OverrideSchedule(
        ov_valid=jnp.asarray(ov_valid),
        ov_begin=jnp.asarray(ov_begin),
        ov_end=jnp.asarray(ov_end),
        ov_cnt=jnp.asarray(ov_cnt),
        ov_cnt_present=jnp.asarray(ov_cnt_present),
        ov_req=jnp.asarray(ov_req),
        ov_req_present=jnp.asarray(ov_req_present),
        spec_cnt=jnp.asarray(spec_cnt),
        spec_cnt_present=jnp.asarray(spec_cnt_present),
        spec_req=jnp.asarray(spec_req),
        spec_req_present=jnp.asarray(spec_req_present),
    )


def encode_class_thresholds(
    base_cnt: np.ndarray,  # int64[T] effective (override-resolved) thresholds
    base_cnt_present: np.ndarray,  # bool[T]
    base_req: np.ndarray,  # int64[T,R]
    base_req_present: np.ndarray,  # bool[T,R]
    accel_entries: Mapping[int, Sequence],  # col → (AccelClassThreshold, ...)
    classes: Sequence[str],
    dims: DimRegistry,
):
    """Per-(throttle, accel-class) effective-threshold tensor with
    first-wins merge (heterogeneity-aware admission, ops/gang_check.py).

    Produces the ``[A, T]`` / ``[A, T, R]`` planes the gang kernel gathers
    per group: row 0 is the BASE effective threshold (exactly the staging
    planes the per-pod check kernel reads — already override-resolved), and
    row 1+a is the fleet seen through accelerator class ``classes[a]``:
    wherever a throttle column declares an ``accelClassThresholds`` entry
    for that class, the FIRST matching entry's threshold REPLACES the whole
    base row (counts and requests both — the same whole-replacement
    semantics as the temporary-override merge, api/types.py
    ``AccelClassThreshold``); columns without a matching entry keep the
    base row. ``accel_entries`` maps device column → the spec's entry
    tuple; only those sparse columns are touched, so the encode is
    O(A × accel-throttles), not O(A × T)."""
    T = base_cnt.shape[0]
    R = base_req.shape[1]
    A = 1 + len(classes)
    cnt = np.tile(base_cnt, (A, 1))
    cnt_p = np.tile(base_cnt_present, (A, 1))
    req = np.tile(base_req, (A, 1, 1))
    req_p = np.tile(base_req_present, (A, 1, 1))
    for a, cls in enumerate(classes, start=1):
        for col, entries in accel_entries.items():
            if col >= T:
                continue  # racing capacity growth: column not encoded yet
            entry = next((e for e in entries if e.accel_class == cls), None)
            if entry is None:
                continue
            thr = entry.threshold
            if thr.resource_counts is not None:
                cnt[a, col] = thr.resource_counts
                cnt_p[a, col] = True
            else:
                cnt[a, col] = 0
                cnt_p[a, col] = False
            req[a, col, :] = 0
            req_p[a, col, :] = False
            for name, q in (thr.resource_requests or {}).items():
                j = dims.index_of(name)
                if j >= R:
                    continue  # dim registered after the planes were sized
                req[a, col, j] = to_milli(q)
                req_p[a, col, j] = True
    return cnt, cnt_p, req, req_p


@jax.jit
def calculate_thresholds(sched: OverrideSchedule, now_ns: jnp.ndarray):
    """Effective thresholds at ``now_ns`` for every throttle.

    Returns (thr_cnt int64[T], thr_cnt_present bool[T],
             thr_req int64[T,R], thr_req_present bool[T,R]).
    """
    # inclusive bounds: begin ≤ now ∧ now ≤ end (temporary_threshold_override.go:67-69)
    active = sched.ov_valid & (sched.ov_begin <= now_ns) & (now_ns <= sched.ov_end)  # [T,O]
    any_active = jnp.any(active, axis=1)  # [T]

    # counts: first active override that has a counts dim. "First" is a
    # cumsum one-hot (cand ∧ running-count==1) selected by a masked sum —
    # elementwise + reduce only; int64 argmax/take_along_axis lower to slow
    # gather paths on TPU (measured 1.5× slower for the whole kernel).
    cnt_cand = active & sched.ov_cnt_present  # [T,O]
    cnt_any = jnp.any(cnt_cand, axis=1)
    cnt_first = cnt_cand & (jnp.cumsum(cnt_cand.astype(jnp.int32), axis=1) == 1)
    cnt_val = jnp.sum(jnp.where(cnt_first, sched.ov_cnt, 0), axis=1)

    thr_cnt_present = jnp.where(any_active, cnt_any, sched.spec_cnt_present)
    thr_cnt = jnp.where(any_active & cnt_any, cnt_val, sched.spec_cnt)
    thr_cnt = jnp.where(thr_cnt_present, thr_cnt, 0)

    # requests: first active override that has each dim (same one-hot form)
    req_cand = active[:, :, None] & sched.ov_req_present  # [T,O,R]
    req_any = jnp.any(req_cand, axis=1)  # [T,R]
    req_first = req_cand & (jnp.cumsum(req_cand.astype(jnp.int32), axis=1) == 1)
    req_val = jnp.sum(jnp.where(req_first, sched.ov_req, 0), axis=1)  # [T,R]

    thr_req_present = jnp.where(any_active[:, None], req_any, sched.spec_req_present)
    thr_req = jnp.where(
        any_active[:, None] & req_any, req_val, sched.spec_req
    )
    thr_req = jnp.where(thr_req_present, thr_req, 0)

    return thr_cnt, thr_cnt_present, thr_req, thr_req_present


# runtime retrace budget (KT_JIT_RETRACE_BUDGET): every jit entry here
# reports its compile-cache size per tick — see utils/retrace.py
from ..utils.retrace import register_all as _register_retrace

_register_retrace(globals(), __name__)
