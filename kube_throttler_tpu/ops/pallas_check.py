"""Pallas TPU kernel for the dense admission sweep.

Why a hand-written kernel: the XLA fusion of the residual-form check streams
the [T,R] throttle tensors from HBM once per pod row (broadcast inputs are
re-read per output tile), so the 100k × 10k sweep is bandwidth-bound at
~200 ms. This kernel tiles the check matrix into [BP × BT] blocks, loads the
pod tile and throttle tile into VMEM ONCE each per block, and does the R
loop entirely on-chip — HBM traffic drops from O(P·T·R) to
O(P·T / BT · R + P·T / BP · R + P·T) (the int8 status output dominates).

64-bit milli values are pre-split into **int32 limb pairs** (hi = v >> 32
signed, lo = low 32 bits biased by 2^31 so signed compare == unsigned
compare); a lexicographic (hi, lo) compare is exactly the s64 compare, in
native int32 VPU ops instead of the X64 rewriter's emulation.

Layout: pod-side arrays [P, R] (pods on sublanes), throttle-side arrays
transposed to [R, T] (throttles on lanes), mask/output [P, T]. R is a
static unrolled loop. P and T must be multiples of the block shape —
callers pad (devicestate capacities and bench shapes already grow in
power-of-two steps).

The kernel consumes the same pod-independent precomputation as
``ops.fastcheck`` (residual form), with the onEqual/step3 variants resolved
to concrete arrays before launch, so the kernel itself has a single static
flag (the step-4 strictness).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .check import (
    CHECK_ACTIVE,
    CHECK_INSUFFICIENT,
    CHECK_NOT_AFFECTED,
    CHECK_NOT_THROTTLED,
    CHECK_POD_EXCEEDS,
)
from .fastcheck import CheckPrecomp
from .schema import PodBatch

BP = 256  # pod rows per block (sublane axis)
BT = 512  # throttle cols per block (lane axis)

_BIAS = jnp.int32(-(2**31))  # xor bias turning unsigned order into signed


def _split_limbs(x: jnp.ndarray):
    """int64 → (hi int32 signed, lo int32 biased)."""
    hi = (x >> 32).astype(jnp.int32)
    lo = jnp.bitwise_xor((x & 0xFFFFFFFF).astype(jnp.uint32).astype(jnp.int32), _BIAS)
    return hi, lo


def _limb_gt(a_hi, a_lo, b_hi, b_lo):
    """(a > b) for s64 split into (signed hi, biased lo)."""
    return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo > b_lo))


def _limb_ge(a_hi, a_lo, b_hi, b_lo):
    return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo >= b_lo))


def _make_kernel(R: int, on_equal: bool):
    def kernel(
        pod_hi_ref,      # [BP, R] i32
        pod_lo_ref,      # [BP, R] i32
        pod_nz_ref,      # [BP, R] i32 — present ∧ nonzero (0/1)
        thr_hi_ref,      # [R, BT] i32 — threshold (step 1)
        thr_lo_ref,      # [R, BT] i32
        thr_pres_ref,    # [R, BT] i32
        resid_hi_ref,    # [R, BT] i32 — step-4 residual
        resid_lo_ref,    # [R, BT] i32
        st_req_ref,      # [R, BT] i32 — step-2 per-dim flags
        sat_req_ref,     # [R, BT] i32 — step-3 per-dim flags (variant-selected)
        tvec_ref,        # [4, BT] i32 — rows: exceeds_cnt, st|sat cnt, over_cnt, valid
        mask_ref,        # [BP, BT] i8
        out_ref,         # [BP, BT] i8
    ):
        # All predicate logic stays in the i32 (8,128) layout domain; the i8
        # mask input and output cross layouts exactly once each (a supported
        # dtype conversion), avoiding Mosaic i1 relayouts between the (8,128)
        # and (32,128) tilings.
        shape = (BP, BT)
        exceeds = jnp.zeros(shape, dtype=jnp.bool_)
        st_or_sat = jnp.zeros(shape, dtype=jnp.bool_)
        over = jnp.zeros(shape, dtype=jnp.bool_)

        for r in range(R):  # static unroll
            p_hi = pod_hi_ref[:, r][:, None]
            p_lo = pod_lo_ref[:, r][:, None]
            p_nz = pod_nz_ref[:, r][:, None] != 0

            t_pres = thr_pres_ref[r, :][None, :] != 0
            gate = p_nz & t_pres

            t_hi = thr_hi_ref[r, :][None, :]
            t_lo = thr_lo_ref[r, :][None, :]
            exceeds |= gate & _limb_gt(p_hi, p_lo, t_hi, t_lo)

            st_or_sat |= p_nz & (
                (st_req_ref[r, :][None, :] != 0) | (sat_req_ref[r, :][None, :] != 0)
            )

            r_hi = resid_hi_ref[r, :][None, :]
            r_lo = resid_lo_ref[r, :][None, :]
            if on_equal:
                over |= gate & _limb_ge(p_hi, p_lo, r_hi, r_lo)
            else:
                over |= gate & _limb_gt(p_hi, p_lo, r_hi, r_lo)

        exceeds |= tvec_ref[0, :][None, :] != 0
        st_or_sat |= tvec_ref[1, :][None, :] != 0
        over |= tvec_ref[2, :][None, :] != 0
        affected = (mask_ref[:, :].astype(jnp.int32) != 0) & (tvec_ref[3, :][None, :] != 0)

        result = jnp.where(
            exceeds,
            jnp.int32(CHECK_POD_EXCEEDS),
            jnp.where(
                st_or_sat,
                jnp.int32(CHECK_ACTIVE),
                jnp.where(over, jnp.int32(CHECK_INSUFFICIENT), jnp.int32(CHECK_NOT_THROTTLED)),
            ),
        )
        result = jnp.where(affected, result, jnp.int32(CHECK_NOT_AFFECTED))
        out_ref[:, :] = result.astype(jnp.int8)

    return kernel


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal", "interpret"))
def pallas_check_pods(
    pre: CheckPrecomp,
    pods: PodBatch,
    mask: jnp.ndarray,
    on_equal: bool = False,
    step3_on_equal: bool = True,
    interpret: bool = False,
) -> jnp.ndarray:
    """Full [P,T] classification via the tiled Pallas kernel.

    P and T must be multiples of (BP, BT); callers pad (encode capacities
    and bench shapes grow in power-of-two steps, so this is a rounding of
    the existing padding, not extra machinery). The limb split and variant
    selection trace into the same jit, so per-call overhead beyond the
    kernel is a handful of cheap elementwise ops.
    """
    P, T = mask.shape
    R = pods.req.shape[1]
    if P % BP or T % BT:
        raise ValueError(f"P={P} and T={T} must be multiples of ({BP},{BT}); pad first")

    pod_hi, pod_lo = _split_limbs(pods.req)
    pod_nz = (pods.req_present & (pods.req != 0) & pods.valid[:, None]).astype(jnp.int32)

    thr_hi, thr_lo = _split_limbs(pre.thr_req.T)
    resid_hi, resid_lo = _split_limbs(pre.resid.T)
    thr_pres = pre.thr_req_present.T.astype(jnp.int32)
    st_req = pre.st_req.T.astype(jnp.int32)
    sat_req = (pre.sat_req_ge if step3_on_equal else pre.sat_req_gt).T.astype(jnp.int32)

    sat_cnt = pre.sat_cnt_ge if step3_on_equal else pre.sat_cnt_gt
    over_cnt = pre.over_cnt_ge if on_equal else pre.over_cnt_gt
    tvec = jnp.stack(
        [
            pre.exceeds_cnt.astype(jnp.int32),
            (pre.st_cnt | sat_cnt).astype(jnp.int32),
            over_cnt.astype(jnp.int32),
            pre.valid.astype(jnp.int32),
        ],
        axis=0,
    )  # [4, T]

    # fold pod validity into the mask: the kernel's pod-independent count
    # flags (tvec) would otherwise classify invalid/padded pod rows whose
    # mask bits are set, diverging from check_pods' NOT_AFFECTED contract
    mask8 = (mask & pods.valid[:, None]).astype(jnp.int8)

    # block indices must be i32 and index maps may not capture constants:
    # with jax_enable_x64 a bare `0` weak-types to i64 (Mosaic fails to
    # legalize the return), so derive an i32 zero from the grid tracers
    pod_spec = pl.BlockSpec((BP, R), lambda i, j: (i, j * 0))
    thr_spec = pl.BlockSpec((R, BT), lambda i, j: (i * 0, j))
    tvec_spec = pl.BlockSpec((4, BT), lambda i, j: (i * 0, j))
    cell_spec = pl.BlockSpec((BP, BT), lambda i, j: (i, j))

    return pl.pallas_call(
        _make_kernel(R, on_equal),
        out_shape=jax.ShapeDtypeStruct((P, T), jnp.int8),
        grid=(P // BP, T // BT),
        in_specs=[
            pod_spec, pod_spec, pod_spec,  # pod hi/lo/nz
            thr_spec, thr_spec, thr_spec,  # thr hi/lo/present
            thr_spec, thr_spec,            # resid hi/lo
            thr_spec, thr_spec,            # st_req, sat_req
            tvec_spec,
            cell_spec,                     # mask
        ],
        out_specs=cell_spec,
        interpret=interpret,
    )(
        pod_hi, pod_lo, pod_nz,
        thr_hi, thr_lo, thr_pres,
        resid_hi, resid_lo,
        st_req, sat_req,
        tvec, mask8,
    )




# runtime retrace budget (KT_JIT_RETRACE_BUDGET): every jit entry here
# reports its compile-cache size per tick — see utils/retrace.py
from ..utils.retrace import register_all as _register_retrace

_register_retrace(globals(), __name__)
