"""Device data plane: the throttler's decision core as XLA tensor programs.

The reference evaluates `used + reserved + pod.requests vs threshold` in a
per-pod × per-throttle × per-dimension nested Go loop on the scheduler hot
path (throttle_controller.go:349-397). Here that loop is a single fused
elementwise/reduction kernel over padded int64 milli-unit arrays:

- ``schema``    — array layout: presence-masked [T,R]/[P,R] state tensors,
  the resource-dimension registry, and host→device encoding.
- ``check``     — the batched ordered 4-state admission check.
- ``aggregate`` — masked used-aggregation (einsum) + streaming scatter-add.
- ``overrides`` — time-varying threshold resolution (first-active-wins).
"""

from .schema import (  # noqa: F401
    DimRegistry,
    PodBatch,
    ThrottleState,
    encode_pods,
    encode_throttle_state,
)
from .check import (  # noqa: F401
    CHECK_ACTIVE,
    CHECK_INSUFFICIENT,
    CHECK_NOT_AFFECTED,
    CHECK_NOT_THROTTLED,
    CHECK_POD_EXCEEDS,
    STATUS_NAMES,
    check_pods,
    check_pods_compact,
    check_pods_gather,
)
