"""Used-amount aggregation and streaming delta updates.

The reference recomputes ``status.used`` per reconcile by scanning every pod
in the namespace and summing matched, counted pods' amounts
(throttle_controller.go:103-119). Batched here as one masked einsum over the
[P,T] selector mask — all throttles at once — plus a scatter-add path for
streaming single-pod events (the BASELINE "1k events/sec streaming
reconcile" config) that avoids full recomputation.

Presence bookkeeping: ``contrib[t,r]`` counts how many contributing pods
carry resource r, so removals keep presence exact (a bool OR could never be
un-set); ``used.resourceCounts`` is present iff ≥1 pod contributed (the Go
accumulator only materializes counts after the first Add —
resource_amount.go:91-110 over throttle_controller.go:116-119).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .schema import PodBatch


def _masked_colsum_exact(m: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Exact ``m.T @ vals`` for bool[P,K] × int64[P,R] (non-negative vals)
    as two float64 dots over 32-bit limbs.

    Quantities are non-negative int64 milli-units; split each into
    ``hi*2^32 + lo`` with both limbs < 2^32. A float64 dot of a 0/1 mask
    against a limb column sums < 2^32 · P, exact in f64 while P < 2^21 —
    far above any padded pod capacity — and the int64 recombination is
    overflow-safe whenever the true total fits int64 (then hi_sum ≤ 2^31).
    Dots hit the platform's GEMM path, ~500× the [P,K,R] broadcast+reduce
    on a host core. Memory: materializes the f64 mask [P,K] (the CPU-side
    quick shapes; the TPU path below never calls this).
    """
    mt = m.astype(jnp.float64).T  # [K,P]
    lo = (vals & 0xFFFFFFFF).astype(jnp.float64)
    hi = (vals >> 32).astype(jnp.float64)
    lo_s = jnp.dot(mt, lo)
    hi_s = jnp.dot(mt, hi)
    return (hi_s.astype(jnp.int64) << 32) + lo_s.astype(jnp.int64)


def _aggregate_core(pods: PodBatch, m: jnp.ndarray, use_dots: bool):
    """Shared body: used aggregates from an already-combined mask bool[P,K]."""
    used_cnt = jnp.sum(m, axis=0, dtype=jnp.int64)  # each pod contributes count 1
    if use_dots:
        used_req = _masked_colsum_exact(m, pods.req)
        contrib = _masked_colsum_exact(
            m, pods.req_present.astype(jnp.int64)
        ).astype(jnp.int32)
    else:
        # broadcast+reduce instead of dot_general: TPU's X64 rewriter
        # emulates s64 add/select/compare as s32 pairs but has no s64 dot
        # lowering, and the MXU cannot accumulate 64-bit integers exactly.
        # XLA loop-fuses the [P,K,R] product into the reduction, so nothing
        # [P,K,R] materializes.
        mb = m[:, :, None]
        used_req = jnp.sum(jnp.where(mb, pods.req[:, None, :], 0), axis=0)
        contrib = jnp.sum(
            (mb & pods.req_present[:, None, :]).astype(jnp.int32), axis=0
        )
    return used_cnt, used_req, contrib


@partial(jax.jit, static_argnames=("use_dots",))
def _aggregate_used_impl(pods, mask, counted, use_dots):
    m = mask & counted[:, None]  # bool[P,T]
    return _aggregate_core(pods, m, use_dots)


def aggregate_used(pods: PodBatch, mask: jnp.ndarray, counted: jnp.ndarray):
    """Full recompute of used amounts for every throttle.

    Args:
      pods: padded pod batch (requests of ALL pods, scheduled or not).
      mask: bool[P,T] selector match matrix.
      counted: bool[P] — shouldCountIn ∧ non-terminated ∧ valid
        (schedulerName match, nodeName set — throttle_controller.go:217-219).

    Returns (used_cnt int64[T], used_req int64[T,R], contrib int32[T,R]).

    Backend-adaptive: on CPU the masked sum runs as exact limb-split f64
    GEMMs (a [P,T,R] elementwise reduce takes ~26s on one host core at
    16k×1k×8, the dot form ~50ms); on TPU the fused broadcast+reduce is
    used (no s64 dot lowering, and the f64 mask would materialize [P,T]×8B).
    """
    return _aggregate_used_impl(
        pods, mask, counted, use_dots=jax.default_backend() == "cpu"
    )


@jax.jit
def apply_pod_delta(
    used_cnt: jnp.ndarray,
    used_req: jnp.ndarray,
    contrib: jnp.ndarray,
    throttle_ids: jnp.ndarray,  # int32[K] — rows to update (may repeat; pad with T)
    sign: jnp.ndarray,  # int64[K] — +1 add / -1 remove / 0 padding
    pod_req: jnp.ndarray,  # int64[R] — the pod's effective request
    pod_req_present: jnp.ndarray,  # bool[R]
):
    """Streaming update: one pod added/removed from K affected throttles.

    ``throttle_ids`` may be padded with out-of-range indices (scatter drops
    them). Donation-friendly: callers re-bind the returned arrays.
    """
    used_cnt = used_cnt.at[throttle_ids].add(sign, mode="drop")
    used_req = used_req.at[throttle_ids].add(
        sign[:, None] * pod_req[None, :], mode="drop"
    )
    contrib = contrib.at[throttle_ids].add(
        (sign[:, None] * pod_req_present[None, :].astype(jnp.int64)).astype(jnp.int32),
        mode="drop",
    )
    return used_cnt, used_req, contrib


@jax.jit
def apply_pod_deltas_batched(
    used_cnt: jnp.ndarray,
    used_req: jnp.ndarray,
    contrib: jnp.ndarray,
    throttle_ids: jnp.ndarray,  # int32[N,K] — per-event target rows (pad with T)
    sign: jnp.ndarray,  # int64[N,K] — +1/-1/0 per (event, slot)
    pod_req: jnp.ndarray,  # int64[N,R]
    pod_req_present: jnp.ndarray,  # bool[N,R]
):
    """N pod events applied in ONE scatter dispatch.

    Scatter-adds commute and associate exactly in int64, so this equals N
    sequential ``apply_pod_delta`` calls (property-tested) — but costs one
    kernel instead of a length-N ``lax.scan`` chain. This is the ingest path
    for event bursts: the host drains its queue, encodes the batch, and
    lands it in a single device tick.
    """
    n, k = throttle_ids.shape
    r = used_req.shape[1]
    flat_ids = throttle_ids.reshape(n * k)
    flat_sign = sign.reshape(n * k)
    used_cnt = used_cnt.at[flat_ids].add(flat_sign, mode="drop")
    req_updates = (sign[:, :, None] * pod_req[:, None, :]).reshape(n * k, r)
    used_req = used_req.at[flat_ids].add(req_updates, mode="drop")
    contrib_updates = (
        sign[:, :, None] * pod_req_present[:, None, :].astype(jnp.int64)
    ).astype(jnp.int32).reshape(n * k, r)
    contrib = contrib.at[flat_ids].add(contrib_updates, mode="drop")
    return used_cnt, used_req, contrib


@partial(jax.jit, static_argnames=("use_dots",))
def _rebase_cols_impl(agg_cnt, agg_req, contrib, pods, mask, counted, cols, use_dots):
    m = mask[:, cols] & (counted & pods.valid)[:, None]  # bool[P,K]
    cnt, req, ctb = _aggregate_core(pods, m, use_dots)
    return (
        agg_cnt.at[cols].set(cnt, mode="drop"),
        agg_req.at[cols].set(req, mode="drop"),
        contrib.at[cols].set(ctb, mode="drop"),
    )


def rebase_cols(
    agg_cnt: jnp.ndarray,  # int64[T]
    agg_req: jnp.ndarray,  # int64[T,R]
    contrib: jnp.ndarray,  # int32[T,R]
    pods: PodBatch,
    mask: jnp.ndarray,  # bool[P,T]
    counted: jnp.ndarray,  # bool[P]
    cols: jnp.ndarray,  # int32[K] — columns to recompute (pad with T → dropped)
):
    """Recompute the used-aggregates of K specific throttle columns from
    scratch (selector/threshold edits invalidate a column's incremental
    aggregate — the membership set changed, so deltas no longer apply).

    One masked [P,K] reduction + scatter, entirely on device; K is bucketed
    by the caller so recompilation is bounded. Backend-adaptive like
    ``aggregate_used`` (exact limb-split GEMMs on CPU)."""
    return _rebase_cols_impl(
        agg_cnt, agg_req, contrib, pods, mask, counted, cols,
        use_dots=jax.default_backend() == "cpu",
    )


@partial(jax.jit, static_argnames=("use_dots",))
def _aggregate_cols_impl(pods, mask, counted, cols, use_dots):
    m = mask[:, cols] & (counted & pods.valid)[:, None]  # bool[P,K]
    return _aggregate_core(pods, m, use_dots)


def aggregate_cols(
    pods: PodBatch,
    mask: jnp.ndarray,  # bool[P,T]
    counted: jnp.ndarray,  # bool[P]
    cols: jnp.ndarray,  # int32[K] — columns to recompute (pad freely)
):
    """Used-aggregates of K specific columns, RETURNED rather than scattered
    (``rebase_cols`` minus the device-resident write): the hybrid reconcile
    data plane computes rebases on device — the masked [P,K] reduction is
    the parallel part — and lands them in the HOST aggregate arrays, which
    serve every reconcile read without a device round trip."""
    return _aggregate_cols_impl(
        pods, mask, counted, cols, use_dots=jax.default_backend() == "cpu"
    )


@jax.jit
def throttled_flags(
    thr_cnt: jnp.ndarray,
    thr_cnt_present: jnp.ndarray,
    thr_req: jnp.ndarray,
    thr_req_present: jnp.ndarray,
    used_cnt: jnp.ndarray,
    used_cnt_present: jnp.ndarray,
    used_req: jnp.ndarray,
    used_req_present: jnp.ndarray,
):
    """status.throttled = threshold.IsThrottled(used, onEqual=True) batched
    over throttles (reconcile's flag computation,
    throttle_controller.go:133).

    Returns (cnt_flag bool[T], req_flag bool[T,R], req_flag_present bool[T,R]);
    flag-map keys are exactly the threshold's request keys
    (resource_amount.go:147-156).
    """
    cnt_flag = thr_cnt_present & used_cnt_present & (used_cnt >= thr_cnt)
    req_flag = thr_req_present & used_req_present & (used_req >= thr_req)
    return cnt_flag, req_flag, thr_req_present


# runtime retrace budget (KT_JIT_RETRACE_BUDGET): every jit entry here
# reports its compile-cache size per tick — see utils/retrace.py
from ..utils.retrace import register_all as _register_retrace

_register_retrace(globals(), __name__)
