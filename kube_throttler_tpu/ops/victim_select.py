"""Batched victim selection — the greedy ranked-prefix walk as ONE
``lax.scan`` dispatch.

Victim selection is inherently sequential: whether candidate *i* is taken
depends on which deficits its selected predecessors already covered. The
host oracle (policy/victims.py ``sequential_victim_select``) expresses
that as a Python loop; this kernel expresses the SAME recurrence as a
``lax.scan`` over the ranked contribution matrix, so a tick's whole
candidate set is judged in one dispatch with no per-candidate host round
trip. Semantics are pinned to the oracle by the seeded equivalence sweep
and the hypothesis twin (tests/test_policy.py,
tests/test_victim_property.py): identical verdicts AND identical selected
sets on identical ranked inputs.

Operands (policy/victims.py ``build_selection_problem`` flattens them from
the per-(kind, throttle, dim) deficits derived off the same sparse
matched-cols structures the gang kernel reads):

- ``contrib`` int64[N, M] — row i = ranked candidate i's freed capacity
  per flattened deficit dim (milli-units / counts; zero-padded rows are
  never selected, so N ladder-pads freely);
- ``deficit`` int64[M] — the positive capacity shortfalls (≤ 0 cells are
  already met; zero-padded dims are inert).

``max_victims`` is a STATIC cap (0 = uncapped): the scan stops taking
once the cap is reached, exactly like the oracle's early break.

The recurrence per candidate: take iff any dim has ``contrib > 0`` while
``remaining > 0`` (and the cap allows), then subtract the WHOLE row —
over-freeing is fine (an evicted pod frees everything it held), and
subtracting unconditionally-on-take keeps the arithmetic identical to the
oracle's ``remaining -= row``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("max_victims",))
def victim_select(contrib, deficit, max_victims: int = 0):
    """→ ``(selected bool[N], ok bool, remaining int64[M])`` — see module
    docstring. ``contrib``/``deficit`` must be int64 (exact milli-unit
    arithmetic; the dtype checker's stance on every admission plane)."""

    def step(carry, row):
        remaining, count = carry
        helps = jnp.any((row > 0) & (remaining > 0))
        if max_victims > 0:  # static branch: cap compiled in or out
            take = helps & (count < max_victims)
        else:
            take = helps
        remaining = jnp.where(take, remaining - row, remaining)
        return (remaining, count + take.astype(jnp.int32)), take

    (remaining, _count), selected = jax.lax.scan(
        step, (deficit, jnp.int32(0)), contrib
    )
    ok = jnp.all(remaining <= 0)
    return selected, ok, remaining


# runtime retrace budget (KT_JIT_RETRACE_BUDGET): every jit entry here
# reports its compile-cache size per tick — see utils/retrace.py
from ..utils.retrace import register_all as _register_retrace

_register_retrace(globals(), __name__)
