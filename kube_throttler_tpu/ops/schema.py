"""Tensor schema: how throttler state becomes padded device arrays.

Encoding rules (all derived from the oracle semantics in ``api/types.py``):

- Quantities are **int64 milli-units** (exact; see ``quantity.to_milli``).
  Encoding raises on sub-milli precision rather than silently rounding.
- Every value tensor carries a **presence mask**. Absent (Go-nil / missing
  map key) is distinct from zero: absent threshold dims are never evaluated,
  absent used dims never throttle (resource_amount.go:143,151-155). Absent
  cells hold value 0 so sums stay valid without branching.
- Arrays are padded to fixed capacities (throttles T, pods P, resource dims
  R) so jitted kernels never recompile on object churn; validity masks mark
  live rows. Capacities grow geometrically (re-jit is rare and amortized).
- The per-throttle *effective* threshold (status.calculatedThreshold if
  calculatedAt is set, else spec.threshold — throttle_types.go:129-132) is
  resolved at encode time; the check kernel sees one threshold tensor.

The [P,T] selector mask is produced by the host selector index (engine/),
not here — matching is string/label work, which stays on host; the device
sees only its boolean result.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..api.pod import Pod
from ..api.types import ClusterThrottle, Throttle
from ..quantity import to_milli
from .. import resourcelist as rl

AnyThrottle = Union[Throttle, ClusterThrottle]

# The int64 planes. Every tensor named here carries exact int64 values —
# milli-unit quantities or pod counts summed over up to 1M pods — and
# must stay int64 end to end: an int32 accumulator overflows at ~2.1e6
# milli-cores (2.1 cores over 1k pods), float32 loses integer exactness
# past 2^24, and float64 past 2^53. The ``dtype`` static checker
# (analysis/device.py) reads this literal set from the AST (the registry
# idiom: keep it a literal) and flags any narrowing cast, narrow-dtype
# accumulator, or default-dtype allocation touching these names anywhere
# in ops/, parallel/, or the engine device/staging planes. The columnar
# arena intentionally stores int32 *columns* (engine/columnar.py); the
# encode boundary upcasts into these planes, which is exactly the cast
# surface the checker pins.
INT64_MILLI_PLANES = frozenset(
    {
        "thr_cnt",
        "thr_req",
        "used_cnt",
        "used_req",
        "res_cnt",
        "res_req",
        "req",  # PodBatch.req / the encoded pod-request rows
        "pod_req",  # engine/devicestate.py staging plane
        "row_req",  # the per-pod encoded [1,R] row
        "au_cnt",  # already-used = used + reserved (gang snapshot)
        "au_req",
        "cls_cnt",  # per-accel-class effective thresholds
        "cls_req",
    }
)


# The verdict-epoch coherence registry. Every attribute named here is a
# verdict-affecting plane or ledger: a PreFilter verdict is a pure
# function of (request-shape id, accel class, matched cols, per-col
# state), and the interned-verdict cache (engine/verdictcache.py) proves
# freshness by epoch sums — so any write to one of these planes that is
# not dominated by a ``col_epoch``/``global_epoch`` bump (or a call into
# a function that bumps) silently serves stale admission verdicts at
# cache-hit speed. The ``epochs`` static checker (analysis/epochs.py)
# reads this literal set from the AST (same registry idiom as
# INT64_MILLI_PLANES above) and flags undominated writes; vetted
# exceptions live in analysis/epoch_allow.txt with justifications.
# Functions that provide the bump for their callers are marked with an
# inline ``#: epoch-bumps:`` annotation at the def site.
VERDICT_EPOCH_PLANES = frozenset(
    {
        # threshold/spec columns (effective_threshold inputs)
        "thr_cnt",
        "thr_cnt_present",
        "thr_req",
        "thr_req_present",
        "thr_valid",
        # usage ledgers
        "used_cnt",
        "used_cnt_present",
        "used_req",
        "used_req_present",
        # reservation ledgers (gang reserve/bind writes land here)
        "res_cnt",
        "res_cnt_present",
        "res_req",
        "res_req_present",
        # throttle-status planes (the st_* flip state)
        "st_cnt_throttled",
        "st_req_throttled",
        "st_req_flag_present",
        # per-accel-class threshold overrides
        "accel_cols",
    }
)


class DimRegistry:
    """Stable resource-name → column-index mapping.

    Grows append-only; encoded arrays are padded to ``capacity`` columns so
    adding the (R+1)-th distinct resource name does not change array shapes
    until capacity doubles.
    """

    def __init__(self, capacity: int = 8):
        self._names: List[str] = []
        self._index: Dict[str, int] = {}
        self.capacity = capacity

    def index_of(self, name: str) -> int:
        idx = self._index.get(name)
        if idx is None:
            idx = len(self._names)
            self._names.append(name)
            self._index[name] = idx
            while idx >= self.capacity:
                self.capacity *= 2
        return idx

    @property
    def names(self) -> Sequence[str]:
        return tuple(self._names)

    def __len__(self) -> int:
        return len(self._names)


@jax.tree_util.register_pytree_node_class
@dataclass
class ThrottleState:
    """Padded per-kind device state: [T] / [T,R] arrays + presence masks.

    One instance per kind (Throttle, ClusterThrottle), mirroring the two
    controllers in the reference.
    """

    valid: jnp.ndarray  # bool[T] — live throttle rows
    thr_cnt: jnp.ndarray  # int64[T] — effective threshold pod-count
    thr_cnt_present: jnp.ndarray  # bool[T]
    thr_req: jnp.ndarray  # int64[T,R]
    thr_req_present: jnp.ndarray  # bool[T,R]
    used_cnt: jnp.ndarray  # int64[T]
    used_cnt_present: jnp.ndarray  # bool[T]
    used_req: jnp.ndarray  # int64[T,R]
    used_req_present: jnp.ndarray  # bool[T,R]
    res_cnt: jnp.ndarray  # int64[T] — scheduler-cycle reservations
    res_cnt_present: jnp.ndarray  # bool[T]
    res_req: jnp.ndarray  # int64[T,R]
    res_req_present: jnp.ndarray  # bool[T,R]
    st_cnt_throttled: jnp.ndarray  # bool[T] — status.throttled.resourceCounts.pod
    st_req_throttled: jnp.ndarray  # bool[T,R] — status.throttled.resourceRequests
    st_req_flag_present: jnp.ndarray  # bool[T,R] — key present in the flag map

    def tree_flatten(self):
        return (
            (
                self.valid,
                self.thr_cnt,
                self.thr_cnt_present,
                self.thr_req,
                self.thr_req_present,
                self.used_cnt,
                self.used_cnt_present,
                self.used_req,
                self.used_req_present,
                self.res_cnt,
                self.res_cnt_present,
                self.res_req,
                self.res_req_present,
                self.st_cnt_throttled,
                self.st_req_throttled,
                self.st_req_flag_present,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def num_throttles(self) -> int:
        return self.valid.shape[0]

    @property
    def num_dims(self) -> int:
        return self.thr_req.shape[1]


@jax.tree_util.register_pytree_node_class
@dataclass
class PodBatch:
    """Padded pod-side arrays: [P] / [P,R]. Pod count is implicitly 1/pod."""

    valid: jnp.ndarray  # bool[P]
    req: jnp.ndarray  # int64[P,R]
    req_present: jnp.ndarray  # bool[P,R]

    def tree_flatten(self):
        return ((self.valid, self.req, self.req_present), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def num_pods(self) -> int:
        return self.valid.shape[0]


def _amount_into(
    row_req: np.ndarray,
    row_present: np.ndarray,
    requests: Optional[Dict[str, object]],
    dims: DimRegistry,
) -> None:
    for name, q in (requests or {}).items():
        j = dims.index_of(name)
        row_req[j] = to_milli(q)
        row_present[j] = True


def encode_throttle_state(
    throttles: Sequence[AnyThrottle],
    dims: DimRegistry,
    reserved: Optional[Sequence[Dict[str, object]]] = None,
    capacity: Optional[int] = None,
) -> ThrottleState:
    """Encode (Cluster)Throttle objects into a padded ThrottleState.

    ``reserved`` optionally supplies per-throttle reserved ResourceAmounts
    (as ``api.types.ResourceAmount``); defaults to empty.
    """
    from ..api.types import effective_threshold

    n = len(throttles)
    # register every name first so R is final before array allocation
    for thr in throttles:
        eff = effective_threshold(thr.spec.threshold, thr.status)
        for name in (eff.resource_requests or {}):
            dims.index_of(name)
        for name in (thr.status.used.resource_requests or {}):
            dims.index_of(name)
        for name in (thr.status.throttled.resource_requests or {}):
            dims.index_of(name)
    if reserved is not None:
        for ra in reserved:
            if ra is not None:
                for name in (ra.resource_requests or {}):
                    dims.index_of(name)

    T = capacity if capacity is not None else max(n, 1)
    R = dims.capacity

    valid = np.zeros(T, dtype=bool)
    thr_cnt = np.zeros(T, dtype=np.int64)
    thr_cnt_present = np.zeros(T, dtype=bool)
    thr_req = np.zeros((T, R), dtype=np.int64)
    thr_req_present = np.zeros((T, R), dtype=bool)
    used_cnt = np.zeros(T, dtype=np.int64)
    used_cnt_present = np.zeros(T, dtype=bool)
    used_req = np.zeros((T, R), dtype=np.int64)
    used_req_present = np.zeros((T, R), dtype=bool)
    res_cnt = np.zeros(T, dtype=np.int64)
    res_cnt_present = np.zeros(T, dtype=bool)
    res_req = np.zeros((T, R), dtype=np.int64)
    res_req_present = np.zeros((T, R), dtype=bool)
    st_cnt_throttled = np.zeros(T, dtype=bool)
    st_req_throttled = np.zeros((T, R), dtype=bool)
    st_req_flag_present = np.zeros((T, R), dtype=bool)

    for i, thr in enumerate(throttles):
        valid[i] = True
        eff = effective_threshold(thr.spec.threshold, thr.status)
        if eff.resource_counts is not None:
            thr_cnt[i] = eff.resource_counts
            thr_cnt_present[i] = True
        _amount_into(thr_req[i], thr_req_present[i], eff.resource_requests, dims)

        used = thr.status.used
        if used.resource_counts is not None:
            used_cnt[i] = used.resource_counts
            used_cnt_present[i] = True
        _amount_into(used_req[i], used_req_present[i], used.resource_requests, dims)

        if reserved is not None and i < len(reserved) and reserved[i] is not None:
            ra = reserved[i]
            if ra.resource_counts is not None:
                res_cnt[i] = ra.resource_counts
                res_cnt_present[i] = True
            _amount_into(res_req[i], res_req_present[i], ra.resource_requests, dims)

        st = thr.status.throttled
        st_cnt_throttled[i] = st.resource_counts_pod
        for name, flag in (st.resource_requests or {}).items():
            j = dims.index_of(name)
            st_req_flag_present[i, j] = True
            st_req_throttled[i, j] = flag

    return ThrottleState(
        valid=jnp.asarray(valid),
        thr_cnt=jnp.asarray(thr_cnt),
        thr_cnt_present=jnp.asarray(thr_cnt_present),
        thr_req=jnp.asarray(thr_req),
        thr_req_present=jnp.asarray(thr_req_present),
        used_cnt=jnp.asarray(used_cnt),
        used_cnt_present=jnp.asarray(used_cnt_present),
        used_req=jnp.asarray(used_req),
        used_req_present=jnp.asarray(used_req_present),
        res_cnt=jnp.asarray(res_cnt),
        res_cnt_present=jnp.asarray(res_cnt_present),
        res_req=jnp.asarray(res_req),
        res_req_present=jnp.asarray(res_req_present),
        st_cnt_throttled=jnp.asarray(st_cnt_throttled),
        st_req_throttled=jnp.asarray(st_req_throttled),
        st_req_flag_present=jnp.asarray(st_req_flag_present),
    )


def encode_pods(
    pods: Sequence[Pod],
    dims: DimRegistry,
    capacity: Optional[int] = None,
) -> PodBatch:
    """Encode pods' effective requests into a padded PodBatch."""
    n = len(pods)
    requests = [rl.pod_request_resource_list(p) for p in pods]
    for reqs in requests:
        for name in reqs:
            dims.index_of(name)

    P = capacity if capacity is not None else max(n, 1)
    R = dims.capacity
    valid = np.zeros(P, dtype=bool)
    req = np.zeros((P, R), dtype=np.int64)
    req_present = np.zeros((P, R), dtype=bool)
    for i, reqs in enumerate(requests):
        valid[i] = True
        for name, q in reqs.items():
            j = dims.index_of(name)
            req[i, j] = to_milli(q)
            req_present[i, j] = True
    return PodBatch(
        valid=jnp.asarray(valid), req=jnp.asarray(req), req_present=jnp.asarray(req_present)
    )


def selector_mask(
    pods: Sequence[Pod],
    throttles: Sequence[AnyThrottle],
    namespaces: Optional[Dict[str, object]] = None,
    pod_capacity: Optional[int] = None,
    throttle_capacity: Optional[int] = None,
) -> jnp.ndarray:
    """Reference-semantics [P,T] selector mask (host loop; small scale /
    tests). Throttles additionally require namespace equality
    (affectedThrottles lists only the pod's namespace —
    throttle_controller.go:248-269); ClusterThrottles match via namespace +
    pod selectors."""
    P = pod_capacity if pod_capacity is not None else max(len(pods), 1)
    T = throttle_capacity if throttle_capacity is not None else max(len(throttles), 1)
    mask = np.zeros((P, T), dtype=bool)
    for i, pod in enumerate(pods):
        for j, thr in enumerate(throttles):
            if isinstance(thr, Throttle):
                mask[i, j] = thr.namespace == pod.namespace and thr.spec.selector.matches_to_pod(pod)
            else:
                ns = (namespaces or {}).get(pod.namespace)
                if ns is None:
                    mask[i, j] = False
                else:
                    mask[i, j] = thr.spec.selector.matches_to_pod(pod, ns)
    return jnp.asarray(mask)
