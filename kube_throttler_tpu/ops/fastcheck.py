"""Residual-form fast check: per-cell compares only, no per-cell arithmetic.

Algebraic restatement of the 4-state check (``ops.check``): every addition in
steps 3-4 involves only pod-independent terms, so

    used + reserved + pod  >  threshold
⟺  pod  >  threshold - (used + reserved)          (exact in int64)

and step 3 (``used + reserved`` vs threshold) has no pod term at all. All
[T]/[T,R] quantities — saturation flags for both onEqual variants, the
step-4 residual, the count verdicts (the pod's count contribution is always
exactly 1) — are precomputed ONCE per state change by
``precompute_check_state``; the per-(pod,throttle,dim) inner loop is then
pure compares + boolean logic. On TPU (emulated s64) this roughly halves the
dense-sweep op count versus the direct form.

Overflow note: ``threshold - (used+reserved)`` cannot overflow for any state
this framework produces (used/reserved are sums of non-negative pod amounts,
thresholds are admission-scale quantities ≪ 2^62).

Outputs are bit-identical to ``check_pods`` / ``check_pods_compact``
(property-tested in tests/test_fastcheck.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from .check import (
    CHECK_ACTIVE,
    CHECK_INSUFFICIENT,
    CHECK_NOT_AFFECTED,
    CHECK_NOT_THROTTLED,
    CHECK_POD_EXCEEDS,
)
from .schema import PodBatch, ThrottleState


@jax.tree_util.register_pytree_node_class
@dataclass
class CheckPrecomp:
    """Pod-independent per-throttle tensors for the residual-form check."""

    valid: jnp.ndarray  # bool[T]
    thr_req: jnp.ndarray  # int64[T,R] — step-1 compare target
    thr_req_present: jnp.ndarray  # bool[T,R]
    exceeds_cnt: jnp.ndarray  # bool[T] — 1 > thr_cnt (step 1, onEqual=False)
    st_cnt: jnp.ndarray  # bool[T] — status.throttled count flag
    st_req: jnp.ndarray  # bool[T,R] — status.throttled request flag ∧ present
    sat_cnt_ge: jnp.ndarray  # bool[T] — step-3 count, onEqual=True
    sat_cnt_gt: jnp.ndarray  # bool[T] — step-3 count, onEqual=False
    sat_req_ge: jnp.ndarray  # bool[T,R]
    sat_req_gt: jnp.ndarray  # bool[T,R]
    resid: jnp.ndarray  # int64[T,R] — thr - (used+reserved), step-4 target
    over_cnt_ge: jnp.ndarray  # bool[T] — step-4 count, onEqual=True
    over_cnt_gt: jnp.ndarray  # bool[T]

    def tree_flatten(self):
        return (
            (
                self.valid, self.thr_req, self.thr_req_present, self.exceeds_cnt,
                self.st_cnt, self.st_req, self.sat_cnt_ge, self.sat_cnt_gt,
                self.sat_req_ge, self.sat_req_gt, self.resid,
                self.over_cnt_ge, self.over_cnt_gt,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.jit
def precompute_check_state(state: ThrottleState) -> CheckPrecomp:
    au_cnt = state.used_cnt + state.res_cnt
    au_cnt_present = state.used_cnt_present | state.res_cnt_present
    au_req = state.used_req + state.res_req
    au_req_present = state.used_req_present | state.res_req_present

    sat_cnt_base = state.thr_cnt_present & au_cnt_present
    sat_req_base = state.thr_req_present & au_req_present

    return CheckPrecomp(
        valid=state.valid,
        thr_req=state.thr_req,
        thr_req_present=state.thr_req_present,
        exceeds_cnt=state.thr_cnt_present & (1 > state.thr_cnt),
        st_cnt=state.st_cnt_throttled,
        st_req=state.st_req_flag_present & state.st_req_throttled,
        sat_cnt_ge=sat_cnt_base & (au_cnt >= state.thr_cnt),
        sat_cnt_gt=sat_cnt_base & (au_cnt > state.thr_cnt),
        sat_req_ge=sat_req_base & (au_req >= state.thr_req),
        sat_req_gt=sat_req_base & (au_req > state.thr_req),
        resid=state.thr_req - au_req,
        # step-4 count: total count = au_cnt + 1, always present
        over_cnt_ge=state.thr_cnt_present & (au_cnt + 1 >= state.thr_cnt),
        over_cnt_gt=state.thr_cnt_present & (au_cnt + 1 > state.thr_cnt),
    )


def _classify_fast(pre: CheckPrecomp, pods: PodBatch, mask: jnp.ndarray,
                   on_equal: bool, step3_on_equal: bool) -> jnp.ndarray:
    if pre.thr_req.shape[1] != pods.req.shape[1]:
        raise ValueError(
            f"resource-dim mismatch: precomp has R={pre.thr_req.shape[1]} "
            f"but pod batch has R={pods.req.shape[1]}"
        )
    pod_req = pods.req[:, None, :]  # [P,1,R]
    pod_present = pods.req_present[:, None, :]
    pod_nonzero = pod_present & (pod_req != 0)

    # step 1 — pod alone > threshold
    exceeds = pre.exceeds_cnt[None, :] | jnp.any(
        pre.thr_req_present[None, :, :]
        & pod_nonzero
        & (pod_req > pre.thr_req[None, :, :]),
        axis=-1,
    )

    # step 2 — persisted flags
    st_active = pre.st_cnt[None, :] | jnp.any(
        pre.st_req[None, :, :] & pod_nonzero, axis=-1
    )

    # step 3 — saturation (fully precomputed; only the pod-nonzero gate is
    # per-cell)
    sat_cnt = pre.sat_cnt_ge if step3_on_equal else pre.sat_cnt_gt
    sat_req = pre.sat_req_ge if step3_on_equal else pre.sat_req_gt
    saturated = sat_cnt[None, :] | jnp.any(sat_req[None, :, :] & pod_nonzero, axis=-1)

    # step 4 — pod vs residual
    over_cnt = pre.over_cnt_ge if on_equal else pre.over_cnt_gt
    if on_equal:
        req_over = pod_req >= pre.resid[None, :, :]
    else:
        req_over = pod_req > pre.resid[None, :, :]
    insufficient = over_cnt[None, :] | jnp.any(
        pre.thr_req_present[None, :, :] & pod_nonzero & req_over, axis=-1
    )

    result = jnp.where(
        exceeds,
        jnp.int8(CHECK_POD_EXCEEDS),
        jnp.where(
            st_active | saturated,
            jnp.int8(CHECK_ACTIVE),
            jnp.where(insufficient, jnp.int8(CHECK_INSUFFICIENT), jnp.int8(CHECK_NOT_THROTTLED)),
        ),
    )
    affected = mask & pre.valid[None, :] & pods.valid[:, None]
    return jnp.where(affected, result, jnp.int8(CHECK_NOT_AFFECTED))


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def fast_check_pods(pre: CheckPrecomp, pods: PodBatch, mask: jnp.ndarray,
                    on_equal: bool = False, step3_on_equal: bool = True) -> jnp.ndarray:
    """Residual-form full [P,T] classification — same contract as check_pods
    but taking the precomputed state."""
    return _classify_fast(pre, pods, mask, on_equal, step3_on_equal)


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def fast_check_pods_compact(pre: CheckPrecomp, pods: PodBatch, mask: jnp.ndarray,
                            on_equal: bool = False, step3_on_equal: bool = True):
    from .check import statuses_to_compact

    return statuses_to_compact(_classify_fast(pre, pods, mask, on_equal, step3_on_equal))


@jax.tree_util.register_pytree_node_class
@dataclass
class CheckPrecompPacked:
    """CheckPrecomp repacked into THREE tensors for the indexed hot path.

    Rationale (measured on v5e through this environment): each small op in a
    chained dispatch costs ~5-7us regardless of size, so the 13-tensor gather
    + ~40-op classify dominates single-pod latency. Packing collapses it to
    3 gathers, ONE int64 compare plane, one fused boolean reduction, and a
    3-deep where chain.

    Layouts:
      vals   int64[T,2,R] — [0]=thr_req (step-1 target), [1]=resid (step-4)
      planes bool [T,4,R] — [0]=thr_req_present, [1]=st_req,
                            [2]=sat_req_ge, [3]=sat_req_gt
      scal   bool [T,8]   — valid, exceeds_cnt, st_cnt, sat_cnt_ge,
                            sat_cnt_gt, over_cnt_ge, over_cnt_gt, pad
    """

    vals: jnp.ndarray
    planes: jnp.ndarray
    scal: jnp.ndarray

    def tree_flatten(self):
        return ((self.vals, self.planes, self.scal), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.jit
def pack_check_state(pre: CheckPrecomp) -> CheckPrecompPacked:
    vals = jnp.stack([pre.thr_req, pre.resid], axis=1)
    planes = jnp.stack(
        [pre.thr_req_present, pre.st_req, pre.sat_req_ge, pre.sat_req_gt], axis=1
    )
    scal = jnp.stack(
        [
            pre.valid, pre.exceeds_cnt, pre.st_cnt, pre.sat_cnt_ge,
            pre.sat_cnt_gt, pre.over_cnt_ge, pre.over_cnt_gt,
            jnp.zeros_like(pre.valid),
        ],
        axis=1,
    )
    return CheckPrecompPacked(vals=vals, planes=planes, scal=scal)


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def fast_check_pod_packed(
    packed: CheckPrecompPacked,
    pod_req: jnp.ndarray,  # int64[R]
    pod_req_present: jnp.ndarray,  # bool[R]
    thr_idx: jnp.ndarray,  # int32[K]
    idx_valid: jnp.ndarray,  # bool[K]
    on_equal: bool = False,
    step3_on_equal: bool = True,
) -> jnp.ndarray:
    """Packed-layout single-pod check; bit-identical to
    ``fast_check_pod_indexed`` (property-tested)."""
    g_vals = packed.vals[thr_idx]  # [K,2,R]
    g_planes = packed.planes[thr_idx]  # [K,4,R]
    g_scal = packed.scal[thr_idx]  # [K,8]

    pod_nonzero = pod_req_present & (pod_req != 0)  # [R]

    # one int64 compare plane: pod vs [thr_req, resid']. ``>=`` for step 4
    # under onEqual folds into ``>`` against resid-1 (exact in int64: resid
    # is thr-(used+res), admission-scale magnitudes); the adjustment is an
    # elementwise subtract, not a scatter.
    targets = g_vals
    if on_equal:
        targets = targets - jnp.array([0, 1], dtype=targets.dtype)[None, :, None]
    cmp = pod_req[None, None, :] > targets  # [K,2,R]

    sat_plane = g_planes[:, 2] if step3_on_equal else g_planes[:, 3]
    hits = jnp.stack(
        [
            g_planes[:, 0] & cmp[:, 0],  # step 1: pod alone exceeds
            g_planes[:, 1],  # step 2: persisted flag
            sat_plane,  # step 3: saturation
            g_planes[:, 0] & cmp[:, 1],  # step 4: pod vs residual
        ],
        axis=1,
    )
    hits = jnp.any(hits & pod_nonzero[None, None, :], axis=-1)  # [K,4]

    exceeds = g_scal[:, 1] | hits[:, 0]
    sat_cnt = g_scal[:, 3] if step3_on_equal else g_scal[:, 4]
    active = g_scal[:, 2] | hits[:, 1] | sat_cnt | hits[:, 2]
    over_cnt = g_scal[:, 5] if on_equal else g_scal[:, 6]
    insufficient = over_cnt | hits[:, 3]

    result = jnp.where(
        exceeds,
        jnp.int8(CHECK_POD_EXCEEDS),
        jnp.where(
            active,
            jnp.int8(CHECK_ACTIVE),
            jnp.where(insufficient, jnp.int8(CHECK_INSUFFICIENT), jnp.int8(CHECK_NOT_THROTTLED)),
        ),
    )
    return jnp.where(idx_valid & g_scal[:, 0], result, jnp.int8(CHECK_NOT_AFFECTED))


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def fast_check_pod_indexed(
    pre: CheckPrecomp,
    pod_req: jnp.ndarray,  # int64[R]
    pod_req_present: jnp.ndarray,  # bool[R]
    thr_idx: jnp.ndarray,  # int32[K] — affected-throttle rows (pad anywhere)
    idx_valid: jnp.ndarray,  # bool[K] — live entries of thr_idx
    on_equal: bool = False,
    step3_on_equal: bool = True,
) -> jnp.ndarray:
    """Single-pod PreFilter against ONLY its affected throttles.

    The dense [1,T] sweep pays for all T throttles even though a pod matches
    a handful; the reference's own hot path iterates just
    ``affectedThrottles(pod)`` (throttle_controller.go:349-397). The host
    selector index supplies those K row ids; this kernel gathers the K
    precomputed rows and classifies in O(K·R). K is a padded static capacity
    so recompilation never happens on match-set churn.

    Returns int8[K] statuses (CHECK_NOT_AFFECTED at padded slots).
    """
    leaves, _ = pre.tree_flatten()
    gathered = CheckPrecomp(*[leaf[thr_idx] for leaf in leaves])
    pods = PodBatch(
        valid=jnp.ones((1,), dtype=bool),
        req=pod_req[None, :],
        req_present=pod_req_present[None, :],
    )
    return _classify_fast(gathered, pods, idx_valid[None, :], on_equal, step3_on_equal)[0]


# runtime retrace budget (KT_JIT_RETRACE_BUDGET): every jit entry here
# reports its compile-cache size per tick — see utils/retrace.py
from ..utils.retrace import register_all as _register_retrace

_register_retrace(globals(), __name__)
