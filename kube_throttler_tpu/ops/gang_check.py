"""Batched gang (pod-group) feasibility — "does the whole group fit under
every matched throttle simultaneously", one dispatch per scheduling tick.

Semantics are DERIVED from the per-pod 4-step check (ops/check.py), not
invented: gang admission is defined as *sequential* per-pod admission —
reserve member 1, check member 2 against used+reserved+member 1, and so on
(engine/gang.py ``sequential_gang_check`` is that oracle, and the
hypothesis property test pins this kernel to it). Under the PreFilter
flags (onEqual=False; step-3 onEqual True for Throttle, False for
ClusterThrottle) the sequential verdict is order-independent and collapses
to a GROUP-LEVEL form — for every throttle column any member matches:

- **member exceeds** (step 1): some matched member alone exceeds the
  (class-resolved) threshold;
- **active** (step 2): the persisted ``st_*`` flags block some matched
  member (pod-count flag always; a request flag needs a member requesting
  that dim non-zero);
- **overflow** (steps 3+4 fused): ``used + reserved + group_total >
  threshold`` on the count dim or any request dim some member requests
  non-zero. The fusion is exact, not an approximation: with integer
  counts, step 3's ``au + prefix ≥ thr`` at the last member equals
  step 4's ``au + total > thr``; for requests, a positive final
  contribution makes saturation of any strict prefix imply overflow of
  the total. (Both step-3 onEqual variants collapse to the same strict
  ``>``, which is why this kernel needs no static flag pair.)

Heterogeneity: thresholds arrive as a per-class tensor ``[A, T]`` /
``[A, T, R]`` (row 0 = the base effective thresholds; rows 1.. = the
per-accel-class replacements, ops/overrides.encode_class_thresholds) and
each group carries a class index — a gang is one job on one accelerator
type, so the class is per-group, not per-member.

Shapes: members [N] with matched cols [N,K] (-1 padded, the same sparse
encoding as ``check_pods_gather``), group ids gid[N] in [0,G), groups
padded to G. Group totals materialize as [G,T]/[G,T,R] scatter-adds —
G is a small per-tick batch (ladder-padded), so the footprint is G× the
throttle state, not P×T. Everything fuses into one jitted call per kind
pair (``gang_check_both``): ONE dispatch per scheduling tick covers every
group against both kinds, no per-rank host loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _gang_classify(
    # member side
    pod_req,  # int64[N,R]
    pod_present,  # bool[N,R]
    member_valid,  # bool[N]
    cols,  # int32[N,K] (-1 padded)
    gid,  # int32[N] group index per member
    # throttle side (class-resolved thresholds + class-agnostic state)
    thr_valid,  # bool[T]
    cls_cnt,  # int64[A,T]
    cls_cnt_present,  # bool[A,T]
    cls_req,  # int64[A,T,R]
    cls_req_present,  # bool[A,T,R]
    st_cnt_throttled,  # bool[T]
    st_req_flag_present,  # bool[T,R]
    st_req_throttled,  # bool[T,R]
    au_cnt,  # int64[T] used+reserved counts (0 where absent)
    au_req,  # int64[T,R]
    # group side
    gclass,  # int32[G] per-group class row (0 = base)
    gvalid,  # bool[G]
    num_groups: int,
):
    """Core group classification → (ok bool[G], exceeds bool[G],
    active bool[G], blocked bool[G,T])."""
    G = num_groups
    T = thr_valid.shape[0]
    c = jnp.maximum(cols, 0)  # [N,K]
    slot = (cols >= 0) & thr_valid[c] & member_valid[:, None]  # [N,K]
    mclass = gclass[gid]  # [N] class row per member

    pod_nonzero = pod_present & (pod_req != 0)  # [N,R]

    # --- step 1 per slot: member alone vs its class threshold ------------
    t_cnt = cls_cnt[mclass[:, None], c]  # [N,K]
    t_cnt_p = cls_cnt_present[mclass[:, None], c]
    t_req = cls_req[mclass[:, None], c]  # [N,K,R]
    t_req_p = cls_req_present[mclass[:, None], c]
    exceeds_slot = t_cnt_p & (1 > t_cnt)
    exceeds_slot |= jnp.any(
        t_req_p & pod_present[:, None, :] & (pod_req[:, None, :] > t_req)
        & (pod_req[:, None, :] != 0),
        axis=-1,
    )
    exceeds_slot &= slot

    # --- step 2 per slot: persisted flags (class-agnostic) ---------------
    active_slot = st_cnt_throttled[c] | jnp.any(
        st_req_flag_present[c] & st_req_throttled[c] & pod_nonzero[:, None, :],
        axis=-1,
    )
    active_slot &= slot

    # per-group reductions of the member-level verdicts (scatter-max)
    z_i32 = jnp.zeros((G,), dtype=jnp.int32)
    g_exceeds = (
        z_i32.at[gid].max(jnp.any(exceeds_slot, axis=1).astype(jnp.int32)) > 0
    )
    g_active = (
        z_i32.at[gid].max(jnp.any(active_slot, axis=1).astype(jnp.int32)) > 0
    )

    # --- group totals per (group, col): segment-sum scatter ---------------
    gid2 = jnp.broadcast_to(gid[:, None], c.shape)  # [N,K]
    R = pod_req.shape[1]
    g_cnt = jnp.zeros((G, T), dtype=jnp.int64).at[gid2, c].add(
        slot.astype(jnp.int64)
    )
    g_req = jnp.zeros((G, T, R), dtype=jnp.int64).at[gid2, c].add(
        jnp.where(slot[:, :, None], pod_req[:, None, :], 0)
    )
    g_nz = (
        jnp.zeros((G, T, R), dtype=jnp.int32)
        .at[gid2, c]
        .max((slot[:, :, None] & pod_nonzero[:, None, :]).astype(jnp.int32))
        > 0
    )
    affected = g_cnt > 0  # [G,T]

    # --- steps 3+4 fused at group granularity -----------------------------
    thr_cnt_g = cls_cnt[gclass]  # [G,T]
    thr_cnt_p_g = cls_cnt_present[gclass]
    thr_req_g = cls_req[gclass]  # [G,T,R]
    thr_req_p_g = cls_req_present[gclass]
    over_cnt = thr_cnt_p_g & (au_cnt[None, :] + g_cnt > thr_cnt_g)
    over_req = jnp.any(
        thr_req_p_g & g_nz & (au_req[None, :, :] + g_req > thr_req_g), axis=-1
    )
    blocked = affected & (over_cnt | over_req)

    ok = gvalid & ~g_exceeds & ~g_active & ~jnp.any(blocked, axis=1)
    return ok, g_exceeds & gvalid, g_active & gvalid, blocked & gvalid[:, None]


@partial(jax.jit, static_argnames=("num_groups",))
def gang_check(
    pod_req, pod_present, member_valid, cols, gid,
    thr_valid, cls_cnt, cls_cnt_present, cls_req, cls_req_present,
    st_cnt_throttled, st_req_flag_present, st_req_throttled,
    au_cnt, au_req, gclass, gvalid, num_groups: int,
):
    """Single-kind batched gang feasibility (see module docstring)."""
    return _gang_classify(
        pod_req, pod_present, member_valid, cols, gid,
        thr_valid, cls_cnt, cls_cnt_present, cls_req, cls_req_present,
        st_cnt_throttled, st_req_flag_present, st_req_throttled,
        au_cnt, au_req, gclass, gvalid, num_groups,
    )


@partial(jax.jit, static_argnames=("num_groups",))
def gang_check_both(kind_a: dict, kind_b: dict, gclass, gvalid, num_groups: int):
    """BOTH kinds' group feasibility in ONE jitted dispatch — the per-tick
    form the device manager serves (``kind_a``/``kind_b`` are dicts of the
    per-kind operands of :func:`gang_check` minus gclass/gvalid; dict
    pytrees keep the signature readable). Returns ``(ok, per-kind detail)``
    where ``ok = ok_a ∧ ok_b`` and detail carries each kind's
    (ok, exceeds, active, blocked[G,T]) for reason construction."""
    out_a = _gang_classify(**kind_a, gclass=gclass, gvalid=gvalid, num_groups=num_groups)
    out_b = _gang_classify(**kind_b, gclass=gclass, gvalid=gvalid, num_groups=num_groups)
    return out_a[0] & out_b[0], (out_a, out_b)


# runtime retrace budget (KT_JIT_RETRACE_BUDGET): every jit entry here
# reports its compile-cache size per tick — see utils/retrace.py
from ..utils.retrace import register_all as _register_retrace

_register_retrace(globals(), __name__)
