"""The batched ordered 4-state admission check — the framework's hot kernel.

Reproduces ``check_throttled_for`` (reference throttle_types.go:128-153,
clusterthrottle_types.go:30-55) for every (pod, throttle) pair at once:

    1. pod alone > threshold                  → POD_EXCEEDS (onEqual=False)
    2. persisted status.throttled flags hit   → ACTIVE
    3. used + reserved saturates threshold    → ACTIVE
       (onEqual hardcoded True for Throttle — throttle_types.go:143 —
        caller's flag for ClusterThrottle — clusterthrottle_types.go:45)
    4. used + reserved + pod overflows        → INSUFFICIENT (caller's flag)
    else                                      → NOT_THROTTLED

Presence-mask algebra (absent ≠ zero) follows resource_amount.go:127-159:
a comparison only fires when the dimension is present in BOTH the threshold
and the used side; "blocks this pod" additionally requires the pod to
request that resource non-zero (resource_amount.go:46-65) — except the
pod-count flag, which always blocks.

Shapes: throttle state [T]/[T,R], pods [P]/[P,R], selector mask [P,T].
Everything broadcasts to [P,T,R] inside a single XLA fusion and reduces over
R — no [P,T,R] intermediate is materialized at the default sizes. Three
output forms:

- ``check_pods``          → int8[P,T] full classification (explain path,
  oracle diffing, reason-string formatting for blocked pods);
- ``check_pods_compact``  → int32[P,4] per-pod class counts + bool[P]
  schedulable (dense batch form: 100k×10k never materializes [P,T]);
- ``check_pods_gather``   → same outputs from int32[P,K] matched-cols lists
  instead of a mask: computes P×K×R, the batch path the device manager
  dispatches in production (real masks are sparse — K ≪ T).

The two static booleans (kind asymmetry, caller onEqual) select among 4
compiled variants; shapes are padded so object churn never recompiles.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .schema import PodBatch, ThrottleState

CHECK_NOT_AFFECTED = -1
CHECK_NOT_THROTTLED = 0
CHECK_ACTIVE = 1
CHECK_INSUFFICIENT = 2
CHECK_POD_EXCEEDS = 3

STATUS_NAMES = {
    CHECK_NOT_AFFECTED: "not-affected",
    CHECK_NOT_THROTTLED: "not-throttled",
    CHECK_ACTIVE: "active",
    CHECK_INSUFFICIENT: "insufficient",
    CHECK_POD_EXCEEDS: "pod-requests-exceeds-threshold",
}


def _cmp(u, t, on_equal: bool):
    return u >= t if on_equal else u > t


def _classify_core(
    pod_req, pod_present, pod_nonzero,
    thr_cnt, thr_cnt_present, thr_req, thr_req_present,
    st_cnt_throttled, st_req_flag_present, st_req_throttled,
    au_cnt, au_cnt_present, au_req, au_req_present,
    on_equal: bool, step3_on_equal: bool, axis: int = -1,
):
    """The 4-step ordered resolution on broadcast-compatible operands:
    pod side [P,1(,R)], throttle side [1,T(,R)] (dense) or R-leading
    [R,P,1] / [R,P,K] (gather — see _gather_statuses for why). ``axis``
    names the R dimension of the per-resource operands; the count-side
    operands never carry it. One body ⇒ the dense and sparse kernels
    cannot drift."""
    # --- step 1: pod alone vs threshold (onEqual=False) -------------------
    # pod count is always 1 and always present
    exceeds_cnt = thr_cnt_present & (1 > thr_cnt)
    exceeds_req = jnp.any(
        thr_req_present & pod_present & (pod_req > thr_req) & (pod_req != 0), axis=axis
    )
    exceeds = exceeds_cnt | exceeds_req

    # --- step 2: persisted throttled flags --------------------------------
    st_active = st_cnt_throttled | jnp.any(
        st_req_flag_present & st_req_throttled & pod_nonzero, axis=axis
    )

    # --- step 3: used + reserved saturation -------------------------------
    sat_cnt = thr_cnt_present & au_cnt_present & _cmp(au_cnt, thr_cnt, step3_on_equal)
    sat_req = jnp.any(
        thr_req_present
        & au_req_present
        & _cmp(au_req, thr_req, step3_on_equal)
        & pod_nonzero,
        axis=axis,
    )
    saturated = sat_cnt | sat_req

    # --- step 4: used + reserved + pod overflow ---------------------------
    # pod contributes count 1 (always present) and its requests
    tot_cnt = au_cnt + 1
    tot_req = au_req + pod_req
    tot_req_present = au_req_present | pod_present

    over_cnt = thr_cnt_present & _cmp(tot_cnt, thr_cnt, on_equal)
    over_req = jnp.any(
        thr_req_present
        & tot_req_present
        & _cmp(tot_req, thr_req, on_equal)
        & pod_nonzero,
        axis=axis,
    )
    insufficient = over_cnt | over_req

    # --- ordered resolution ----------------------------------------------
    return jnp.where(
        exceeds,
        jnp.int8(CHECK_POD_EXCEEDS),
        jnp.where(
            st_active | saturated,
            jnp.int8(CHECK_ACTIVE),
            jnp.where(insufficient, jnp.int8(CHECK_INSUFFICIENT), jnp.int8(CHECK_NOT_THROTTLED)),
        ),
    )


def _classify(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
              on_equal: bool, step3_on_equal: bool) -> jnp.ndarray:
    """Core classification → int8[P,T]. Static flags pick the variant."""
    # trace-time guard: DimRegistry capacity may have doubled between the
    # throttle-state and pod-batch encodes; fail with an actionable message
    # instead of an opaque XLA broadcast error
    if state.thr_req.shape[1] != pods.req.shape[1]:
        raise ValueError(
            f"resource-dim mismatch: throttle state has R={state.thr_req.shape[1]} "
            f"but pod batch has R={pods.req.shape[1]}; the dim registry grew — "
            "re-encode both against the same capacity"
        )
    if mask.shape != (pods.req.shape[0], state.thr_req.shape[0]):
        raise ValueError(
            f"mask shape {mask.shape} != (P={pods.req.shape[0]}, T={state.thr_req.shape[0]})"
        )
    # pod-side broadcast views: [P,1,R] vs throttle [1,T,R]
    pod_req = pods.req[:, None, :]
    pod_present = pods.req_present[:, None, :]
    pod_nonzero = pod_present & (pod_req != 0)

    result = _classify_core(
        pod_req, pod_present, pod_nonzero,
        state.thr_cnt[None, :], state.thr_cnt_present[None, :],
        state.thr_req[None, :, :], state.thr_req_present[None, :, :],
        state.st_cnt_throttled[None, :],
        state.st_req_flag_present[None, :, :], state.st_req_throttled[None, :, :],
        (state.used_cnt + state.res_cnt)[None, :],
        (state.used_cnt_present | state.res_cnt_present)[None, :],
        (state.used_req + state.res_req)[None, :, :],
        (state.used_req_present | state.res_req_present)[None, :, :],
        on_equal, step3_on_equal,
    )
    affected = mask & state.valid[None, :] & pods.valid[:, None]
    return jnp.where(affected, result, jnp.int8(CHECK_NOT_AFFECTED))


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def check_pods(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
               on_equal: bool = False, step3_on_equal: bool = True) -> jnp.ndarray:
    """Full [P,T] classification (int8)."""
    return _classify(state, pods, mask, on_equal, step3_on_equal)


def statuses_to_compact(statuses: jnp.ndarray):
    """[P,T] statuses → (counts int32[P,4], schedulable bool[P]); the
    schedulable gate mirrors PreFilter (plugin.go:177-180). Shared by every
    compact path so the gate can never silently diverge between kernels."""
    counts = jnp.stack(
        [jnp.sum(statuses == c, axis=1, dtype=jnp.int32) for c in range(4)], axis=1
    )
    schedulable = (
        counts[:, CHECK_ACTIVE] + counts[:, CHECK_INSUFFICIENT] + counts[:, CHECK_POD_EXCEEDS]
    ) == 0
    return counts, schedulable


def _compact(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
             on_equal: bool, step3_on_equal: bool):
    return statuses_to_compact(_classify(state, pods, mask, on_equal, step3_on_equal))


def check_step(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray):
    """Un-jitted forward step (PreFilter defaults: onEqual=False, Throttle
    kind) for embedding under an outer jit — returns (counts, schedulable)."""
    return _compact(state, pods, mask, False, True)


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def check_pods_gather(state: ThrottleState, pods: PodBatch, cols: jnp.ndarray,
                      on_equal: bool = False, step3_on_equal: bool = True):
    """Sparse batch check: ``cols`` int32[P,K] lists each pod's matched
    throttle columns (-1 pads empty slots). Gathers the K throttle rows per
    pod and runs the same 4-step resolution as ``check_pods_compact`` over
    [P,K,R] instead of [P,T,R] — on real clusters each pod matches a
    handful of throttles, so K ≪ T and the batch drops ~T/K× in both FLOPs
    and memory traffic (and needs no [P,T] mask on device at all).

    Returns ``(counts int32[P,4], schedulable bool[P])``, identical to
    ``check_pods_compact`` given a cols/mask pair describing the same
    matches (parity-tested)."""
    if state.thr_req.shape[1] != pods.req.shape[1]:
        raise ValueError(
            f"resource-dim mismatch: throttle state has R={state.thr_req.shape[1]} "
            f"but pod batch has R={pods.req.shape[1]}; the dim registry grew — "
            "re-encode both against the same capacity"
        )
    if cols.ndim != 2 or cols.shape[0] != pods.req.shape[0]:
        raise ValueError(
            f"cols shape {cols.shape} != (P={pods.req.shape[0]}, K)"
        )
    return statuses_to_compact(
        _gather_statuses_blocked(state, pods, cols, on_equal, step3_on_equal)
    )


def _gather_statuses(state, pods, cols, on_equal, step3_on_equal):
    """Shared body of the sparse gather kernels: int8[P,K] per-slot
    statuses (CHECK_NOT_AFFECTED for padded/invalid slots).

    Orientation: the per-resource operands are gathered R-LEADING —
    ``state.thr_req.T[:, c]`` → [R,P,K] — not the naive ``thr_req[c]`` →
    [P,K,R]. TPU tiles the two minor dims (8,128): an R-minor gather
    result pads R=8 → 128 lanes, a 16× memory/bandwidth expansion that
    OOM'd the 100k×10k prewarm on a 16G v5e (4G per gathered u32 operand,
    observed r5). R-leading puts K on the lane dim (pads ≤2× at K=64 and
    not at all from 128 up) and R on the cheap outer dim; the reduction
    over R becomes ``axis=0``."""
    c = jnp.maximum(cols, 0)  # [P,K]; padded slots gather col 0 then mask out
    slot = (cols >= 0) & state.valid[c] & pods.valid[:, None]

    def g(a):  # [T,R] per-resource state → [R,P,K]
        return a.T[:, c]

    pod_req = pods.req.T[:, :, None]  # [R,P,1]
    pod_present = pods.req_present.T[:, :, None]
    pod_nonzero = pod_present & (pod_req != 0)

    result = _classify_core(
        pod_req, pod_present, pod_nonzero,
        state.thr_cnt[c], state.thr_cnt_present[c],
        g(state.thr_req), g(state.thr_req_present),
        state.st_cnt_throttled[c],
        g(state.st_req_flag_present), g(state.st_req_throttled),
        (state.used_cnt + state.res_cnt)[c],
        (state.used_cnt_present | state.res_cnt_present)[c],
        g(state.used_req + state.res_req),
        g(state.used_req_present | state.res_req_present),
        on_equal, step3_on_equal, axis=0,
    )
    return jnp.where(slot, result, jnp.int8(CHECK_NOT_AFFECTED))


# Peak-footprint governor for the sparse gather kernels: a [P,K] dispatch
# materializes ~6 gathered [R,P,K] operands (u32 limbs + presence preds),
# so an unbounded P×K — the 2048-col rung at the 131072-pod ladder cap is
# 2.1G elements — cannot be dispatched as one program on a 16G chip. Blocks
# of ≤ KT_GATHER_CHUNK_ELEMS padded elements (R × P_block × K_padded) run
# under lax.map: one compiled block body, device-serial blocks, bit-
# identical statuses. 64M elems ≈ 256M per u32 operand ≈ ~1.5G peak.
try:
    _GATHER_CHUNK_ELEMS = int(
        os.environ.get("KT_GATHER_CHUNK_ELEMS", str(64 * 1024 * 1024))
    )
except ValueError:
    # a malformed override must not kill module import (the tpu_watch.py
    # KT_TUNNEL_PROBE_PORT guard, for the same reason); fall back to the
    # 64M default
    _GATHER_CHUNK_ELEMS = 64 * 1024 * 1024


def _gather_statuses_blocked(state, pods, cols, on_equal, step3_on_equal):
    """_gather_statuses, chunked over P when the padded gather footprint
    exceeds _GATHER_CHUNK_ELEMS. Shapes are static under jit, so the block
    decomposition is a trace-time decision; P is padded to a whole number
    of blocks with invalid pods (slot masking already yields
    CHECK_NOT_AFFECTED there) and sliced back."""
    P, K = cols.shape
    R = pods.req.shape[1]
    k_pad = max(K, 128)  # lane-dim tile: K below 128 pads up to 128
    if P * k_pad * R <= _GATHER_CHUNK_ELEMS:
        return _gather_statuses(state, pods, cols, on_equal, step3_on_equal)
    pb = max(1, _GATHER_CHUNK_ELEMS // (k_pad * R))
    nb = -(-P // pb)
    pad = nb * pb - P

    def padp(a):
        widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        return jnp.pad(a, widths)

    pods_b = PodBatch(
        valid=padp(pods.valid).reshape(nb, pb),
        req=padp(pods.req).reshape(nb, pb, R),
        req_present=padp(pods.req_present).reshape(nb, pb, R),
    )
    cols_b = jnp.pad(cols, ((0, pad), (0, 0)), constant_values=-1).reshape(nb, pb, K)

    def block(xs):
        bpods, bcols = xs
        return _gather_statuses(state, bpods, bcols, on_equal, step3_on_equal)

    out = lax.map(block, (pods_b, cols_b))  # [nb, pb, K] int8
    return out.reshape(nb * pb, K)[:P]


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def check_pods_gather_statuses(
    state: ThrottleState, pods: PodBatch, cols: jnp.ndarray,
    on_equal: bool = False, step3_on_equal: bool = True,
):
    """``check_pods_gather`` returning the raw int8[P,K] per-slot statuses
    instead of compact counts — the micro-batching pre_filter front-end
    needs each pod's per-throttle classification to build reference reason
    strings (plugin.go:182-214), not just the verdict."""
    return _gather_statuses_blocked(state, pods, cols, on_equal, step3_on_equal)


@partial(jax.jit, static_argnames=("on_equal", "step3_on_equal"))
def check_pods_compact(state: ThrottleState, pods: PodBatch, mask: jnp.ndarray,
                       on_equal: bool = False, step3_on_equal: bool = True):
    """Hot-path form: per-pod class counts, no [P,T] materialization.

    Returns ``(counts int32[P,4], schedulable bool[P])`` where counts[p,c]
    is the number of affected throttles classifying pod p as class c
    (NOT_THROTTLED/ACTIVE/INSUFFICIENT/POD_EXCEEDS), and schedulable[p]
    mirrors PreFilter's gate: no active/insufficient/exceeds throttle
    (plugin.go:177-180).
    """
    return _compact(state, pods, mask, on_equal, step3_on_equal)


# runtime retrace budget (KT_JIT_RETRACE_BUDGET): every jit entry here
# reports its compile-cache size per tick — see utils/retrace.py
from ..utils.retrace import register_all as _register_retrace

_register_retrace(globals(), __name__)
