"""Simulated kube-apiserver speaking the list+watch wire protocol over real
HTTP — the integration-tier fixture SURVEY §4 calls for (the reference's
weakest point is its hard dependency on a kind cluster,
Makefile:130-142; this in-process server lets the same scenarios run
deterministically and at 100k-object scale).

Backed by a :class:`~kube_throttler_tpu.engine.store.Store`: tests (or a
driver process) mutate ``server.store`` and every watch connection streams
the resulting events exactly like a real apiserver:

- ``GET <collection>`` → a List document with per-item and list-level
  ``metadata.resourceVersion``;
- ``GET <collection>?watch=true&resourceVersion=N`` → chunked stream of
  ``{"type": ..., "object": ...}`` lines, replaying retained events with
  rv > N first, then live events; BOOKMARK events are emitted on idle so
  clients can advance their resume point (and detect dead streams);
- a resume point older than the retained per-kind event log → a 410-coded
  ERROR event (client-go relists on it; so does our Reflector);
- ``PUT .../status`` → optimistic-concurrency status update (stale
  ``metadata.resourceVersion`` → 409), mirroring the status subresource.

The event-log bound (``log_size``) is deliberately small-able so tests can
force the 410→relist path.

Server-side fault verbs: assigning a :class:`~..faults.FaultPlan` to
``server.faults`` lets integration tests script outages the CLIENT cannot
distinguish from real ones — sites ``mock.list`` (500 / 410 / stall),
``mock.watch.cut`` (stream severed mid-flight), ``mock.watch.gone``
(410 ERROR event mid-stream), ``mock.status.conflict`` (forced 409),
``mock.status.error`` (500 on a status PUT), ``mock.status.delay`` (a
status PUT stalls for the rule's delay) and ``mock.lease`` (lease
endpoint 500s/409s/stalls — leader-election chaos). This is the other half
of the fault matrix: client-side injection (transport.py) exercises our
error handling; server-side verbs exercise the full wire round trip through
real sockets.

HA fencing: writes may carry an ``X-Kube-Throttler-Epoch`` header
(engine/replication.py). The server tracks the highest epoch presented and
409s (reason ``FencedEpoch``) any write from a lower one — the wire half of
split-brain prevention: a paused-then-resumed deposed leader's status and
lease writes bounce without touching state.
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Queue
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from ..utils.lockorder import guard_attrs, make_lock
from ..api.serialization import (
    cluster_throttle_from_dict,
    object_to_dict,
    throttle_from_dict,
)
from ..engine.store import Event, EventType, NotFoundError, Store, key_of
from .transport import COLLECTION_PATHS, GROUP, LIST_KINDS, VERSION

_EVENT_TYPES = {
    EventType.ADDED: "ADDED",
    EventType.MODIFIED: "MODIFIED",
    EventType.DELETED: "DELETED",
}

_STATUS_RE = re.compile(
    rf"^/apis/{re.escape(GROUP)}/{re.escape(VERSION)}/"
    rf"(?:namespaces/(?P<ns>[^/]+)/throttles|clusterthrottles)"
    rf"/(?P<name>[^/]+)/status$"
)

_LEASE_RE = re.compile(
    r"^/apis/coordination\.k8s\.io/v1/namespaces/(?P<ns>[^/]+)/leases/(?P<name>[^/]+)$"
)
_LEASE_COLLECTION_RE = re.compile(
    r"^/apis/coordination\.k8s\.io/v1/namespaces/(?P<ns>[^/]+)/leases$"
)
_EVENTS_RE = re.compile(
    r"^/api/v1/namespaces/(?P<ns>[^/]+)/events(?:/(?P<name>[^/]+))?$"
)


@guard_attrs
class MockApiServer:
    """In-process apiserver double. ``start()`` binds an ephemeral port;
    ``server.url`` is the client-facing base URL."""

    # event logs, watch fan-out lists, lease/event docs, and continue
    # tokens are touched from every handler thread — all under the one
    # server lock. (self.store has its own lock; the two nest
    # store-inside-server only at the consistent-snapshot sites.)
    GUARDED_BY = {
        "_logs": "self._lock",
        "_dropped_rv": "self._lock",
        "_watchers": "self._lock",
        "_leases": "self._lock",
        "_lease_rv": "self._lock",
        "_events": "self._lock",
        "_continues": "self._lock",
        "_fencing_epoch": "self._lock",
        "stale_epoch_rejected": "self._lock",
    }

    def __init__(
        self,
        store: Optional[Store] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        log_size: int = 4096,
        bookmark_interval: float = 0.2,
        token: str = "",
    ):
        self.store = store or Store()
        self.host = host
        self._port = port
        self.token = token
        self.bookmark_interval = bookmark_interval
        self._lock = make_lock("mockserver")
        # per-kind bounded event log: deque of (rv, type_str, obj_dict)
        self._logs: Dict[str, deque] = {
            kind: deque(maxlen=log_size) for kind in COLLECTION_PATHS
        }
        # max rv ever evicted from each log — watches must 410 below it
        self._dropped_rv: Dict[str, int] = {kind: 0 for kind in COLLECTION_PATHS}
        # live watch subscriptions: kind -> list of Queues
        self._watchers: Dict[str, List[Queue]] = {
            kind: [] for kind in COLLECTION_PATHS
        }
        self._shutdown = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # serving generation: bumped by every start(); zombie watch loops
        # from a previous incarnation compare their captured generation and
        # exit instead of streaming from a "restarted" server (a real
        # apiserver restart severs every stream)
        self._generation = 0
        # coordination.k8s.io Lease objects (leader election): (ns, name) →
        # (doc, rv); versioned off their own counter under self._lock
        self._leases: Dict[Tuple[str, str], Tuple[Dict[str, Any], int]] = {}
        self._lease_rv = 0
        # v1 Events (Warning emission from remote daemons): (ns, name) → doc
        self._events: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # paginated-LIST continue tokens: token → (remaining items, list rv,
        # deadline). The real apiserver serves continue reads from the
        # snapshot the first page was cut at; the mock holds the remainder
        # server-side, TTL'd + capped so abandoned paginations can't leak
        # snapshots for the server's lifetime (this class also backs the
        # standalone daemon's wire mode, not just tests).
        self._continues: Dict[str, Tuple[List[Dict[str, Any]], int, float]] = {}
        self.continue_ttl = 300.0  # ≈ the apiserver's etcd compaction window
        self._continue_cap = 64
        # observability for tests: largest single LIST response (items)
        self.max_list_page_items = 0
        self.list_requests = 0
        # server-side fault verbs: a FaultPlan scripted by tests (see module
        # docstring); None = no injection
        self.faults = None
        # HA fencing (engine/replication.py): the highest epoch any writer
        # has presented via the X-Kube-Throttler-Epoch header. A write
        # carrying a LOWER epoch is a paused-then-resumed deposed leader —
        # rejected 409 with reason FencedEpoch and counted, exactly what
        # the real apiserver's resourceVersion + Lease machinery achieves
        # for the reference's embedded scheduler. Writes with no header
        # pass (non-HA clients are unaffected).
        self._fencing_epoch = 0
        self.stale_epoch_rejected = 0
        for kind in COLLECTION_PATHS:
            self.store.add_event_handler(kind, self._make_recorder(kind), replay=False)

    # -- event capture -----------------------------------------------------

    def _obj_dict(self, kind: str, obj, rv: int) -> Dict[str, Any]:
        doc = object_to_dict(obj)
        doc.setdefault("metadata", {})["resourceVersion"] = str(rv)
        return doc

    def _make_recorder(self, kind: str):
        def record(event: Event) -> None:
            # the event carries its own rv (batched dispatch runs after the
            # whole batch mutated, so latest_resource_version would report
            # the batch's LAST version for every event); events from older
            # dispatch paths without one fall back to the live counter
            rv = event.rv
            if rv is None:  # pragma: no cover — all store paths stamp rv now
                rv = self.store.latest_resource_version
            entry = (rv, _EVENT_TYPES[event.type], self._obj_dict(kind, event.obj, rv))
            with self._lock:
                log = self._logs[kind]
                if log.maxlen is not None and len(log) == log.maxlen and log:
                    self._dropped_rv[kind] = max(self._dropped_rv[kind], log[0][0])
                log.append(entry)
                for q in self._watchers[kind]:
                    q.put(entry)

        return record

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # response headers and body go out as separate sends; with
            # Nagle on, keep-alive clients wait out a ~40ms delayed ACK
            # per request (measured) — the real apiserver serves with
            # TCP_NODELAY too (Go net/http default)
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send_json(self, code: int, doc: Dict[str, Any]) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                if not server.token:
                    return True
                if self.headers.get("Authorization") == f"Bearer {server.token}":
                    return True
                self._send_json(401, {"message": "unauthorized"})
                return False

            def _json_body(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    return json.loads(self.rfile.read(length)) if length else {}
                except json.JSONDecodeError:
                    self._send_json(400, {"message": "invalid JSON"})
                    return None

            def do_GET(self):
                if not self._authorized():
                    return
                split = urlsplit(self.path)
                query = parse_qs(split.query)
                if _LEASE_RE.match(split.path):
                    server._serve_lease(self, "GET", split.path, None)
                    return
                kind = next(
                    (k for k, p in COLLECTION_PATHS.items() if p == split.path), None
                )
                if kind is None:
                    self._send_json(404, {"message": f"no route {split.path}"})
                    return
                if query.get("watch", ["false"])[0] == "true":
                    server._serve_watch(self, kind, query)
                else:
                    server._serve_list(self, kind, query)

            def do_POST(self):
                if not self._authorized():
                    return
                body = self._json_body()
                if body is None:
                    return
                path = urlsplit(self.path).path
                if _LEASE_COLLECTION_RE.match(path):
                    # create is POST to the collection, like the real
                    # apiserver; the object name comes from the body
                    server._serve_lease(self, "POST", path, body)
                elif _LEASE_RE.match(path):
                    self._send_json(
                        405, {"message": "POST to a named resource; use the collection"}
                    )
                elif _EVENTS_RE.match(path):
                    server._serve_event(self, "POST", path, body)
                else:
                    self._send_json(404, {"message": f"no route {path}"})

            def do_PUT(self):
                if not self._authorized():
                    return
                body = self._json_body()
                if body is None:
                    return
                path = urlsplit(self.path).path
                if _LEASE_RE.match(path):
                    server._serve_lease(self, "PUT", path, body)
                    return
                if _EVENTS_RE.match(path):
                    server._serve_event(self, "PUT", path, body)
                    return
                server._serve_status_put(self, self.path, body)

        self._httpd = ThreadingHTTPServer((self.host, self._port), Handler)
        self._httpd.daemon_threads = True
        # remember the RESOLVED port so a restart rebinds the same address
        # (clients keep their base URL across an apiserver restart)
        self._port = self._httpd.server_address[1]
        self._shutdown = threading.Event()
        self._generation += 1
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mock-apiserver", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # -- restart semantics (scenario engine: apiserver restart) -----------

    stop_serving = stop  # alias: state survives; only the listener dies

    def reset_rv_window(self) -> int:
        """Fresh resourceVersion retention horizon, as if the restarted
        apiserver's watch cache started empty over a compacted etcd: every
        retained event log is dropped, the per-kind 410 floor jumps to the
        CURRENT store RV, and outstanding LIST continue tokens expire. A
        client re-watching from any pre-restart resume point gets the 410
        ERROR event and must relist; a mid-pagination continue read gets
        410 and falls back to an unpaginated LIST — together the
        post-restart relist storm. Returns the new 410 floor."""
        floor = self.store.latest_resource_version
        with self._lock:
            for kind in self._logs:
                self._logs[kind].clear()
                self._dropped_rv[kind] = floor
            self._continues.clear()
        return floor

    def restart(self, reset_rv_window: bool = True, downtime_s: float = 0.0) -> None:
        """Stop serving, optionally reset the RV window (the
        apiserver-restart shape: clients must relist), wait ``downtime_s``
        (connection-refused window), then serve again on the SAME port."""
        self.stop_serving()
        if reset_rv_window:
            self.reset_rv_window()
        if downtime_s > 0:
            time.sleep(downtime_s)
        self.start()

    @property
    def port(self) -> int:
        assert self._httpd is not None, "server not started"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- endpoint implementations -----------------------------------------

    def _fault(self, site: str):
        """One fault-point check against the scripted plan (None when no
        plan is installed or the site stays quiet this hit)."""
        if self.faults is None:
            return None
        fault = self.faults.check(site)
        if fault is not None:
            fault.sleep()
        return fault

    # -- HA fencing ---------------------------------------------------------

    def _check_fencing(self, handler) -> bool:
        """Epoch gate for every write verb: a request whose
        ``X-Kube-Throttler-Epoch`` is below the highest epoch ever
        presented is a deposed leader's write — 409 (reason FencedEpoch),
        counted, and the state it targeted stays untouched. Requests
        without the header pass unexamined."""
        raw = handler.headers.get("X-Kube-Throttler-Epoch")
        if not raw:
            return True
        try:
            epoch = int(raw)
        except ValueError:
            handler._send_json(400, {"message": f"bad fencing epoch {raw!r}"})
            return False
        with self._lock:
            if epoch < self._fencing_epoch:
                self.stale_epoch_rejected += 1
                current = self._fencing_epoch
            else:
                self._fencing_epoch = epoch
                return True
        handler._send_json(
            409,
            {
                "message": f"stale fencing epoch: writer epoch {epoch} < "
                f"fenced epoch {current}",
                "reason": "FencedEpoch",
            },
        )
        return False

    @property
    def fencing_epoch(self) -> int:
        with self._lock:
            return self._fencing_epoch

    def stale_rejections(self) -> int:
        """Locked read of the stale-epoch PUT counter (GUARDED_BY; the
        harness asserts reading it bare raced the request threads —
        lockset detector, gen-3)."""
        with self._lock:
            return self.stale_epoch_rejected

    def _serve_list(self, handler, kind: str, query=None) -> None:
        fault = self._fault("mock.list")
        if fault is not None:
            if fault.mode == "gone":
                handler._send_json(
                    410, {"message": "injected: resourceVersion too old", "code": 410}
                )
                return
            if fault.mode == "error":
                handler._send_json(500, {"message": "injected apiserver error"})
                return
            # mode "delay": the sleep already happened — serve normally
        query = query or {}
        try:
            limit = int((query.get("limit") or ["0"])[0] or "0")
        except ValueError:
            limit = 0
        token = (query.get("continue") or [""])[0]
        now = time.monotonic()
        with self._lock:  # prune abandoned paginations
            for k in [k for k, (_, _, dl) in self._continues.items() if dl < now]:
                del self._continues[k]
        if token:
            with self._lock:
                entry = self._continues.pop(token, None)
            if entry is None:
                # expired/unknown continue token — the real apiserver 410s
                # and the client falls back to a full relist
                handler._send_json(
                    410, {"message": "The provided continue parameter is too old", "code": 410}
                )
                return
            items, list_rv, _ = entry
        else:
            with self.store._lock:  # consistent snapshot: items + list rv
                if kind == "Pod":
                    objs = self.store.list_pods()
                elif kind == "Namespace":
                    objs = self.store.list_namespaces()
                elif kind == "Throttle":
                    objs = self.store.list_throttles()
                else:
                    objs = self.store.list_cluster_throttles()
                items = [
                    self._obj_dict(
                        kind, o, self.store.resource_version(kind, key_of(kind, o))
                    )
                    for o in objs
                ]
                list_rv = self.store.latest_resource_version
        meta: Dict[str, Any] = {"resourceVersion": str(list_rv)}
        if limit and len(items) > limit:
            page, rest = items[:limit], items[limit:]
            next_token = uuid.uuid4().hex
            with self._lock:
                while len(self._continues) >= self._continue_cap:
                    # drop the oldest outstanding snapshot (dicts are
                    # insertion-ordered); that pagination will 410 and relist
                    del self._continues[next(iter(self._continues))]
                self._continues[next_token] = (
                    rest, list_rv, now + self.continue_ttl
                )
            meta["continue"] = next_token
            meta["remainingItemCount"] = len(rest)
        else:
            page = items
        with self._lock:
            self.list_requests += 1
            self.max_list_page_items = max(self.max_list_page_items, len(page))
        handler._send_json(
            200,
            {
                "apiVersion": "v1" if kind in ("Pod", "Namespace") else f"{GROUP}/{VERSION}",
                "kind": LIST_KINDS[kind],
                "metadata": meta,
                "items": page,
            },
        )

    def expire_continue_tokens(self) -> int:
        """Test hook: drop all outstanding continue tokens so the next
        continue read 410s (simulates the apiserver's token TTL)."""
        with self._lock:
            n = len(self._continues)
            self._continues.clear()
        return n

    def _write_watch_line(self, handler, doc: Dict[str, Any]) -> bool:
        data = json.dumps(doc).encode() + b"\n"
        try:
            handler.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
            handler.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False

    def _serve_watch(self, handler, kind: str, query) -> None:
        # capture THIS incarnation's shutdown event + generation: start()
        # replaces the event, so a zombie loop re-reading self._shutdown
        # after a restart would never see the stop signal
        shutdown = self._shutdown
        generation = self._generation
        since = int(query.get("resourceVersion", ["0"])[0] or "0")
        try:
            timeout_s = float(query.get("timeoutSeconds", ["0"])[0] or "0")
        except ValueError:
            timeout_s = 0.0
        deadline = (time.monotonic() + timeout_s) if timeout_s > 0 else None
        q: Queue = Queue()
        with self._lock:
            if since < self._dropped_rv[kind]:
                # compacted past the resume point → 410 ERROR event
                handler.send_response(200)
                handler.send_header("Content-Type", "application/json")
                handler.send_header("Transfer-Encoding", "chunked")
                handler.end_headers()
                self._write_watch_line(
                    handler,
                    {
                        "type": "ERROR",
                        "object": {
                            "kind": "Status",
                            "code": 410,
                            "reason": "Expired",
                            "message": f"too old resource version: {since}",
                        },
                    },
                )
                try:
                    handler.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
                return
            replay = [e for e in self._logs[kind] if e[0] > since]
            self._watchers[kind].append(q)
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()
            last_rv = since
            for rv, etype, obj in replay:
                if not self._write_watch_line(handler, {"type": etype, "object": obj}):
                    return
                last_rv = rv
            while not shutdown.is_set() and generation == self._generation:
                if deadline is not None and time.monotonic() >= deadline:
                    break  # graceful timeoutSeconds expiry; client re-watches
                fault = self._fault("mock.watch.cut")
                if fault is not None:
                    # sever the stream abruptly: no chunked terminator, so
                    # the client sees a mid-body connection loss (the torn
                    # TCP session a crashing apiserver leaves behind).
                    # shutdown(), not just close(): the handler's
                    # rfile/wfile still hold the socket, so close() alone
                    # would defer the FIN until the keep-alive loop ends
                    # and leave the client blocked on a silent stream.
                    import socket as _socket

                    try:
                        handler.connection.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
                    handler.close_connection = True
                    return
                fault = self._fault("mock.watch.gone")
                if fault is not None:
                    # mid-stream 410 ERROR event (compaction overtook the
                    # resume point while the stream was open)
                    self._write_watch_line(
                        handler,
                        {
                            "type": "ERROR",
                            "object": {
                                "kind": "Status",
                                "code": 410,
                                "reason": "Expired",
                                "message": "injected: too old resource version",
                            },
                        },
                    )
                    try:
                        handler.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        pass
                    return
                got = None
                try:
                    got = q.get(timeout=self.bookmark_interval)
                except Empty:
                    pass
                if got is not None:
                    rv, etype, obj = got
                    # batch-drain: one wfile.write for everything queued
                    # (the real apiserver's http2 frames coalesce the same
                    # way). Per-event write+flush cost one GIL round trip
                    # each — at ~1k ev/s with busy reconcile threads that
                    # queueing dominated wire-in delivery latency.
                    batch = [(rv, etype, obj)]
                    while len(batch) < 64:
                        try:
                            batch.append(q.get_nowait())
                        except Empty:
                            break
                    chunks = []
                    for rv, etype, obj in batch:
                        if rv <= last_rv:
                            continue  # already replayed
                        data = (
                            json.dumps({"type": etype, "object": obj}).encode()
                            + b"\n"
                        )
                        chunks.append(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                        last_rv = rv
                    if chunks:
                        try:
                            handler.wfile.write(b"".join(chunks))
                            handler.wfile.flush()
                        except (BrokenPipeError, ConnectionResetError, OSError):
                            return
                    continue
                # idle stream (queue empty for a bookmark interval): the
                # bookmark RV must never cover an event this watcher
                # has not been sent, or a reconnecting client resumes
                # past it and loses it forever. Read the store RV FIRST
                # (lock order is store→mock; taking mock then store
                # would deadlock against the recorder), then confirm
                # the queue is still empty under the mock lock: any
                # event recorded after the RV read is either already in
                # the queue (→ skip the bookmark) or carries a strictly
                # greater RV (→ the bookmark doesn't cover it).
                bm_rv = self.store.latest_resource_version
                with self._lock:
                    if not q.empty():
                        continue  # deliver the raced-in event first
                bookmark = {
                    "type": "BOOKMARK",
                    "object": {
                        "kind": kind,
                        "metadata": {"resourceVersion": str(bm_rv)},
                    },
                }
                if not self._write_watch_line(handler, bookmark):
                    return
            try:  # graceful stream end: chunked terminator → client sees EOF
                handler.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
        finally:
            with self._lock:
                try:
                    self._watchers[kind].remove(q)
                except ValueError:
                    pass

    def _serve_lease(
        self, handler, verb: str, path: str, body: Optional[Dict[str, Any]]
    ) -> None:
        """coordination.k8s.io Lease object: GET / POST(create) /
        PUT(update, optimistic via metadata.resourceVersion) — the three
        verbs client-go leader election needs. POST takes the collection
        path (name from body.metadata); GET/PUT take the named path.

        Fault verbs (site ``mock.lease``): mode "error" 500s any lease
        verb, "conflict" 409s a write, "delay" stalls — the leader-election
        chaos the failover e2e tests script. Writes also pass the fencing
        gate: a deposed leader's renew attempt must bounce."""
        fault = self._fault("mock.lease")
        if fault is not None:
            if fault.mode == "error":
                handler._send_json(500, {"message": "injected lease apiserver error"})
                return
            if fault.mode == "conflict" and verb in ("POST", "PUT"):
                handler._send_json(
                    409, {"message": "injected: the lease has been modified"}
                )
                return
            # mode "delay": the sleep already happened — serve normally
        if verb in ("POST", "PUT") and not self._check_fencing(handler):
            return
        if verb == "POST":
            m = _LEASE_COLLECTION_RE.match(path)
            name = str(((body or {}).get("metadata") or {}).get("name", ""))
            if not name:
                handler._send_json(400, {"message": "lease body missing metadata.name"})
                return
            key = (m.group("ns"), name)
        else:
            m = _LEASE_RE.match(path)
            key = (m.group("ns"), m.group("name"))
        with self._lock:
            existing = self._leases.get(key)
            if verb == "GET":
                if existing is None:
                    handler._send_json(404, {"message": f"lease {key} not found"})
                    return
                doc, rv = existing
                out = dict(doc)
                out["metadata"] = {**(doc.get("metadata") or {}), "resourceVersion": str(rv)}
                handler._send_json(200, out)
                return
            if verb == "POST":
                if existing is not None:
                    handler._send_json(409, {"message": f"lease {key} exists"})
                    return
                self._lease_rv += 1
                self._leases[key] = (body, self._lease_rv)
                out = dict(body)
                out["metadata"] = {
                    **(body.get("metadata") or {}),
                    "resourceVersion": str(self._lease_rv),
                }
                handler._send_json(201, out)
                return
            # PUT
            if existing is None:
                handler._send_json(404, {"message": f"lease {key} not found"})
                return
            _, current_rv = existing
            rv_raw = str((body.get("metadata") or {}).get("resourceVersion", "") or "")
            if rv_raw and rv_raw != str(current_rv):
                handler._send_json(
                    409,
                    {"message": f"lease {key}: resourceVersion conflict"},
                )
                return
            self._lease_rv += 1
            self._leases[key] = (body, self._lease_rv)
            out = dict(body)
            out["metadata"] = {
                **(body.get("metadata") or {}),
                "resourceVersion": str(self._lease_rv),
            }
            handler._send_json(200, out)

    def _serve_event(
        self, handler, verb: str, path: str, body: Dict[str, Any]
    ) -> None:
        """v1 Events: POST to the collection creates (409 if the name
        exists, like the real apiserver); PUT to the named path replaces
        (the recorder's count-bump). Tests read via the in-process
        ``events_in`` accessor — there is no GET route."""
        m = _EVENTS_RE.match(path)
        ns = m.group("ns")
        if verb == "POST":
            name = str(((body or {}).get("metadata") or {}).get("name", ""))
            if not name:
                handler._send_json(400, {"message": "event missing metadata.name"})
                return
            with self._lock:
                if (ns, name) in self._events:
                    handler._send_json(409, {"message": f"event {ns}/{name} exists"})
                    return
                self._events[(ns, name)] = body
            handler._send_json(201, body)
            return
        # PUT named
        name = m.group("name") or ""
        with self._lock:
            if (ns, name) not in self._events:
                handler._send_json(404, {"message": f"event {ns}/{name} not found"})
                return
            self._events[(ns, name)] = body
        handler._send_json(200, body)

    def events_in(self, namespace: str):
        """Test accessor: the Event docs posted for a namespace."""
        with self._lock:
            return [doc for (ns, _), doc in self._events.items() if ns == namespace]

    def _serve_status_put(self, handler, path: str, body: Dict[str, Any]) -> None:
        m = _STATUS_RE.match(urlsplit(path).path)
        if m is None:
            handler._send_json(404, {"message": f"no route {path}"})
            return
        if not self._check_fencing(handler):
            return  # deposed leader: the status write never touches state
        fault = self._fault("mock.status.conflict")
        if fault is not None:
            handler._send_json(
                409, {"message": "injected: the object has been modified"}
            )
            return
        fault = self._fault("mock.status.error")
        if fault is not None:
            handler._send_json(500, {"message": "injected apiserver error"})
            return
        # mock.status.delay: the _fault helper already slept the rule's
        # delay — the PUT then serves normally (publication slowdown, the
        # scenario engine's injected-regression knob)
        self._fault("mock.status.delay")
        kind = "Throttle" if m.group("ns") else "ClusterThrottle"
        rv_raw = str((body.get("metadata") or {}).get("resourceVersion", "") or "")
        try:
            if kind == "Throttle":
                obj = throttle_from_dict(body)
                key = f"{obj.namespace}/{obj.name}"
            else:
                obj = cluster_throttle_from_dict(body)
                key = obj.name
            try:
                rv_wanted = int(rv_raw) if rv_raw else None
            except ValueError:
                handler._send_json(400, {"message": f"bad resourceVersion {rv_raw!r}"})
                return
            with self.store._lock:  # version check + write atomically
                current_rv = self.store.resource_version(kind, key)
                if rv_wanted is not None and rv_wanted != current_rv:
                    handler._send_json(
                        409,
                        {
                            "message": f"Operation cannot be fulfilled on {kind} "
                            f"{key!r}: the object has been modified",
                        },
                    )
                    return
                if kind == "Throttle":
                    updated = self.store.update_throttle_status(obj)
                else:
                    updated = self.store.update_cluster_throttle_status(obj)
                new_rv = self.store.resource_version(kind, key)
            handler._send_json(200, self._obj_dict(kind, updated, new_rv))
        except NotFoundError:
            handler._send_json(404, {"message": f"{kind} {path} not found"})
        except KeyError:
            handler._send_json(404, {"message": f"{kind} {path} not found"})
