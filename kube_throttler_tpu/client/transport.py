"""Kubernetes apiserver wire transport: list+watch reflectors feeding the
local :class:`~kube_throttler_tpu.engine.store.Store`, plus a remote status
writer — the analog of the reference's client-go stack
(plugin.go:71-130: ``clientcmd.BuildConfigFromFlags(kubeconfig)`` →
clientset + SharedInformerFactory → WaitForCacheSync).

Design: the in-process ``Store`` stays the single informer-cache the whole
daemon reads (device mirror, informers/listers, controllers). In remote
mode a :class:`Reflector` per kind keeps that cache synced with a real
apiserver over the list+watch wire protocol:

- LIST once, diff against the cache (synthesizing ADDED/MODIFIED/DELETED so
  downstream handlers observe a consistent stream), remember the list
  resourceVersion;
- WATCH from that resourceVersion with ``allowWatchBookmarks``; BOOKMARK
  events advance the resume point without touching the cache
  (client-go reflector.go semantics);
- a closed/timed-out stream re-watches from the last seen resourceVersion;
  ``410 Gone`` (resourceVersion too old) falls back to a full relist —
  exactly client-go's ListAndWatch loop.

Status write-back goes straight to the apiserver (UpdateStatus,
throttle_controller.go:170); the local cache is NOT updated in place — the
write echoes back through the watch, which is the reference's
update-then-observe loop (§3.4 of SURVEY.md). Conflicts (409) surface as
:class:`~kube_throttler_tpu.engine.store.ConflictError` so the reconcile
requeues rate-limited, like client-go retry-on-conflict.

Only stdlib (http.client/json/ssl) — no kubernetes python client exists in
this environment, and the wire protocol is small enough to speak directly.
"""

from __future__ import annotations

import json
import logging
import os
import random
import socket
import ssl
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from http.client import HTTPConnection, HTTPException, HTTPSConnection
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple
from urllib.parse import urlencode, urlsplit

from ..api.serialization import (
    API_GROUP as GROUP,
    VERSION,
    object_from_dict,
    object_to_dict,
)
from ..api.types import ClusterThrottle, Throttle
from ..engine.store import ConflictError, NotFoundError, Store, key_of
from ..utils.lockorder import make_lock

logger = logging.getLogger(__name__)

# collection paths per kind (cluster-wide list+watch, like the reference's
# cluster-scoped informer factories)
COLLECTION_PATHS = {
    "Pod": "/api/v1/pods",
    "Namespace": "/api/v1/namespaces",
    "Throttle": f"/apis/{GROUP}/{VERSION}/throttles",
    "ClusterThrottle": f"/apis/{GROUP}/{VERSION}/clusterthrottles",
}

LIST_KINDS = {
    "Pod": "PodList",
    "Namespace": "NamespaceList",
    "Throttle": "ThrottleList",
    "ClusterThrottle": "ClusterThrottleList",
}


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class GoneError(ApiError):
    """410: the requested resourceVersion is no longer retained — relist."""

    def __init__(self, message: str = "resourceVersion too old"):
        super().__init__(410, message)


class FencedError(ApiError):
    """409 with reason ``FencedEpoch``: this writer's fencing epoch is
    stale — a newer leader has taken over (engine/replication.py). Unlike
    an ordinary optimistic-concurrency 409, this is TERMINAL for the
    writer: retrying can never succeed, and continuing to serve would be
    split brain. Callers fence themselves and stop."""

    def __init__(self, message: str = "stale fencing epoch"):
        super().__init__(409, message)


@dataclass(frozen=True)
class RestConfig:
    """The slice of a kubeconfig the transport needs (the analog of
    clientcmd's rest.Config)."""

    server: str
    token: str = ""
    verify_tls: bool = True
    ca_file: str = ""
    cert_file: str = ""  # client certificate (mTLS auth)
    key_file: str = ""
    # bearer token re-read from disk per request (mtime-cached): in-cluster
    # BoundServiceAccountTokens rotate ~hourly and a static string would
    # expire mid-run (client-go re-reads the mount the same way)
    token_file: str = ""


# decoded kubeconfig credential material: memfd-backed on Linux (never
# touches disk, gone with the process no matter how it dies); tempfile
# fallback elsewhere, cleaned at interpreter exit (best-effort — atexit
# does not run on SIGKILL, which is the memfd path's whole point)
_credential_fds: List[int] = []  # keep memfds alive for the process
_materialized_credentials: List[str] = []


def _cleanup_materialized() -> None:
    for path in _materialized_credentials:
        try:
            os.unlink(path)
        except OSError:
            pass
    _materialized_credentials.clear()


def _inline_or_file(data_b64: str, file_path: str, suffix: str) -> str:
    """kubeconfigs carry credentials either as file paths or inline base64
    ``*-data`` fields; the ssl module only takes paths, so inline data is
    materialized — into an anonymous memfd exposed via /proc/self/fd on
    Linux (a path that works in-process and can never outlive it), or a
    0600 temp file with atexit cleanup as the portable fallback."""
    if not data_b64:
        return file_path
    import base64

    raw = base64.b64decode(data_b64)
    if hasattr(os, "memfd_create"):
        try:
            fd = os.memfd_create(f"kubeconfig{suffix}")
            os.write(fd, raw)
            _credential_fds.append(fd)  # must stay open for the path to resolve
            return f"/proc/self/fd/{fd}"
        except OSError:
            pass  # fall through to the tempfile path
    import atexit
    import tempfile

    fd, tmp = tempfile.mkstemp(suffix=suffix)
    with os.fdopen(fd, "wb") as f:
        f.write(raw)
    if not _materialized_credentials:
        atexit.register(_cleanup_materialized)
    _materialized_credentials.append(tmp)
    return tmp


def parse_kubeconfig(path: str) -> RestConfig:
    """Minimal kubeconfig loader: current-context → cluster server + user
    credentials. Supports bearer tokens AND client certificates (both as
    file paths and inline ``*-data`` base64); exec/auth-provider plugins
    are not supported."""
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f) or {}

    def by_name(items, name):
        for item in items or []:
            if item.get("name") == name:
                return item
        raise ValueError(f"kubeconfig: no entry named {name!r}")

    current = cfg.get("current-context") or ""
    if not current:
        contexts = cfg.get("contexts") or []
        if not contexts:
            raise ValueError("kubeconfig: no contexts")
        current = contexts[0]["name"]
    ctx = by_name(cfg.get("contexts"), current).get("context", {})
    cluster = by_name(cfg.get("clusters"), ctx.get("cluster", "")).get("cluster", {})
    user: Dict[str, Any] = {}
    if ctx.get("user"):
        user = by_name(cfg.get("users"), ctx["user"]).get("user", {}) or {}
    return RestConfig(
        server=str(cluster.get("server", "")).rstrip("/"),
        token=str(user.get("token", "") or ""),
        verify_tls=not bool(cluster.get("insecure-skip-tls-verify")),
        ca_file=_inline_or_file(
            str(cluster.get("certificate-authority-data", "") or ""),
            str(cluster.get("certificate-authority", "") or ""),
            ".ca.crt",
        ),
        cert_file=_inline_or_file(
            str(user.get("client-certificate-data", "") or ""),
            str(user.get("client-certificate", "") or ""),
            ".client.crt",
        ),
        key_file=_inline_or_file(
            str(user.get("client-key-data", "") or ""),
            str(user.get("client-key", "") or ""),
            ".client.key",
        ),
    )


_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_config(sa_dir: str = _SA_DIR) -> RestConfig:
    """rest.InClusterConfig analog: apiserver address from the
    ``KUBERNETES_SERVICE_{HOST,PORT}`` env the kubelet injects, bearer
    token + CA from the ServiceAccount mount. The reference reaches this
    via ``BuildConfigFromFlags("")`` when no kubeconfig is configured
    (plugin.go:71 → clientcmd fallback)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise ValueError(
            "not running in-cluster: KUBERNETES_SERVICE_HOST is unset"
        )
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"  # IPv6 service host
    token_file = os.path.join(sa_dir, "token")
    ca_file = os.path.join(sa_dir, "ca.crt")
    if not os.path.exists(token_file):
        raise ValueError(f"in-cluster token missing at {token_file}")
    if not os.path.exists(ca_file):
        # never downgrade to an unverified connection while still sending
        # the bearer token: warn and verify against system roots instead
        # (client-go's ICC behavior for a partial SA mount)
        logger.warning(
            "in-cluster ca.crt missing at %s; verifying against system roots",
            ca_file,
        )
        ca_file = ""
    return RestConfig(
        server=f"https://{host}:{port}",
        token_file=token_file,
        ca_file=ca_file,
        verify_tls=True,
    )


class Backoff:
    """Jittered exponential backoff with a cap and reset-on-success — the
    client-go wait.Backoff analog the reflector loop uses instead of its
    former fixed 1.0s sleep (a thundering-herd and a 30×-too-slow recovery
    at the same time).

    ``next()`` returns ``base * factor^n`` capped at ``cap``, with the top
    half jittered (half-fixed/half-random, the "equal jitter" scheme): under
    a mass disconnect N reflectors spread over [d/2, d] instead of stamping
    the apiserver in lockstep. ``reset()`` (on a healthy stream) snaps the
    next delay back to ``base``."""

    def __init__(
        self,
        base: float = 1.0,
        cap: float = 30.0,
        factor: float = 2.0,
        rng: Optional[random.Random] = None,
    ):
        self.base = max(0.001, float(base))
        self.cap = float(cap)
        self.factor = float(factor)
        self._rng = rng or random.Random()
        self._attempts = 0  #: guarded-by: self._lock
        self._lock = make_lock("transport.backoff")

    @property
    def attempts(self) -> int:
        return self._attempts

    def next(self) -> float:
        with self._lock:
            raw = min(self.cap, self.base * (self.factor**self._attempts))
            self._attempts += 1
        return raw / 2 + self._rng.random() * (raw / 2)

    def reset(self) -> None:
        with self._lock:
            self._attempts = 0


class _TokenBucket:
    """Client-side write rate limiter — the analog of client-go's
    rest.Config QPS/Burst that the reference's generated clientset
    inherits (flowcontrol token bucket behind every request). Blocking
    ``take`` is the back-pressure: the status-writer thread slows down
    instead of flooding the apiserver."""

    def __init__(self, qps: float, burst: int):
        if qps <= 0 or burst < 1:
            # ValueError, not assert: reachable from CLI flags, and under
            # python -O a stripped assert would build a bucket whose take()
            # blocks forever (refill capped at burst=0)
            raise ValueError(f"qps must be > 0 and burst >= 1 (got {qps}, {burst})")
        self.qps = float(qps)
        self.burst = float(burst)
        self._tokens = float(burst)  #: guarded-by: self._lock
        self._stamp = time.monotonic()  #: guarded-by: self._lock
        self._lock = make_lock("transport.tokenbucket")

    def take(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._stamp) * self.qps
                )
                self._stamp = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)


class ApiClient:
    """Blocking REST client for the four watched kinds + status subresource.

    One short-lived connection per request; ``watch`` holds a streaming
    connection and yields decoded watch events.

    Mutating verbs (POST/PUT) pass a client-side token bucket
    (``qps``/``burst``), mirroring client-go's rest.Config rate limiting
    that the reference inherits (plugin.go:71 BuildConfigFromFlags →
    default 5 QPS / 10 burst). The defaults here are the kube-scheduler's
    clientConnection values (50/100): the streaming status pipeline
    sustains ~1k coalesced writes/sec against the in-memory store, and a
    5-QPS ceiling would make the remote mode's write lag pathological.
    Reads are not limited — they are a handful of long-lived watches.
    ``qps=None`` disables limiting (in-process/mock servers)."""

    def __init__(
        self,
        config: RestConfig,
        timeout: float = 10.0,
        qps: Optional[float] = 50.0,
        burst: int = 100,
        page_size: Optional[int] = None,
        faults=None,
        epoch_provider: Optional[Callable[[], Optional[int]]] = None,
    ):
        self.config = config
        self.timeout = timeout
        # optional FaultPlan (faults/plan.py): deterministic client-side
        # failure injection — connection resets, 409/410 storms, stalled
        # watch reads — for chaos tests. None in production.
        self.faults = faults
        # HA fencing (engine/replication.py): when set, every request
        # carries X-Kube-Throttler-Epoch so a fenced server can reject a
        # deposed leader's writes (FencedError). None for non-HA clients.
        self.epoch_provider = epoch_provider
        self.page_size = (
            self.DEFAULT_PAGE_SIZE if page_size is None else max(0, page_size)
        )
        self._write_bucket = _TokenBucket(qps, burst) if qps else None
        split = urlsplit(config.server)
        if split.scheme not in ("http", "https"):
            raise ValueError(f"unsupported server scheme: {config.server!r}")
        self._scheme = split.scheme
        self._host = split.hostname or "127.0.0.1"
        self._port = split.port or (443 if self._scheme == "https" else 80)
        # SSLContext cached per credential-file mtimes: re-parsing PEMs per
        # request would burden the status-write hot path, but a fully
        # static context would hold expired certs across on-disk rotation
        # (kubeadm renewal) for the process lifetime — a cheap stat per
        # connect picks up rotated files and rebuilds only then
        self._ssl_ctx = None
        self._ssl_ctx_stamp = None
        self._token_cache: Optional[Tuple[int, str]] = None
        self._conn_local = threading.local()  # keep-alive conn per thread
        if self._scheme == "https":
            self._ssl_ctx = self._build_ssl_ctx()

    def _cred_stamp(self):
        def mtime(path):
            try:
                return os.stat(path).st_mtime_ns
            except OSError:
                return None

        cfg = self.config
        return tuple(mtime(p) for p in (cfg.ca_file, cfg.cert_file, cfg.key_file) if p)

    def _build_ssl_ctx(self):
        cfg = self.config
        if cfg.verify_tls:
            ctx = ssl.create_default_context(cafile=cfg.ca_file or None)
        else:
            ctx = ssl._create_unverified_context()
        if cfg.cert_file:
            # mTLS client auth (kubeconfig client-certificate/key)
            ctx.load_cert_chain(cfg.cert_file, cfg.key_file or None)
        self._ssl_ctx_stamp = self._cred_stamp()
        return ctx

    # -- connection plumbing ----------------------------------------------

    def _connect(self, timeout: float):
        if self._scheme == "https":
            if self._ssl_ctx_stamp != self._cred_stamp():
                self._ssl_ctx = self._build_ssl_ctx()  # credentials rotated
            conn = HTTPSConnection(
                self._host, self._port, timeout=timeout, context=self._ssl_ctx
            )
        else:
            conn = HTTPConnection(self._host, self._port, timeout=timeout)
        # http.client writes headers and body as separate sends; with Nagle
        # on, the body segment waits out the peer's delayed ACK (~40ms per
        # request on a reused keep-alive connection — measured). client-go
        # disables Nagle the same way (net/http DisableKeepAlives=false +
        # TCP_NODELAY default in Go's net.TCPConn).
        conn.connect()
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except (OSError, AttributeError):
            pass  # non-TCP transports (tests with mocks) have no sockopt
        # the credential stamp THIS connection handshaked under: rotation
        # checks must be per-connection, not against the shared context
        # stamp (another thread's reconnect refreshes that, which would
        # let a stale-credential connection pass the check forever)
        conn._kt_cred_stamp = self._cred_stamp()
        return conn

    def _headers(self) -> Dict[str, str]:
        headers = {"Accept": "application/json"}
        token = self.config.token
        if self.config.token_file:
            token = self._file_token() or token
        if token:
            headers["Authorization"] = f"Bearer {token}"
        if self.epoch_provider is not None:
            epoch = self.epoch_provider()
            if epoch:
                headers["X-Kube-Throttler-Epoch"] = str(epoch)
        return headers

    def _file_token(self) -> str:
        """Token from ``token_file``, re-read on mtime change (rotating
        ServiceAccount mounts)."""
        path = self.config.token_file
        try:
            stamp = os.stat(path).st_mtime_ns
        except OSError:
            return self._token_cache[1] if self._token_cache else ""
        if self._token_cache is None or self._token_cache[0] != stamp:
            try:
                with open(path) as f:
                    self._token_cache = (stamp, f.read().strip())
            except OSError:
                return self._token_cache[1] if self._token_cache else ""
        return self._token_cache[1]

    def _request(
        self, method: str, path: str, body: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """One REST round trip over a per-thread KEEP-ALIVE connection.

        client-go multiplexes everything over reused connections; opening a
        fresh TCP (+TLS) connection per status PUT dominated the remote
        write path. The cached connection is retried ONCE on a fresh one
        when it fails — a reused keep-alive socket the server closed
        between requests is indistinguishable from a network error, and
        the single retry is the standard stale-socket pattern. Credential
        rotation invalidates the cache (the SSL context is stamped)."""
        if self.faults is not None:
            # a reset here is indistinguishable from a mid-request network
            # failure: callers see the same exception surface they would
            # from a dying apiserver
            self.faults.maybe_raise("transport.request", default=ConnectionResetError)
        headers = self._headers()
        payload = None
        if body is not None:
            payload = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        cached = getattr(self._conn_local, "conn", None)
        if cached is not None and self._scheme == "https":
            if getattr(cached, "_kt_cred_stamp", None) != self._cred_stamp():
                cached.close()
                cached = None  # rotated credentials: next connect rebuilds
        conn, reused = cached, cached is not None
        try:
            while True:
                if conn is None:
                    conn = self._connect(self.timeout)
                    reused = False
                try:
                    conn.request(method, path, body=payload, headers=headers)
                    resp = conn.getresponse()
                    data = resp.read()
                    break
                except (HTTPException, OSError, ssl.SSLError) as e:
                    conn.close()
                    conn = None
                    if isinstance(e, (socket.timeout, TimeoutError)):
                        # a timeout is NOT a stale keep-alive socket: the
                        # server may still be processing the (possibly
                        # non-idempotent) request — re-sending could
                        # double-apply it and blocks up to 2× timeout
                        raise
                    if method not in ("GET", "PUT", "DELETE", "HEAD"):
                        # non-idempotent (POST: event create, lease acquire):
                        # a reset AFTER the server processed the request is
                        # indistinguishable from a stale socket, and a
                        # resend double-applies. Same policy as Go net/http,
                        # which only retries idempotent methods (or when no
                        # request bytes were written).
                        raise
                    if not reused:
                        raise  # a fresh connection failing is a real error
            if resp.will_close:
                conn.close()
                self._conn_local.conn = None
            else:
                self._conn_local.conn = conn
        except BaseException:
            self._conn_local.conn = None
            raise
        if resp.status == 409:
            text = data.decode(errors="replace")[:200]
            if "FencedEpoch" in text or "stale fencing epoch" in text:
                raise FencedError(text)
            raise ConflictError(path)
        if resp.status == 404:
            raise NotFoundError(path)
        if resp.status == 410:
            raise GoneError(data.decode(errors="replace")[:200])
        if resp.status >= 400:
            raise ApiError(resp.status, data.decode(errors="replace")[:200])
        return json.loads(data) if data else {}

    # -- verbs -------------------------------------------------------------

    # client-go's pager chunks relists at 500 items/page by default; at 100k
    # pods an unbounded LIST is one giant response body on a single socket
    # read (reference client layer takes ListOptions on every List/Watch —
    # throttle.go:82-103)
    DEFAULT_PAGE_SIZE = 500

    def list_pages(
        self, kind: str, page_size: Optional[int] = None
    ) -> Iterator[Tuple[List[Dict[str, Any]], str]]:
        """Chunked LIST: yield ``(page items, list resourceVersion)`` per
        page, following ``metadata.continue`` tokens until exhausted.
        ``page_size=0`` disables chunking (one unbounded page). A 410 on an
        expired continue token surfaces as :class:`GoneError` — the caller
        decides whether to fall back to an unpaginated full relist."""
        limit = self.page_size if page_size is None else page_size
        token = ""
        while True:
            params = {}
            if limit:
                params["limit"] = str(limit)
            if token:
                params["continue"] = token
            path = COLLECTION_PATHS[kind]
            if params:
                path = f"{path}?{urlencode(params)}"
            doc = self._request("GET", path)
            meta = doc.get("metadata") or {}
            yield list(doc.get("items") or []), str(meta.get("resourceVersion", "0"))
            token = str(meta.get("continue") or "")
            if not token:
                return

    def list(
        self, kind: str, page_size: Optional[int] = None
    ) -> Tuple[List[Dict[str, Any]], str]:
        """LIST a collection → (item dicts, list resourceVersion). Paginates
        internally; use :meth:`list_pages` to stream pages without
        accumulating (the reflector's relist does)."""
        items: List[Dict[str, Any]] = []
        rv = "0"
        for page, rv in self.list_pages(kind, page_size):
            items.extend(page)
        return items, rv

    # a real apiserver bookmarks roughly once a minute on a quiet cluster;
    # the server-side timeoutSeconds ends the stream gracefully well before
    # the socket read timeout would tear the connection down, so idle
    # watches are NOT reconnect churn (client-go uses 5-10 min here)
    WATCH_TIMEOUT_SECONDS = 300

    def watch(
        self,
        kind: str,
        resource_version: str,
        stop: Optional[threading.Event] = None,
        read_timeout: Optional[float] = None,
    ) -> Iterator[Dict[str, Any]]:
        """WATCH a collection from ``resource_version``; yields raw watch
        event dicts ``{"type": ..., "object": {...}}`` (BOOKMARK included —
        the reflector advances its resume point on them). The stream ends
        on server close / timeoutSeconds expiry / read timeout (caller
        re-watches from the last RV) and raises :class:`GoneError` on an
        ERROR event carrying 410."""
        if read_timeout is None:
            read_timeout = self.WATCH_TIMEOUT_SECONDS + 30.0
        if self.faults is not None:
            self.faults.maybe_raise(
                "transport.watch.open", default=lambda: ApiError(500, "injected")
            )
        query = urlencode(
            {
                "watch": "true",
                "resourceVersion": resource_version,
                "allowWatchBookmarks": "true",
                "timeoutSeconds": str(self.WATCH_TIMEOUT_SECONDS),
            }
        )
        conn = self._connect(read_timeout)
        try:
            conn.request(
                "GET", f"{COLLECTION_PATHS[kind]}?{query}", headers=self._headers()
            )
            resp = conn.getresponse()
            if resp.status == 410:
                resp.read()
                raise GoneError()
            if resp.status >= 400:
                raise ApiError(resp.status, resp.read().decode(errors="replace")[:200])
            while stop is None or not stop.is_set():
                if self.faults is not None:
                    fault = self.faults.check("transport.watch.read")
                    if fault is not None:
                        fault.sleep()  # "delay" stalls the read (slow stream)
                        if fault.mode == "close":
                            return  # stream torn down — caller re-watches
                        if fault.mode == "gone":
                            raise GoneError("injected 410")
                        if fault.mode == "error":
                            raise fault.make_error()
                try:
                    line = resp.readline()
                except (socket.timeout, TimeoutError):
                    return  # idle stream — caller resumes from last RV
                except (OSError, ssl.SSLError):
                    return  # connection torn down
                except HTTPException:
                    # chunked stream severed mid-chunk (IncompleteRead):
                    # same recovery as a torn connection — re-watch
                    return
                if not line:
                    return  # server closed the stream
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                if event.get("type") == "ERROR":
                    obj = event.get("object") or {}
                    if obj.get("code") == 410:
                        raise GoneError(str(obj.get("message", "")))
                    raise ApiError(
                        int(obj.get("code", 500)), str(obj.get("message", ""))
                    )
                yield event
        finally:
            conn.close()

    def get(self, path: str) -> Dict[str, Any]:
        """GET a JSON document; 404 raises NotFoundError."""
        return self._request("GET", path)

    def post(self, path: str, body: Dict[str, Any]) -> Dict[str, Any]:
        """POST (create) a JSON document; 409 raises ConflictError."""
        if self._write_bucket is not None:
            self._write_bucket.take()
        return self._request("POST", path, body=body)

    def put(self, path: str, body: Dict[str, Any]) -> Dict[str, Any]:
        """PUT a JSON document (status-subresource / lease writes). The body
        must carry ``metadata.resourceVersion`` for optimistic concurrency;
        409 raises ConflictError."""
        if self._write_bucket is not None:
            self._write_bucket.take()
        if self.faults is not None:
            # 409 storm: same surface as a real optimistic-concurrency loss
            self.faults.maybe_raise(
                "transport.put.conflict", default=lambda: ConflictError(path)
            )
        return self._request("PUT", path, body=body)


@dataclass
class RemoteVersions:
    """Last-seen remote resourceVersion per (kind, key) — shared between the
    reflectors (writers) and the status writer (reader), because the local
    Store assigns its own local versions and the apiserver requires the
    REMOTE one on updates.

    ``set`` is MONOTONE for numeric resourceVersions: under watch/ingest
    backlog (a relist storm, sustained overload) the echo of an OLDER
    write can arrive hundreds of ms after a PUT response already recorded
    a fresher rv — last-writer-wins would plant the stale rv and turn
    every subsequent PUT of that key into a 409, whose retry backoff then
    head-of-line blocks the committer shard (measured as persistent
    "dropping status publication after N attempts" storms in the scenario
    corpus' saturated runs). etcd resourceVersions are globally
    monotonic, so keeping the max is always the freshest truth; a
    non-numeric rv (foreign server) falls back to last-writer-wins."""

    _lock: threading.Lock = field(
        default_factory=lambda: make_lock("transport.remoteversions")
    )
    #: guarded-by: self._lock
    _versions: Dict[Tuple[str, str], str] = field(default_factory=dict)

    def set(self, kind: str, key: str, rv: str) -> None:
        with self._lock:
            cur = self._versions.get((kind, key), "")
            if cur:
                try:
                    if int(rv) < int(cur):
                        return  # late echo: never regress the freshest rv
                except ValueError:
                    pass
            self._versions[(kind, key)] = rv

    def get(self, kind: str, key: str) -> str:
        with self._lock:
            return self._versions.get((kind, key), "")

    def drop(self, kind: str, key: str) -> None:
        with self._lock:
            self._versions.pop((kind, key), None)


class ReflectorMetrics:
    """client-go reflector-metrics analog, exported through the daemon's
    /metrics registry: lists/relists, watch (re)connects, events applied,
    410 falls — the signals that tell an operator the watch path is
    healthy vs thrashing."""

    def __init__(self, registry) -> None:
        self.lists = registry.counter_vec(
            "kube_throttler_reflector_lists_total",
            "LIST operations performed per kind (first sync + 410 relists)",
            ["kind"],
        )
        self.watches = registry.counter_vec(
            "kube_throttler_reflector_watches_total",
            "WATCH streams opened per kind (reconnects included)",
            ["kind"],
        )
        self.events = registry.counter_vec(
            "kube_throttler_reflector_events_total",
            "Watch events applied to the local cache per kind (bookmarks "
            "and unknown types excluded)",
            ["kind"],
        )
        self.gone = registry.counter_vec(
            "kube_throttler_reflector_gone_total",
            "410-expired resume points per kind (forced relists)",
            ["kind"],
        )


class Reflector:
    """client-go reflector for one kind: ListAndWatch into the Store."""

    def __init__(
        self,
        client: ApiClient,
        kind: str,
        store: Store,
        versions: Optional[RemoteVersions] = None,
        backoff: float = 1.0,
        metrics: Optional[ReflectorMetrics] = None,
        backoff_cap: float = 30.0,
        backoff_rng: Optional[random.Random] = None,
        ingest_batcher=None,
    ):
        self.client = client
        self.kind = kind
        self.store = store
        # optional MicroBatchIngest (engine/ingest.py): watch events route
        # through the adaptive micro-batcher instead of per-event store
        # calls — the wire parser never waits on the store lock, and a
        # backlog group-commits. Relists FLUSH it first (a relist diffs
        # the live store; unapplied queued events would read as deletions).
        self.ingest_batcher = ingest_batcher
        self.versions = versions
        # ``backoff`` stays the BASE delay (compat kwarg); the loop now
        # walks base→cap with jitter and resets on a healthy stream instead
        # of sleeping a fixed second per failure (transport hardening)
        self.backoff = backoff
        self._backoff = Backoff(base=backoff, cap=backoff_cap, rng=backoff_rng)
        self.metrics = metrics
        self.last_resource_version = "0"
        # consecutive failed list/watch attempts since the last healthy
        # stream — the /readyz health probe reads this (0 = healthy)
        self.consecutive_failures = 0
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _count(self, counter) -> None:
        if self.metrics is not None:
            counter(self.metrics).inc({"kind": self.kind})

    # -- store application -------------------------------------------------

    def _obj_from(self, item: Dict[str, Any]):
        obj = object_from_dict({**item, "kind": self.kind})
        rv = str((item.get("metadata") or {}).get("resourceVersion", ""))
        if self.versions is not None and rv:
            self.versions.set(self.kind, key_of(self.kind, obj), rv)
        return obj

    def _upsert(self, obj) -> None:
        store = self.store
        try:
            if self.kind == "Pod":
                store.update_pod(obj)
            elif self.kind == "Namespace":
                store.update_namespace(obj)
            elif self.kind == "Throttle":
                store.update_throttle(obj)
            else:
                store.update_cluster_throttle(obj)
        except NotFoundError:
            self._create(obj)

    def _create(self, obj) -> None:
        store = self.store
        try:
            if self.kind == "Pod":
                store.create_pod(obj)
            elif self.kind == "Namespace":
                store.create_namespace(obj)
            elif self.kind == "Throttle":
                store.create_throttle(obj)
            else:
                store.create_cluster_throttle(obj)
        except ValueError:
            self._upsert(obj)  # raced: exists already

    def _delete(self, obj) -> None:
        key = key_of(self.kind, obj)
        if self.versions is not None:
            self.versions.drop(self.kind, key)
        try:
            if self.kind == "Pod":
                self.store.delete_pod(obj.namespace, obj.name)
            elif self.kind == "Namespace":
                self.store.delete_namespace(obj.name)
            elif self.kind == "Throttle":
                self.store.delete_throttle(obj.namespace, obj.name)
            else:
                self.store.delete_cluster_throttle(obj.name)
        except NotFoundError:
            pass

    def _current_keys(self) -> Dict[str, Any]:
        if self.kind == "Pod":
            objs = self.store.list_pods()
        elif self.kind == "Namespace":
            objs = self.store.list_namespaces()
        elif self.kind == "Throttle":
            objs = self.store.list_throttles()
        else:
            objs = self.store.list_cluster_throttles()
        return {key_of(self.kind, o): o for o in objs}

    def _sync_list(self, items: List[Dict[str, Any]]) -> None:
        """Reconcile the cache with a full LIST: synthesize the minimal
        ADDED/MODIFIED/DELETED set (client-go's Replace)."""
        self._sync_pages(iter([(items, self.last_resource_version)]))

    # batched relist application: one store.apply_events per this many
    # changed objects. The store lock is held once per chunk (group-commit
    # journal line batch, one informer mirror pass, one workqueue fan-out)
    # and RELEASED between chunks — so a 100k-object relist storm no longer
    # serializes the controllers' flip express drains behind one per-event
    # lock acquisition per object (relist-storm backpressure, PR 8)
    RELIST_APPLY_CHUNK = 128

    def _sync_pages(
        self, pages: Iterator[Tuple[List[Dict[str, Any]], str]]
    ) -> str:
        """Streaming Replace: apply each LIST page to the cache as it
        arrives, then delete whatever the relist didn't mention. Memory
        high-water is one page of raw item dicts plus the seen-key set —
        not the whole collection — so a 100k-pod cold start never holds
        one giant response body.

        With an ingest batcher wired (the daemon's micro-batched mode) the
        changed objects land through :meth:`Store.apply_events` in bounded
        chunks instead of per-object store calls: the same batched path
        watch bursts take, with the same equivalence contract — and the
        flip express lane breathes between chunks instead of starving for
        the duration of a full relist."""
        current = self._current_keys()
        seen: set = set()
        rv = self.last_resource_version
        batched = self.ingest_batcher is not None
        chunk: List[Tuple[str, str, Any]] = []

        def flush_chunk() -> None:
            if chunk:
                self.store.apply_events(chunk)
                chunk.clear()

        for items, rv in pages:
            for item in items:
                obj = self._obj_from(item)
                key = key_of(self.kind, obj)
                seen.add(key)
                if key not in current:
                    if batched:
                        chunk.append(("upsert", self.kind, obj))
                    else:
                        self._create(obj)
                elif current[key] != obj:
                    if batched:
                        chunk.append(("upsert", self.kind, obj))
                    else:
                        self._upsert(obj)
                if len(chunk) >= self.RELIST_APPLY_CHUNK:
                    flush_chunk()
            flush_chunk()  # page boundary: never carry ops across pages
        for key, obj in current.items():
            if key not in seen:
                if batched:
                    if self.versions is not None:
                        self.versions.drop(self.kind, key)
                    chunk.append(("delete", self.kind, key))
                    if len(chunk) >= self.RELIST_APPLY_CHUNK:
                        flush_chunk()
                else:
                    self._delete(obj)
        flush_chunk()
        return rv

    def _relist(self) -> str:
        """Paginated relist; on a mid-pagination 410 (continue token
        expired server-side) fall back to ONE unpaginated full LIST, the
        same way client-go's pager does. Returns the list RV."""
        if self.ingest_batcher is not None:
            # the replace diff reads the live store; queued-but-unapplied
            # events would make current objects look deleted
            self.ingest_batcher.flush(timeout=30.0)
        self._count(lambda m: m.lists)
        try:
            return self._sync_pages(self.client.list_pages(self.kind))
        except GoneError:
            self._count(lambda m: m.gone)
            logger.info(
                "reflector %s: continue token expired mid-relist; "
                "falling back to unpaginated LIST",
                self.kind,
            )
            return self._sync_pages(self.client.list_pages(self.kind, 0))

    def _apply_event(self, event: Dict[str, Any]) -> None:
        etype = event.get("type")
        item = event.get("object") or {}
        rv = str((item.get("metadata") or {}).get("resourceVersion", ""))
        if etype == "BOOKMARK":
            if rv:
                self.last_resource_version = rv
            return
        obj = self._obj_from(item)
        if etype in ("ADDED", "MODIFIED") and self.ingest_batcher is not None:
            self.ingest_batcher.upsert(self.kind, obj)
        elif etype == "ADDED":
            self._create(obj)
        elif etype == "MODIFIED":
            self._upsert(obj)
        elif etype == "DELETED":
            if self.ingest_batcher is not None:
                if self.versions is not None:
                    self.versions.drop(self.kind, key_of(self.kind, obj))
                self.ingest_batcher.delete(self.kind, key_of(self.kind, obj))
            else:
                self._delete(obj)
        else:
            logger.warning("reflector %s: unknown watch event %r", self.kind, etype)
            return
        self._count(lambda m: m.events)  # applied to the cache (not bookmarks)
        if self._backoff.attempts or self.consecutive_failures:
            # an applied event IS the health signal: snap the retry ladder
            # back to base so the next hiccup starts cheap again
            self._backoff.reset()
            self.consecutive_failures = 0
        if rv:
            self.last_resource_version = rv

    # -- loop --------------------------------------------------------------

    def list_and_watch_once(self) -> None:
        """One LIST + one WATCH stream (until it ends). Split out for
        deterministic tests."""
        self._count(lambda m: m.watches)
        rv = self._relist()
        self.last_resource_version = rv
        self._synced.set()
        for event in self.client.watch(self.kind, rv, stop=self._stop):
            self._apply_event(event)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.last_resource_version = self._relist()
                self._synced.set()
                self._backoff.reset()  # healthy list
                self.consecutive_failures = 0
            except Exception:
                if self._stop.is_set():
                    return
                self.consecutive_failures += 1
                delay = self._backoff.next()
                logger.exception(
                    "reflector %s: list failed; backing off %.2fs", self.kind, delay
                )
                self._stop.wait(delay)
                continue
            # watch → re-watch from last RV; Gone → fall through to relist
            force_relist = False
            while not self._stop.is_set() and not force_relist:
                try:
                    self._count(lambda m: m.watches)
                    for event in self.client.watch(
                        self.kind, self.last_resource_version, stop=self._stop
                    ):
                        self._apply_event(event)
                        if self.ingest_batcher is not None and (
                            self.ingest_batcher.take_overflow(self.kind)
                        ):
                            # the bounded ingest queue shed events of OUR
                            # kind (verdict-safe pod upserts only): the
                            # cache has a gap no watch resume can close —
                            # force a relist to repair it
                            logger.warning(
                                "reflector %s: ingest overflow shed events; "
                                "forcing relist to repair the gap",
                                self.kind,
                            )
                            force_relist = True
                            break
                except GoneError:
                    self._count(lambda m: m.gone)
                    logger.info(
                        "reflector %s: resourceVersion %s gone, relisting",
                        self.kind,
                        self.last_resource_version,
                    )
                    break
                except Exception:
                    if self._stop.is_set():
                        return
                    self.consecutive_failures += 1
                    delay = self._backoff.next()
                    logger.exception(
                        "reflector %s: watch failed; backing off %.2fs",
                        self.kind, delay,
                    )
                    self._stop.wait(delay)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"reflector-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def wait_for_sync(self, timeout: float = 30.0) -> bool:
        return self._synced.wait(timeout)

    def health_state(self) -> str:
        """Health-component contract (health.py): ``down`` before the first
        successful list, ``degraded`` while retrying behind backoff, ``ok``
        on a healthy stream."""
        if not self._synced.is_set():
            return "down"
        if self.consecutive_failures >= 3:
            return "degraded"
        return "ok"


class RemoteStatusWriter:
    """Store-compatible status-writer facade the controllers call in remote
    mode (``update_throttle_status`` / ``update_cluster_throttle_status``):
    PUTs the status subresource with the last-seen REMOTE resourceVersion.
    The local cache is left alone — the watch echoes the write back, closing
    the reference's update-then-observe loop (§3.4)."""

    def __init__(self, client: ApiClient, versions: RemoteVersions):
        self.client = client
        self.versions = versions

    def _put(self, kind: str, obj) -> None:
        body = object_to_dict(obj)
        rv = self.versions.get(kind, key_of(kind, obj))
        if rv:
            body["metadata"]["resourceVersion"] = rv
        if isinstance(obj, Throttle):
            path = (
                f"/apis/{GROUP}/{VERSION}/namespaces/{obj.namespace}"
                f"/throttles/{obj.name}/status"
            )
        else:
            path = f"/apis/{GROUP}/{VERSION}/clusterthrottles/{obj.name}/status"
        doc = self.client.put(path, body)
        new_rv = str((doc.get("metadata") or {}).get("resourceVersion", ""))
        if new_rv:
            # remember the post-write RV so a second write racing the watch
            # echo doesn't 409 against our own update
            self.versions.set(kind, key_of(kind, obj), new_rv)

    def update_throttle_status(self, thr: Throttle, expected_version=None) -> Throttle:
        self._put("Throttle", thr)
        return thr

    def update_cluster_throttle_status(
        self, thr: ClusterThrottle, expected_version=None
    ) -> ClusterThrottle:
        self._put("ClusterThrottle", thr)
        return thr

    def refresh_version(self, kind: str, obj) -> None:
        """GET the live object and adopt its resourceVersion — the 409
        recovery read (client-go's RetryOnConflict re-read)."""
        if isinstance(obj, Throttle):
            path = (
                f"/apis/{GROUP}/{VERSION}/namespaces/{obj.namespace}"
                f"/throttles/{obj.name}"
            )
        else:
            path = f"/apis/{GROUP}/{VERSION}/clusterthrottles/{obj.name}"
        doc = self.client.get(path)
        rv = str((doc.get("metadata") or {}).get("resourceVersion", ""))
        if rv:
            self.versions.set(kind, key_of(kind, obj), rv)


class AsyncStatusCommitter:
    """Concurrent per-key-coalescing status commits for remote mode.

    The reference PUTs each status synchronously inside its reconcile
    (throttle_controller.go:157-173 UpdateStatus via the typed clientset,
    throttle.go:152-167); over a real wire that serializes the whole drain
    behind ~1ms HTTP round trips and caps the event pipeline at the
    single-connection PUT rate. This committer decouples reconcile from
    publication:

    - ``submit`` stores the NEWEST planned object per key (newest-wins: a
      hot throttle re-reconciled 10× between wire commits costs ONE PUT);
    - TWO LANES per shard: keys whose ``throttled`` flags or
      ``calculatedThreshold`` changed (flips — the only status bits that
      change admission verdicts) land in a priority lane drained before
      the value-only ``used``-refresh lane. At the all-keys-dirty
      equilibrium the refresh lane holds thousands of queued PUTs; without
      the lane split a flip waited behind all of them (measured p99
      2.3-2.8s at full scale), with it a flip waits at most one in-flight
      PUT plus the other queued flips;
    - N workers drain the key slots concurrently over their own keep-alive
      connections (ApiClient is per-thread-connection already);
    - PER-KEY ORDERING is structural, not locked: a key hashes to exactly
      one worker shard, lives in exactly ONE lane slot at a time (a flip
      submit promotes the key's pending slot; a later refresh updates that
      slot in place without demoting it), and a shard is drained by one
      worker — so two PUTs for one key can neither race nor reorder. Lane
      assignment is a scheduling hint only: what gets PUT is always the
      newest object, whichever lane it sat in;
    - 409 conflicts re-read the live resourceVersion and retry (bounded);
      transient transport errors retry with backoff; a REFRESH that fails
      while flips are queued re-stages itself (keeping its retry budget)
      so a conflict storm on the refresh lane cannot head-of-line block a
      flip; a key that exhausts retries is dropped with a counter bump —
      the controller's resync re-plans it (crash-only stance: the next
      reconcile regenerates any dropped publication from local truth).

    The daemon's serving truth (host aggregates + reservations) is local;
    the PUT is publication. Reconcile therefore proceeds (unreserve-on-
    observe, wakeups) as soon as the newest status is QUEUED — the local
    aggregate snapshot the status was computed from is already coherent —
    matching the batched local-store commit semantics rather than the
    reference's write-then-continue."""

    # per-shard lanes and the busy flags move under that shard's condition;
    # the deliberate lock-free reads (pending(), the retry path's
    # lane-pressure hints) are waived in the analyzer baseline
    GUARDED_BY = {
        "_hi_shards": "self._conds",
        "_lo_shards": "self._conds",
        "_busy": "self._conds",
    }

    def __init__(self, writer: "RemoteStatusWriter", workers: int = 4,
                 metrics_registry=None, max_retries: int = 4,
                 on_fenced: Optional[Callable[[], None]] = None):
        self._writer = writer
        self._n = max(1, int(workers))
        # HA fencing: a FencedError from a PUT is terminal — the callback
        # fires ONCE (wired to FencingEpoch.fence + the daemon stop event)
        # and the slot is dropped, never retried (retries cannot succeed
        # and would hammer the fenced apiserver)
        self.on_fenced = on_fenced
        self._fenced_fired = False
        # per-shard lanes: key → (kind, obj, event_ts|None, flip, attempts)
        self._hi_shards: list = [{} for _ in range(self._n)]
        self._lo_shards: list = [{} for _ in range(self._n)]
        self._conds = [threading.Condition() for _ in range(self._n)]
        self._busy = [False] * self._n
        self._threads: list = []
        self._stopped = False
        self._max_retries = max_retries
        self._commits = None
        self._lag = None
        if metrics_registry is not None:
            from ..metrics import StatusLagMetrics

            self._commits = metrics_registry.counter_vec(
                "kube_throttler_remote_status_commit_total",
                "remote status PUT outcomes by kind and result",
                ["kind", "result"],
            )
            self._lag = StatusLagMetrics(metrics_registry, "remote")

    # -- writer-compatible surface (status_writer duck type) --------------

    def update_throttle_status(self, thr: Throttle, expected_version=None) -> Throttle:
        self._submit("Throttle", thr, thr.key)
        return thr

    def update_cluster_throttle_status(
        self, thr: ClusterThrottle, expected_version=None
    ) -> ClusterThrottle:
        self._submit("ClusterThrottle", thr, thr.name)
        return thr

    def update_throttle_statuses(self, thrs) -> Dict[str, object]:
        return self.update_throttle_statuses_prioritized(thrs)

    def update_cluster_throttle_statuses(self, thrs) -> Dict[str, object]:
        return self.update_cluster_throttle_statuses_prioritized(thrs)

    def update_throttle_statuses_prioritized(
        self, thrs, flip_keys=frozenset(), event_ts=None
    ) -> Dict[str, object]:
        """Batch submit with lane routing: ``flip_keys`` (store keys) take
        the priority lane; ``event_ts`` ({store key: monotonic ts of the
        causing event}) feeds the flip/total lag histograms at PUT
        completion."""
        out: Dict[str, object] = {}
        ts = event_ts or {}
        for thr in thrs:
            key = thr.key
            self._submit(
                "Throttle", thr, key, flip=key in flip_keys, event_ts=ts.get(key)
            )
            out[key] = thr
        return out

    def update_cluster_throttle_statuses_prioritized(
        self, thrs, flip_keys=frozenset(), event_ts=None
    ) -> Dict[str, object]:
        out: Dict[str, object] = {}
        ts = event_ts or {}
        for thr in thrs:
            key = thr.name
            self._submit(
                "ClusterThrottle", thr, key, flip=key in flip_keys,
                event_ts=ts.get(key),
            )
            out[key] = thr
        return out

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        self._stopped = False
        for i in range(self._n):
            t = threading.Thread(
                target=self._run, args=(i,), name=f"status-commit-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 5.0) -> None:
        self.flush(timeout)
        self._stopped = True
        for cond in self._conds:
            with cond:
                cond.notify_all()
        for t in self._threads:
            t.join(timeout=1.0)
        self._threads = []

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every queued status has been PUT (or timeout).
        True when fully drained."""
        deadline = time.monotonic() + timeout
        for i, cond in enumerate(self._conds):
            with cond:
                while (
                    self._hi_shards[i] or self._lo_shards[i] or self._busy[i]
                ) and time.monotonic() < deadline:
                    cond.wait(0.05)
                if self._hi_shards[i] or self._lo_shards[i] or self._busy[i]:
                    return False
        return True

    def pending(self) -> int:
        return sum(len(s) for s in self._hi_shards) + sum(
            len(s) for s in self._lo_shards
        )

    # -- internals --------------------------------------------------------

    def _submit(
        self, kind: str, obj, key: str, flip: bool = False, event_ts=None
    ) -> None:
        i = hash(key) % self._n
        cond = self._conds[i]
        with cond:
            hi, lo = self._hi_shards[i], self._lo_shards[i]
            prev = hi.pop(key, None)
            was_hi = prev is not None
            if prev is None:
                prev = lo.pop(key, None)
            ts = event_ts
            if prev is not None and prev[2] is not None:
                # the lag sample spans from the OLDEST unpublished event:
                # coalescing must not shrink the measured staleness window
                ts = prev[2] if ts is None else min(ts, prev[2])
            # promote-never-demote while pending: the newest object carries
            # the flipped state until it is published, so the key keeps its
            # lane even when the latest submit is a value-only refresh
            is_flip = flip or (prev is not None and prev[3])
            (hi if (flip or was_hi) else lo)[key] = (kind, obj, ts, is_flip, 0)
            cond.notify_all()

    def _count(self, kind: str, result: str) -> None:
        if self._commits is not None:
            self._commits.inc({"kind": kind, "result": result})

    def _run(self, i: int) -> None:
        """Shard worker: one slot at a time, priority lane first. Taking a
        single slot per lock hold (instead of the whole shard) is what lets
        a flip submitted mid-backlog overtake queued refreshes: the lane
        check re-runs before every PUT. The lock is ~ns against the ~ms
        PUT it brackets."""
        cond = self._conds[i]
        hi, lo = self._hi_shards[i], self._lo_shards[i]
        while True:
            with cond:
                while not hi and not lo and not self._stopped:
                    cond.wait(0.2)
                if self._stopped and not hi and not lo:
                    return
                lane = hi if hi else lo
                key = next(iter(lane))  # dicts preserve insertion order
                slot = lane.pop(key)
                self._busy[i] = True
            try:
                self._put_with_retry(i, key, slot)
            finally:
                with cond:
                    self._busy[i] = False
                    cond.notify_all()  # wake flush()

    def _restage(self, i: int, key: str, slot) -> bool:
        """Put a failed refresh back at the tail of its lane so queued
        flips go first; keeps the slot's retry budget. False when a newer
        submit claimed the key meanwhile (newest-wins: this older object
        is obsolete — drop it silently)."""
        cond = self._conds[i]
        with cond:
            if key in self._hi_shards[i] or key in self._lo_shards[i]:
                return False
            lane = self._hi_shards[i] if slot[3] else self._lo_shards[i]
            lane[key] = slot
            return True

    def _put_with_retry(self, i: int, key: str, slot) -> None:
        kind, obj, ts, flip, attempts = slot
        for attempt in range(attempts, self._max_retries + 1):
            delay = min(0.01 * (2 ** attempt), 0.5)
            try:
                self._writer._put(kind, obj)
                self._count(kind, "ok")
                if self._lag is not None and ts is not None:
                    self._lag.observe(kind, time.monotonic() - ts, flip)
                return
            except NotFoundError:
                # the object was deleted while its status sat queued —
                # permanent; retrying would head-of-line block the shard
                self._count(kind, "not_found")
                return
            except FencedError:
                # a newer leader owns publication now: drop the slot, fire
                # the demotion hook once, and stop writing (split-brain
                # prevention — see engine/replication.py)
                self._count(kind, "fenced")
                if not self._fenced_fired:
                    self._fenced_fired = True
                    logger.warning(
                        "status PUT rejected by fencing (%s %s): a newer "
                        "leader has taken over — demoting",
                        kind, key_of(kind, obj),
                    )
                    if self.on_fenced is not None:
                        try:
                            self.on_fenced()
                        except Exception:
                            logger.exception("on_fenced callback failed")
                return
            except ConflictError:
                self._count(kind, "conflict")
                try:
                    self._writer.refresh_version(kind, obj)
                except Exception:
                    pass  # retry PUTs with the stale RV; bounded anyway
                if self._stopped:
                    break
                # a failing REFRESH must not head-of-line block queued
                # flips: hand the shard back with the retry budget intact
                # and let the worker drain the priority lane first
                if not flip and self._hi_shards[i]:
                    if self._restage(i, key, (kind, obj, ts, flip, attempt + 1)):
                        return
                # client-go's RetryOnConflict backs off too: under a
                # persistent conflict (two writers fighting) immediate
                # GET+PUT pairs multiply apiserver load exactly when it is
                # already contended
                time.sleep(delay)
            except Exception:
                self._count(kind, "retry")
                if self._stopped:
                    break
                if not flip and self._hi_shards[i]:
                    if self._restage(i, key, (kind, obj, ts, flip, attempt + 1)):
                        return
                time.sleep(delay)
        self._count(kind, "dropped")
        logger.warning(
            "dropping status publication for %s %s after %d attempts "
            "(resync will re-plan it)", kind, key_of(kind, obj), self._max_retries + 1
        )


class RemoteEventRecorder:
    """Event recorder that emits v1 Events to the apiserver — the
    reference's Warning events reach the cluster through the framework
    handle's recorder (plugin.go:190-201); in remote mode ours go through
    the same wire.

    Emission is ASYNCHRONOUS (the real kube recorder buffers too): eventf
    enqueues and returns — the scheduling hot path never blocks on an
    apiserver round trip, and a full queue drops the event (best-effort
    semantics, logged at debug). Identical events aggregate client-side
    into a count (the event-correlator behavior); the aggregation map is
    bounded with oldest-first eviction like RecordingEventRecorder. Event
    object names use a DETERMINISTIC content hash so count bumps keep
    landing on the same object across restarts and replicas, and created
    names are remembered so steady-state repeats cost one RPC (PUT), not a
    doomed POST + PUT."""

    def __init__(
        self,
        client: ApiClient,
        component: str = "kube-throttler",
        max_entries: int = 10_000,
        queue_size: int = 1024,
    ):
        import queue as _queue

        self.client = client
        self.component = component
        self._lock = threading.Lock()
        self._max_entries = max_entries
        self._counts: "OrderedDict[Tuple[str, str, str], int]" = OrderedDict()
        self._created: set = set()
        self._queue: "_queue.Queue" = _queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._drain, name="event-recorder", daemon=True
        )
        self._worker.start()

    @staticmethod
    def _object_name(pod_name: str, reason: str, note: str) -> str:
        import hashlib

        digest = hashlib.sha1(f"{reason}\x00{note}".encode()).hexdigest()[:10]
        return f"{pod_name}.{digest}"

    def eventf(
        self, pod_key: str, event_type: str, reason: str, action: str, note: str
    ) -> None:
        import queue as _queue

        key = (pod_key, reason, note)
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            count = self._counts[key]
            self._counts.move_to_end(key)
            while len(self._counts) > self._max_entries:
                self._counts.popitem(last=False)
        try:
            self._queue.put_nowait((pod_key, event_type, reason, action, note, count))
        except _queue.Full:
            logger.debug("event queue full; dropping %s %s", pod_key, reason)

    def _drain(self) -> None:
        import queue as _queue

        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.5)
            except _queue.Empty:
                continue
            try:
                self._emit(*item)
            except Exception:
                logger.debug("event emission failed", exc_info=True)

    def _emit(
        self,
        pod_key: str,
        event_type: str,
        reason: str,
        action: str,
        note: str,
        count: int,
    ) -> None:
        namespace, _, name = pod_key.partition("/")
        obj_name = self._object_name(name, reason, note)
        body = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"namespace": namespace, "name": obj_name},
            "involvedObject": {"kind": "Pod", "namespace": namespace, "name": name},
            "type": event_type,
            "reason": reason,
            "action": action,
            "message": note,
            "count": count,
            "source": {"component": self.component},
        }
        named = f"/api/v1/namespaces/{namespace}/events/{obj_name}"
        known = (namespace, obj_name) in self._created
        try:
            if known:
                self.client.put(named, body)
                return
            self.client.post(f"/api/v1/namespaces/{namespace}/events", body)
            self._created.add((namespace, obj_name))
        except ConflictError:
            # created by a previous incarnation/replica: bump in place
            self._created.add((namespace, obj_name))
            try:
                self.client.put(named, body)
            except Exception:
                logger.debug("event update failed", exc_info=True)
        except Exception:
            logger.debug("event post failed", exc_info=True)

    def flush(self, timeout: float = 5.0) -> None:
        """Best-effort wait for queued events to emit (tests/shutdown)."""
        deadline = time.monotonic() + timeout
        while not self._queue.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def close(self) -> None:
        self.flush(timeout=1.0)
        self._stop.set()


class RemoteSession:
    """Everything the daemon needs to run against a real apiserver: four
    reflectors feeding the local Store + the remote status writer. The
    plugin-side analog of plugin.go:71-130 (build config → clients →
    informers → WaitForCacheSync)."""

    KINDS = ("Namespace", "Throttle", "ClusterThrottle", "Pod")

    def __init__(
        self,
        config: RestConfig,
        store: Store,
        metrics_registry=None,
        qps: Optional[float] = 50.0,
        burst: int = 100,
        faults=None,
        ingest_batch=None,
    ):
        self.config = config
        self.store = store
        self.client = ApiClient(config, qps=qps, burst=burst, faults=faults)
        self.versions = RemoteVersions()
        metrics = (
            ReflectorMetrics(metrics_registry) if metrics_registry is not None else None
        )
        # ``ingest_batch`` ("adaptive" or a fixed int) routes every
        # reflector's watch events through ONE shared micro-batcher
        # (engine/ingest.py) — per-event store application otherwise
        self.ingest = None
        if ingest_batch is not None:
            from ..engine.ingest import MicroBatchIngest

            self.ingest = MicroBatchIngest(
                store, batch_policy=ingest_batch, faults=faults,
                metrics_registry=metrics_registry,
            )
        self.reflectors = {
            kind: Reflector(
                self.client, kind, store, versions=self.versions, metrics=metrics,
                ingest_batcher=self.ingest,
            )
            for kind in self.KINDS
        }
        self.status_writer = RemoteStatusWriter(self.client, self.versions)
        # the committer is what controllers should use as their
        # status_writer: same duck type plus batch + coalescing + N
        # concurrent PUT workers (the raw writer stays for direct callers)
        try:
            put_workers = int(os.environ.get("KT_STATUS_PUT_WORKERS", "4"))
        except ValueError:
            put_workers = 4  # malformed override must not kill session setup
        self.status_committer = AsyncStatusCommitter(
            self.status_writer,
            workers=put_workers,
            metrics_registry=metrics_registry,
        )
        self.event_recorder = RemoteEventRecorder(self.client)

    @classmethod
    def from_kubeconfig(cls, path: str, store: Store) -> "RemoteSession":
        return cls(parse_kubeconfig(path), store)

    def start(self, sync_timeout: float = 30.0) -> None:
        """Start reflectors; namespaces first so namespaced objects land in
        existing namespaces. Blocks until every cache lists once
        (WaitForCacheSync, plugin.go:114-130)."""
        for kind in self.KINDS:
            self.reflectors[kind].start()
            if not self.reflectors[kind].wait_for_sync(sync_timeout):
                raise TimeoutError(f"reflector {kind} did not sync")
        self.status_committer.start()

    def stop(self) -> None:
        self.status_committer.stop()
        self.event_recorder.close()
        for refl in self.reflectors.values():
            refl.stop()
        if self.ingest is not None:
            self.ingest.stop()

    def register_health(self, health) -> None:
        """Expose each reflector as a /readyz component (health.Health):
        the watch path being down/degraded is exactly what an operator's
        readiness probe needs to see before blaming admission."""
        for kind, refl in self.reflectors.items():
            health.register(
                f"reflector.{kind}",
                lambda r=refl: (
                    r.health_state(),
                    {
                        "resourceVersion": r.last_resource_version,
                        "consecutiveFailures": r.consecutive_failures,
                    },
                ),
            )
